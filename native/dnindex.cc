// dnindex: memory-mapped columnar index store.
//
// The native index engine replacing the reference's only native
// component, the sqlite3 binding (lib/index-sink.js, lib/index-query.js
// store aggregated points in SQLite tables and answer queries with
// SELECT cols, SUM(value) ... WHERE ... GROUP BY cols).  Here the index
// artifact is a single column-oriented file:
//
//   [header]  magic "DNCIDX1\n", u32 version, u32 pad,
//             u64 footer_off, u64 footer_len   (patched at finalize)
//   [blocks]  8-byte-aligned column blocks: i64 data, i32 dictionary
//             codes, u32 dictionary offsets, utf-8 dictionary bytes,
//             f64 values, u8 integrality flags
//   [footer]  JSON: config pairs (version 2.0.0, dn_start...), the
//             metric catalog, and per-table column descriptors with
//             block offsets
//
// The file is self-describing and atomically renamed into place by the
// caller, preserving the reference's durability contract
// (lib/index-sink.js:264-304).  Reads mmap the file; column arrays are
// exposed zero-copy to numpy, predicate masks are evaluated vectorized
// in Python with SQLite type-affinity semantics, and the GROUP BY / SUM
// hot loop runs here (dn_idx_groupby): fused-key dense accumulation
// when the key space is small, hash aggregation otherwise, with groups
// emitted in ascending key order exactly as SQLite's sorter would.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'D', 'N', 'C', 'I', 'D', 'X', '1', '\n'};
constexpr uint32_t kVersion = 1;
constexpr int64_t kHeaderSize = 32;

struct Writer {
  int fd = -1;
  int64_t off = 0;
  bool failed = false;
};

struct Reader {
  const uint8_t* base = nullptr;
  int64_t size = 0;
  int64_t footer_off = 0;
  int64_t footer_len = 0;
};

struct GroupResult {
  int32_t nkeys = 0;
  int64_t ngroups = 0;
  std::vector<int64_t> keys;  // ngroups * nkeys, row-major
  std::vector<double> sums;
  std::vector<uint8_t> isint;
};

bool write_all(int fd, const void* buf, int64_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t n = write(fd, p, static_cast<size_t>(len));
    if (n < 0) {
      if (errno == EINTR)
        continue;
      return false;
    }
    p += n;
    len -= n;
  }
  return true;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------
// writer

void* dn_idx_writer_create(const char* path) {
  int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0)
    return nullptr;
  Writer* w = new Writer();
  w->fd = fd;
  // header placeholder; footer_off/footer_len patched at finalize
  char header[kHeaderSize];
  memset(header, 0, sizeof(header));
  memcpy(header, kMagic, sizeof(kMagic));
  memcpy(header + 8, &kVersion, sizeof(kVersion));
  if (!write_all(fd, header, kHeaderSize)) {
    close(fd);
    delete w;
    return nullptr;
  }
  w->off = kHeaderSize;
  return w;
}

// Appends a block, padding to 8-byte alignment first; returns the
// block's file offset, or -1 on I/O error.
int64_t dn_idx_writer_block(void* h, const void* buf, int64_t len) {
  Writer* w = static_cast<Writer*>(h);
  if (w->failed)
    return -1;
  static const char zeros[8] = {0};
  int64_t pad = (8 - (w->off & 7)) & 7;
  if (pad && !write_all(w->fd, zeros, pad)) {
    w->failed = true;
    return -1;
  }
  w->off += pad;
  int64_t at = w->off;
  if (len > 0 && !write_all(w->fd, buf, len)) {
    w->failed = true;
    return -1;
  }
  w->off += len;
  return at;
}

// Writes the footer JSON, patches the header, and closes.  No fsync —
// the reference disables synchronous writes too (pragma synchronous =
// off, lib/index-sink.js:169-178); atomicity comes from the caller's
// tmp-file + rename.  Returns 0 on success.
int32_t dn_idx_writer_finalize(void* h, const char* footer,
                               int64_t footer_len) {
  Writer* w = static_cast<Writer*>(h);
  int64_t at = dn_idx_writer_block(h, footer, footer_len);
  int32_t rv = -1;
  if (at >= 0 && !w->failed) {
    char patch[16];
    memcpy(patch, &at, 8);
    memcpy(patch + 8, &footer_len, 8);
    if (pwrite(w->fd, patch, sizeof(patch), 16) == sizeof(patch))
      rv = 0;
  }
  if (close(w->fd) != 0)
    rv = -1;
  delete w;
  return rv;
}

void dn_idx_writer_abort(void* h) {
  Writer* w = static_cast<Writer*>(h);
  close(w->fd);
  delete w;
}

// ---------------------------------------------------------------------
// reader

void* dn_idx_open(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0)
    return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < kHeaderSize) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                    MAP_PRIVATE, fd, 0);
  close(fd);  // mmap keeps its own reference
  if (base == MAP_FAILED)
    return nullptr;
  const uint8_t* p = static_cast<const uint8_t*>(base);
  uint32_t version;
  memcpy(&version, p + 8, 4);
  Reader* r = new Reader();
  r->base = p;
  r->size = st.st_size;
  memcpy(&r->footer_off, p + 16, 8);
  memcpy(&r->footer_len, p + 24, 8);
  if (memcmp(p, kMagic, sizeof(kMagic)) != 0 || version != kVersion ||
      r->footer_off < kHeaderSize || r->footer_len < 0 ||
      r->footer_off + r->footer_len > r->size) {
    munmap(const_cast<uint8_t*>(r->base), static_cast<size_t>(r->size));
    delete r;
    return nullptr;
  }
  return r;
}

const uint8_t* dn_idx_base(void* h) {
  return static_cast<Reader*>(h)->base;
}

int64_t dn_idx_size(void* h) {
  return static_cast<Reader*>(h)->size;
}

int64_t dn_idx_footer_off(void* h) {
  return static_cast<Reader*>(h)->footer_off;
}

int64_t dn_idx_footer_len(void* h) {
  return static_cast<Reader*>(h)->footer_len;
}

void dn_idx_close(void* h) {
  Reader* r = static_cast<Reader*>(h);
  munmap(const_cast<uint8_t*>(r->base), static_cast<size_t>(r->size));
  delete r;
}

// ---------------------------------------------------------------------
// GROUP BY / SUM kernel
//
// keycols: nkeys column arrays of rank-encoded keys (the Python side
// maps dictionary codes to byte-order ranks so ascending rank ==
// SQLite BINARY-collation order; integer columns pass through).  mask
// selects the rows surviving the WHERE clause.  Sums accumulate in f64;
// a group's result is integral only if every contributing row was
// (SQLite's SUM returns REAL once any operand is REAL).

void* dn_idx_groupby(const int64_t** keycols, int32_t nkeys,
                     const double* values, const uint8_t* isint,
                     const uint8_t* mask, int64_t nrows) {
  GroupResult* g = new GroupResult();
  g->nkeys = nkeys;

  if (nkeys == 0) {
    // single group over all surviving rows (matches SELECT SUM(value)
    // with no GROUP BY only when rows exist; caller handles empty)
    double sum = 0.0;
    uint8_t allint = 1;
    int64_t seen = 0;
    for (int64_t i = 0; i < nrows; i++) {
      if (!mask[i])
        continue;
      sum += values[i];
      allint &= isint[i];
      seen++;
    }
    if (seen > 0) {
      g->ngroups = 1;
      g->sums.push_back(sum);
      g->isint.push_back(allint);
    }
    return g;
  }

  // Fused-key path: mixed-radix composite when every key range is known
  // and the product fits comfortably (dense accumulator, O(n)).
  int64_t lo[16], hi[16];
  bool fused_ok = nkeys <= 16;
  if (fused_ok) {
    bool any = false;
    for (int32_t k = 0; k < nkeys; k++) {
      lo[k] = INT64_MAX;
      hi[k] = INT64_MIN;
    }
    for (int64_t i = 0; i < nrows; i++) {
      if (!mask[i])
        continue;
      any = true;
      for (int32_t k = 0; k < nkeys; k++) {
        int64_t v = keycols[k][i];
        if (v < lo[k])
          lo[k] = v;
        if (v > hi[k])
          hi[k] = v;
      }
    }
    if (!any)
      return g;
    int64_t space = 1;
    for (int32_t k = 0; k < nkeys && fused_ok; k++) {
      int64_t range = hi[k] - lo[k] + 1;
      if (range <= 0 || space > (int64_t(1) << 42) / range)
        fused_ok = false;
      else
        space *= range;
    }
    if (fused_ok && space > (1 << 22) && space > nrows * 4)
      fused_ok = false;  // too sparse for a dense accumulator
    if (fused_ok) {
      std::vector<double> acc(static_cast<size_t>(space), 0.0);
      std::vector<uint8_t> accint(static_cast<size_t>(space), 1);
      std::vector<uint8_t> present(static_cast<size_t>(space), 0);
      for (int64_t i = 0; i < nrows; i++) {
        if (!mask[i])
          continue;
        int64_t fused = 0;
        for (int32_t k = 0; k < nkeys; k++)
          fused = fused * (hi[k] - lo[k] + 1) + (keycols[k][i] - lo[k]);
        acc[fused] += values[i];
        accint[fused] &= isint[i];
        present[fused] = 1;
      }
      // ascending fused order == ascending lexicographic key order
      for (int64_t f = 0; f < space; f++) {
        if (!present[f])
          continue;
        int64_t rem = f;
        int64_t key[16];
        for (int32_t k = nkeys - 1; k >= 0; k--) {
          int64_t range = hi[k] - lo[k] + 1;
          key[k] = lo[k] + rem % range;
          rem /= range;
        }
        for (int32_t k = 0; k < nkeys; k++)
          g->keys.push_back(key[k]);
        g->sums.push_back(acc[f]);
        g->isint.push_back(accint[f]);
        g->ngroups++;
      }
      return g;
    }
  }

  // Hash path: 64-bit mixed key -> group slot; final sort by key tuple.
  struct Slot {
    double sum = 0.0;
    uint8_t allint = 1;
    int64_t first = 0;  // index into tuples
  };
  std::unordered_map<uint64_t, std::vector<int64_t>> buckets;
  std::vector<int64_t> tuples;  // flattened candidate key tuples
  std::vector<Slot> slots;
  buckets.reserve(1024);
  for (int64_t i = 0; i < nrows; i++) {
    if (!mask[i])
      continue;
    uint64_t hv = 1469598103934665603ull;  // FNV-1a over the tuple
    for (int32_t k = 0; k < nkeys; k++) {
      uint64_t v = static_cast<uint64_t>(keycols[k][i]);
      for (int b = 0; b < 8; b++) {
        hv ^= (v >> (b * 8)) & 0xff;
        hv *= 1099511628211ull;
      }
    }
    auto& cands = buckets[hv];
    int64_t slot = -1;
    for (int64_t s : cands) {
      bool eq = true;
      for (int32_t k = 0; k < nkeys; k++) {
        if (tuples[slots[s].first + k] != keycols[k][i]) {
          eq = false;
          break;
        }
      }
      if (eq) {
        slot = s;
        break;
      }
    }
    if (slot < 0) {
      slot = static_cast<int64_t>(slots.size());
      Slot ns;
      ns.first = static_cast<int64_t>(tuples.size());
      for (int32_t k = 0; k < nkeys; k++)
        tuples.push_back(keycols[k][i]);
      slots.push_back(ns);
      cands.push_back(slot);
    }
    slots[slot].sum += values[i];
    slots[slot].allint &= isint[i];
  }

  std::vector<int64_t> order(slots.size());
  for (size_t s = 0; s < slots.size(); s++)
    order[s] = static_cast<int64_t>(s);
  std::sort(order.begin(), order.end(),
            [&](int64_t a, int64_t b) {
              const int64_t* ta = &tuples[slots[a].first];
              const int64_t* tb = &tuples[slots[b].first];
              for (int32_t k = 0; k < nkeys; k++) {
                if (ta[k] != tb[k])
                  return ta[k] < tb[k];
              }
              return false;
            });
  g->ngroups = static_cast<int64_t>(order.size());
  g->keys.reserve(order.size() * nkeys);
  for (int64_t s : order) {
    const int64_t* t = &tuples[slots[s].first];
    for (int32_t k = 0; k < nkeys; k++)
      g->keys.push_back(t[k]);
    g->sums.push_back(slots[s].sum);
    g->isint.push_back(slots[s].allint);
  }
  return g;
}

int64_t dn_gb_ngroups(void* gh) {
  return static_cast<GroupResult*>(gh)->ngroups;
}

// Copies group keys for key column k (ngroups values).
void dn_gb_keys(void* gh, int32_t k, int64_t* out) {
  GroupResult* g = static_cast<GroupResult*>(gh);
  for (int64_t i = 0; i < g->ngroups; i++)
    out[i] = g->keys[i * g->nkeys + k];
}

void dn_gb_sums(void* gh, double* out) {
  GroupResult* g = static_cast<GroupResult*>(gh);
  memcpy(out, g->sums.data(), static_cast<size_t>(g->ngroups) * 8);
}

void dn_gb_isint(void* gh, uint8_t* out) {
  GroupResult* g = static_cast<GroupResult*>(gh);
  memcpy(out, g->isint.data(), static_cast<size_t>(g->ngroups));
}

void dn_gb_free(void* gh) {
  delete static_cast<GroupResult*>(gh);
}

}  // extern "C"
