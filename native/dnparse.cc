// dnparse: newline-JSON -> projected columnar batches.
//
// The native half of the ingest path.  The reference's hot loop parsed
// every record into a V8 object and walked it per stage
// (lib/format-json.js, vstream-json-parser); here a single streaming
// pass over the byte buffer extracts only the projected field paths and
// emits columnar arrays (value tags, numbers, interned string codes,
// pre-parsed ISO-8601 dates) that the Python/JAX engine consumes
// directly.
//
// Semantics preserved exactly:
//  * jsprim-pluck projection: a literal key "req.method" beats the
//    nested req -> method path (direct-key-first), and within the same
//    priority the *last* JSON occurrence wins (JSON.parse duplicate-key
//    rule),
//  * invalid lines are counted and skipped (vstream "invalid json"),
//  * numbers are IEEE doubles (JS semantics),
//  * ISO-8601 date parsing with ES5 rules (missing offset == UTC),
//    numbers pass through as epoch seconds (lib/stream-synthetic.js).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------
// value tags (must match dragnet_tpu/native.py)
enum Tag : uint8_t {
  TAG_MISSING = 0,
  TAG_NULL = 1,
  TAG_FALSE = 2,
  TAG_TRUE = 3,
  TAG_NUMBER = 4,   // non-integral or large
  TAG_INT = 5,      // integral, |v| <= 2^53
  TAG_STRING = 6,
  TAG_OBJECT = 7,   // object (kept opaque: String(v) == "[object Object]")
  TAG_ARRAY = 8,    // array: raw JSON text interned for JS coercion
};

enum DateErr : uint8_t {
  DATE_OK = 0,
  DATE_UNDEF = 1,
  DATE_BAD = 2,
};

// Open-addressing interning dictionary keyed by byte span: the hot
// path (per projected string per record) never constructs a temporary
// std::string or runs std::hash — FNV over the raw span, linear probe,
// memcmp against the stored value.
struct StringDict {
  std::vector<std::string> values;
  std::vector<int32_t> table = std::vector<int32_t>(64, -1);
  size_t mask = 63;

  static uint64_t hash_span(const char* s, size_t len) {
    uint64_t h = 1469598103934665603ull;
    for (size_t i = 0; i < len; i++) {
      h ^= static_cast<unsigned char>(s[i]);
      h *= 1099511628211ull;
    }
    return h;
  }

  void grow() {
    size_t nsize = table.size() * 2;
    std::vector<int32_t> ntable(nsize, -1);
    size_t nmask = nsize - 1;
    for (int32_t c = 0; c < static_cast<int32_t>(values.size()); c++) {
      size_t i = hash_span(values[c].data(), values[c].size()) & nmask;
      while (ntable[i] != -1) i = (i + 1) & nmask;
      ntable[i] = c;
    }
    table.swap(ntable);
    mask = nmask;
  }

  int32_t code_span(const char* s, size_t len) {
    size_t i = hash_span(s, len) & mask;
    while (table[i] != -1) {
      const std::string& v = values[table[i]];
      if (v.size() == len && memcmp(v.data(), s, len) == 0)
        return table[i];
      i = (i + 1) & mask;
    }
    int32_t c = static_cast<int32_t>(values.size());
    values.emplace_back(s, len);
    table[i] = c;
    if (values.size() * 4 > table.size() * 3) grow();
    return c;
  }

  int32_t code(const std::string& s) {
    return code_span(s.data(), s.size());
  }
};

struct FieldOut {
  std::vector<uint8_t> tags;
  std::vector<double> nums;
  std::vector<int32_t> strcodes;
  std::vector<double> datesecs;   // only filled when date_hint
  std::vector<uint8_t> dateerr;   // only filled when date_hint
  StringDict dict;
  bool date_hint = false;
  bool want_dict = true;
  // scratch per record: priority of the value currently held
  // (0 = none, 1 = nested match, 2 = direct full-key match)
  uint8_t cur_prio = 0;
};

// projection trie node: at each object depth, a key either terminates a
// field (direct or final segment) or descends.  Children are a small
// linear-scan vector: record keys are matched by raw byte span with no
// hashing or allocation (projected key sets are tiny).
struct TrieNode {
  std::vector<std::pair<std::string, TrieNode*>> children;
  // field index terminated by this key at this level, with priority
  int32_t field = -1;
  uint8_t prio = 0;
  // every (field, priority) reachable at-or-below this node: used to
  // honor JSON.parse last-occurrence-wins when a later duplicate key
  // replaces a whole subtree (earlier captures must be cleared)
  std::vector<std::pair<int32_t, uint8_t>> subtree_fields;
  // first-byte dispatch: most record keys are not projected, and a
  // single table load rejects them without touching the child list
  // (-1 = no child starts with this byte, -2 = several do: scan,
  // >= 0 = the only candidate child).  Built by fill_subtree_fields.
  int16_t first_map[256];

  TrieNode() { memset(first_map, -1, sizeof(first_map)); }

  TrieNode* find(const char* k, size_t len) const {
    if (len == 0) return find_scan(k, len);  // empty projected key
    int16_t fm = first_map[static_cast<unsigned char>(k[0])];
    if (fm == -1) return nullptr;
    if (fm >= 0) {
      const auto& kv = children[fm];
      if (kv.first.size() == len &&
          memcmp(kv.first.data(), k, len) == 0) {
        return kv.second;
      }
      return nullptr;
    }
    return find_scan(k, len);
  }
  TrieNode* find_scan(const char* k, size_t len) const {
    for (const auto& kv : children) {
      if (kv.first.size() == len &&
          memcmp(kv.first.data(), k, len) == 0) {
        return kv.second;
      }
    }
    return nullptr;
  }
  TrieNode* find_or_add(const std::string& k) {
    TrieNode* n = find_scan(k.data(), k.size());
    if (n != nullptr) return n;
    n = new TrieNode();
    children.emplace_back(k, n);
    return n;
  }
  void build_first_map() {
    memset(first_map, -1, sizeof(first_map));
    for (size_t i = 0; i < children.size(); i++) {
      if (children[i].first.empty()) continue;
      unsigned char b =
          static_cast<unsigned char>(children[i].first[0]);
      first_map[b] = first_map[b] == -1 ? static_cast<int16_t>(i) : -2;
    }
  }
  ~TrieNode() {
    for (auto& kv : children) delete kv.second;
  }
};

struct Parser {
  std::vector<std::string> paths;
  std::vector<FieldOut> fields;
  TrieNode root;
  // shared read-only projection trie (workers point at the main
  // parser's root; the owner points at its own)
  const TrieNode* trie = nullptr;
  uint64_t nlines = 0;
  uint64_t nbad = 0;
  uint64_t nrecords = 0;
  uint64_t batch_records = 0;
  std::string err;
  // worker pool for multithreaded parse (owner only)
  std::vector<Parser*> workers;
  // persistent worker-code -> owner-code dictionary remaps,
  // [worker][field][worker_code]
  std::vector<std::vector<std::vector<int32_t>>> remaps;

  ~Parser() {
    for (Parser* w : workers) delete w;
  }
};

// ---------------------------------------------------------------------
// date parsing: ISO-8601 subset (ES5 Date.parse), returns ms since
// epoch; false on failure.
bool days_from_civil(int64_t y, unsigned m, unsigned d, int64_t* out) {
  // Howard Hinnant's algorithm
  y -= m <= 2;
  const int64_t era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = static_cast<unsigned>(y - era * 400);
  const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  *out = era * 146097 + static_cast<int64_t>(doe) - 719468;
  return true;
}

inline bool two_digits(const char* p, int* out) {
  if (p[0] < '0' || p[0] > '9' || p[1] < '0' || p[1] > '9') return false;
  *out = (p[0] - '0') * 10 + (p[1] - '0');
  return true;
}

bool parse_iso_date(const char* s, size_t len, int64_t* ms_out) {
  // YYYY[-MM[-DD]][T HH:MM[:SS[.fff...]][Z|+-HH:MM|+-HHMM]]
  // The Python reference (jsvalues.date_parse) strips surrounding
  // whitespace before matching; mirror it so both parse lanes agree.
  while (len > 0 && (*s == ' ' || *s == '\t' || *s == '\r' ||
                     *s == '\n' || *s == '\f' || *s == '\v')) {
    s++;
    len--;
  }
  while (len > 0 && (s[len - 1] == ' ' || s[len - 1] == '\t' ||
                     s[len - 1] == '\r' || s[len - 1] == '\n' ||
                     s[len - 1] == '\f' || s[len - 1] == '\v')) {
    len--;
  }
  if (len < 4) return false;
  const char* p = s;
  const char* end = s + len;
  int year = 0;
  for (int i = 0; i < 4; i++) {
    if (p[i] < '0' || p[i] > '9') return false;
    year = year * 10 + (p[i] - '0');
  }
  p += 4;
  int month = 1, day = 1, hh = 0, mm = 0, ss = 0, msec = 0;
  if (p < end && *p == '-') {
    if (end - p < 3 || !two_digits(p + 1, &month)) return false;
    p += 3;
    if (p < end && *p == '-') {
      if (end - p < 3 || !two_digits(p + 1, &day)) return false;
      p += 3;
    }
  }
  long tz_offset_min = 0;
  if (p < end) {
    if (*p != 'T' && *p != ' ') return false;
    p++;
    if (end - p < 5 || !two_digits(p, &hh)) return false;
    if (p[2] != ':') return false;
    if (!two_digits(p + 3, &mm)) return false;
    p += 5;
    if (p < end && *p == ':') {
      if (end - p < 3 || !two_digits(p + 1, &ss)) return false;
      p += 3;
      if (p < end && *p == '.') {
        p++;
        int ndig = 0;
        int frac = 0;
        while (p < end && *p >= '0' && *p <= '9') {
          if (ndig < 3) frac = frac * 10 + (*p - '0');
          ndig++;
          p++;
        }
        if (ndig == 0) return false;
        while (ndig < 3) { frac *= 10; ndig++; }
        msec = frac;
      }
    }
    if (p < end) {
      if (*p == 'Z') {
        p++;
      } else if (*p == '+' || *p == '-') {
        // offsets require minutes: [+-]HH:MM or [+-]HHMM
        // (matching the reference path's ISO regex)
        int sign = (*p == '+') ? 1 : -1;
        p++;
        int tzh = 0, tzm = 0;
        if (end - p < 2 || !two_digits(p, &tzh)) return false;
        p += 2;
        if (p < end && *p == ':') p++;
        if (end - p < 2 || !two_digits(p, &tzm)) return false;
        p += 2;
        tz_offset_min = sign * (tzh * 60 + tzm);
      } else {
        return false;
      }
    }
  }
  if (p != end) return false;
  // the Python reference path builds a datetime, which rejects year 0
  if (year < 1) return false;
  if (month < 1 || month > 12) return false;
  static const int kDays[] = {31, 28, 31, 30, 31, 30,
                              31, 31, 30, 31, 30, 31};
  int maxday = kDays[month - 1];
  if (month == 2 &&
      (year % 4 == 0 && (year % 100 != 0 || year % 400 == 0))) {
    maxday = 29;
  }
  if (day < 1 || day > maxday) return false;
  // the Python reference path builds a datetime, which rejects hour 24
  if (hh > 23 || mm > 59 || ss > 59) return false;
  int64_t days;
  days_from_civil(year, month, day, &days);
  int64_t ms = ((days * 24 + hh) * 60 + mm) * 60 + ss;
  ms = ms * 1000 + msec;
  ms -= tz_offset_min * 60000;
  *ms_out = ms;
  return true;
}

// ---------------------------------------------------------------------
// JSON scanning

// Scan: advance to the first byte that is '"', '\\', or a raw
// control char (< 0x20).  These are the only bytes a JSON string
// scanner must act on; everything else is literal content.  SWAR
// (8 bytes/step) baseline with an AVX2 (32 bytes/step) variant
// dispatched at runtime — the library is built on the host it runs
// on, but the binary stays loadable on machines without AVX2.
static inline const char* scan_plain_swar(const char* p,
                                          const char* end) {
  constexpr uint64_t kOnes = 0x0101010101010101ull;
  constexpr uint64_t kHigh = 0x8080808080808080ull;
  while (end - p >= 8) {
    uint64_t w;
    memcpy(&w, p, 8);
    uint64_t q = w ^ (kOnes * 0x22);          // '"'
    uint64_t b = w ^ (kOnes * 0x5C);          // '\\'
    uint64_t c = w & (kOnes * 0xE0);          // 0 iff byte < 0x20
    uint64_t hit = ((q - kOnes) & ~q & kHigh) |
                   ((b - kOnes) & ~b & kHigh) |
                   ((c - kOnes) & ~c & kHigh);
    if (hit)
      return p + (__builtin_ctzll(hit) >> 3);
    p += 8;
  }
  while (p < end) {
    unsigned char ch = static_cast<unsigned char>(*p);
    if (ch == '"' || ch == '\\' || ch < 0x20)
      return p;
    p++;
  }
  return end;
}

#if defined(__x86_64__)
#include <immintrin.h>
__attribute__((target("avx2")))
static const char* scan_plain_avx2(const char* p, const char* end) {
  const __m256i vq = _mm256_set1_epi8('"');
  const __m256i vb = _mm256_set1_epi8('\\');
  const __m256i vlim = _mm256_set1_epi8(0x1F);
  while (end - p >= 32) {
    __m256i w = _mm256_loadu_si256(
        reinterpret_cast<const __m256i*>(p));
    __m256i hq = _mm256_cmpeq_epi8(w, vq);
    __m256i hb = _mm256_cmpeq_epi8(w, vb);
    // unsigned (byte < 0x20)  <=>  min(byte, 0x1F) == byte
    __m256i hc = _mm256_cmpeq_epi8(_mm256_min_epu8(w, vlim), w);
    unsigned mask = static_cast<unsigned>(_mm256_movemask_epi8(
        _mm256_or_si256(hq, _mm256_or_si256(hb, hc))));
    if (mask)
      return p + __builtin_ctz(mask);
    p += 32;
  }
  return scan_plain_swar(p, end);
}

static const bool kHaveAvx2 =
    (__builtin_cpu_init(), __builtin_cpu_supports("avx2"));

static inline const char* scan_plain(const char* p, const char* end) {
  if (kHaveAvx2)
    return scan_plain_avx2(p, end);
  return scan_plain_swar(p, end);
}
#else
static inline const char* scan_plain(const char* p, const char* end) {
  return scan_plain_swar(p, end);
}
#endif

struct Scanner {
  const char* p;
  const char* end;

  bool at_end() const { return p >= end; }
  char peek() const { return *p; }

  void skip_ws() {
    while (p < end &&
           (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n')) p++;
  }

  bool skip_string() {
    // assumes *p == '"'; validates JSON string syntax (escape set,
    // no raw control chars) so the skip path rejects exactly what
    // JSON.parse / json.loads reject
    p++;
    while (true) {
      p = scan_plain(p, end);
      if (p >= end) return false;
      unsigned char c = static_cast<unsigned char>(*p);
      if (c == '"') {
        p++;
        return true;
      }
      if (c < 0x20) return false;
      // backslash escape
      p++;
      if (p >= end) return false;
      char e = *p;
      if (e == 'u') {
        if (end - p < 5) return false;
        for (int i = 1; i <= 4; i++) {
          char h = p[i];
          if (!((h >= '0' && h <= '9') || (h >= 'a' && h <= 'f') ||
                (h >= 'A' && h <= 'F'))) return false;
        }
        p += 5;
      } else if (e == '"' || e == '\\' || e == '/' || e == 'b' ||
                 e == 'f' || e == 'n' || e == 'r' || e == 't') {
        p++;
      } else {
        return false;
      }
    }
  }

  // Scan a JSON string assuming *p == '"'.  Fast path: no escapes and
  // no raw control chars -> returns the raw byte span (still valid
  // UTF-8 text, since JSON strings without escapes are literal).  If an
  // escape is present, falls back to full decode into *decoded and sets
  // *span_len = SIZE_MAX.  Returns false on invalid string syntax.
  bool read_string_span(const char** span, size_t* span_len,
                        std::string* decoded) {
    const char* q = scan_plain(p + 1, end);
    if (q >= end) return false;
    if (*q == '"') {
      *span = p + 1;
      *span_len = static_cast<size_t>(q - (p + 1));
      p = q + 1;
      return true;
    }
    if (static_cast<unsigned char>(*q) < 0x20) return false;
    *span_len = static_cast<size_t>(-1);
    return read_string(decoded);
  }

  // decode a JSON string into out (UTF-8); assumes *p == '"'
  bool read_string(std::string* out) {
    p++;
    out->clear();
    while (p < end) {
      unsigned char c = static_cast<unsigned char>(*p);
      if (c == '"') {
        p++;
        return true;
      }
      if (c == '\\') {
        p++;
        if (p >= end) return false;
        char e = *p++;
        switch (e) {
          case '"': out->push_back('"'); break;
          case '\\': out->push_back('\\'); break;
          case '/': out->push_back('/'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'n': out->push_back('\n'); break;
          case 'r': out->push_back('\r'); break;
          case 't': out->push_back('\t'); break;
          case 'u': {
            if (end - p < 4) return false;
            unsigned cp = 0;
            for (int i = 0; i < 4; i++) {
              char h = p[i];
              cp <<= 4;
              if (h >= '0' && h <= '9') cp |= h - '0';
              else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
              else return false;
            }
            p += 4;
            if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 6 &&
                p[0] == '\\' && p[1] == 'u') {
              unsigned lo = 0;
              bool ok = true;
              for (int i = 0; i < 4; i++) {
                char h = p[2 + i];
                lo <<= 4;
                if (h >= '0' && h <= '9') lo |= h - '0';
                else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
                else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
                else { ok = false; break; }
              }
              if (ok && lo >= 0xDC00 && lo <= 0xDFFF) {
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                p += 6;
              }
            }
            // encode UTF-8
            if (cp < 0x80) {
              out->push_back(static_cast<char>(cp));
            } else if (cp < 0x800) {
              out->push_back(static_cast<char>(0xC0 | (cp >> 6)));
              out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
            } else if (cp < 0x10000) {
              out->push_back(static_cast<char>(0xE0 | (cp >> 12)));
              out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
            } else {
              out->push_back(static_cast<char>(0xF0 | (cp >> 18)));
              out->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
            }
            break;
          }
          default:
            return false;
        }
      } else if (c < 0x20) {
        return false;
      } else {
        out->push_back(static_cast<char>(c));
        p++;
      }
    }
    return false;
  }

  // skip any JSON value, validating full JSON grammar so the native
  // path rejects exactly the lines the Python fallback rejects
  bool skip_value() {
    skip_ws();
    if (at_end()) return false;
    char c = *p;
    if (c == '"') return skip_string();
    if (c == '{') return skip_object_strict();
    if (c == '[') return skip_array_strict();
    if (c == 't') return literal("true");
    if (c == 'f') return literal("false");
    if (c == 'n') return literal("null");
    return skip_number(nullptr, nullptr);
  }

  bool skip_object_strict() {
    p++;  // '{'
    skip_ws();
    if (!at_end() && *p == '}') { p++; return true; }
    while (true) {
      skip_ws();
      if (at_end() || *p != '"') return false;
      if (!skip_string()) return false;
      skip_ws();
      if (at_end() || *p != ':') return false;
      p++;
      if (!skip_value()) return false;
      skip_ws();
      if (at_end()) return false;
      if (*p == ',') { p++; continue; }
      if (*p == '}') { p++; return true; }
      return false;
    }
  }

  bool skip_array_strict() {
    p++;  // '['
    skip_ws();
    if (!at_end() && *p == ']') { p++; return true; }
    while (true) {
      if (!skip_value()) return false;
      skip_ws();
      if (at_end()) return false;
      if (*p == ',') { p++; continue; }
      if (*p == ']') { p++; return true; }
      return false;
    }
  }

  bool literal(const char* lit) {
    size_t len = strlen(lit);
    if (static_cast<size_t>(end - p) < len ||
        memcmp(p, lit, len) != 0) return false;
    p += len;
    return true;
  }

  bool skip_number(double* out, bool* is_int) {
    // strict JSON number grammar: -?(0|[1-9][0-9]*)(\.[0-9]+)?
    // ([eE][+-]?[0-9]+)?  (no leading zeros, no bare "1.")
    const char* start = p;
    bool neg = false;
    if (p < end && (*p == '-')) { neg = true; p++; }
    if (p >= end || *p < '0' || *p > '9') return false;
    uint64_t mant = 0;
    int ndigits = 0;
    if (*p == '0') {
      p++;
      ndigits = 1;
    } else {
      while (p < end && *p >= '0' && *p <= '9') {
        if (ndigits < 19) mant = mant * 10 + (*p - '0');
        ndigits++;
        p++;
      }
    }
    bool integral = true;
    if (p < end && *p == '.') {
      integral = false;
      p++;
      if (p >= end || *p < '0' || *p > '9') return false;
      while (p < end && *p >= '0' && *p <= '9') p++;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
      integral = false;
      p++;
      if (p < end && (*p == '+' || *p == '-')) p++;
      if (p >= end || *p < '0' || *p > '9') return false;
      while (p < end && *p >= '0' && *p <= '9') p++;
    }
    if (out != nullptr) {
      if (integral && ndigits <= 18) {
        // <= 18 digits fits uint64 exactly; uint64 -> double rounds to
        // nearest, matching strtod's correctly-rounded result
        double v = static_cast<double>(mant);
        *out = neg ? -v : v;
        *is_int = std::fabs(*out) <= 9007199254740992.0;
      } else {
        char tmp[512];
        size_t n = static_cast<size_t>(p - start);
        if (n >= sizeof(tmp)) {
          std::string big(start, n);
          *out = strtod(big.c_str(), nullptr);
        } else {
          memcpy(tmp, start, n);
          tmp[n] = '\0';
          *out = strtod(tmp, nullptr);
        }
        double v = *out;
        *is_int = integral && std::fabs(v) <= 9007199254740992.0 &&
                  v == std::floor(v);
      }
    }
    return true;
  }
};

// parse one record line, filling matched fields
bool parse_object(Parser* pr, Scanner* sc, const TrieNode* node,
                  int depth) {
  sc->skip_ws();
  if (sc->at_end() || sc->peek() != '{') return false;
  sc->p++;
  sc->skip_ws();
  if (!sc->at_end() && sc->peek() == '}') { sc->p++; return true; }

  std::string key;
  std::string sval;
  while (true) {
    sc->skip_ws();
    if (sc->at_end() || sc->peek() != '"') return false;
    const char* kspan;
    size_t klen;
    if (!sc->read_string_span(&kspan, &klen, &key)) return false;
    if (klen == static_cast<size_t>(-1)) {
      kspan = key.data();
      klen = key.size();
    }
    sc->skip_ws();
    if (sc->at_end() || sc->peek() != ':') return false;
    sc->p++;
    sc->skip_ws();

    const TrieNode* child =
        (node != nullptr) ? node->find(kspan, klen) : nullptr;

    if (child != nullptr) {
      // JSON.parse keeps the LAST occurrence of a duplicate key: any
      // field previously captured through this key's subtree (at the
      // priority this subtree grants) must be cleared before the new
      // value is considered — even if the new value is a non-object
      // that provides nothing.
      for (const auto& fp : child->subtree_fields) {
        FieldOut& f = pr->fields[fp.first];
        if (f.cur_prio != 0 && f.cur_prio <= fp.second) {
          size_t i = f.tags.size() - 1;
          f.cur_prio = 0;
          f.tags[i] = TAG_MISSING;
          f.nums[i] = 0.0;
          f.strcodes[i] = -1;
          if (f.date_hint) {
            f.datesecs[i] = 0.0;
            f.dateerr[i] = DATE_UNDEF;
          }
        }
      }
    }

    if (child != nullptr && child->field >= 0) {
      FieldOut& f = pr->fields[child->field];
      // direct-key-first: a higher-priority match overwrites a lower
      // one; same priority -> last occurrence wins (JSON.parse rule)
      if (child->prio >= f.cur_prio) {
        f.cur_prio = child->prio;
        size_t i = f.tags.size() - 1;  // current record slot
        char c = sc->at_end() ? '\0' : sc->peek();
        if (c == '"') {
          const char* vspan;
          size_t vlen;
          if (!sc->read_string_span(&vspan, &vlen, &sval)) return false;
          if (vlen == static_cast<size_t>(-1)) {
            vspan = sval.data();
            vlen = sval.size();
          }
          f.tags[i] = TAG_STRING;
          f.strcodes[i] = f.want_dict
              ? f.dict.code_span(vspan, vlen) : -1;
          if (f.date_hint) {
            int64_t ms;
            if (parse_iso_date(vspan, vlen, &ms)) {
              f.dateerr[i] = DATE_OK;
              // JS Math.floor(ms/1000)
              double d = static_cast<double>(ms);
              f.datesecs[i] = std::floor(d / 1000.0);
            } else {
              f.dateerr[i] = DATE_BAD;
            }
          }
        } else if (c == '[') {
          // arrays participate in JS coercion (String/Number via
          // join), so intern the raw JSON text for host-side handling
          const char* vstart = sc->p;
          if (!sc->skip_value()) return false;
          f.tags[i] = TAG_ARRAY;
          f.strcodes[i] = f.want_dict
              ? f.dict.code_span(vstart,
                                 static_cast<size_t>(sc->p - vstart))
              : -1;
          if (f.date_hint) f.dateerr[i] = DATE_BAD;
        } else if (c == '{') {
          if (child->children.empty()) {
            if (!sc->skip_value()) return false;
            f.tags[i] = TAG_OBJECT;
            if (f.date_hint) f.dateerr[i] = DATE_BAD;
          } else {
            // rare: key both terminates one field and prefixes others
            if (!parse_object(pr, sc, child, depth + 1)) return false;
            f.tags[i] = TAG_OBJECT;
            if (f.date_hint) f.dateerr[i] = DATE_BAD;
          }
        } else if (c == 't' || c == 'f') {
          bool istrue = (c == 't');
          if (!sc->literal(istrue ? "true" : "false")) return false;
          f.tags[i] = istrue ? TAG_TRUE : TAG_FALSE;
          if (f.date_hint) f.dateerr[i] = DATE_BAD;
        } else if (c == 'n') {
          if (!sc->literal("null")) return false;
          f.tags[i] = TAG_NULL;
          if (f.date_hint) f.dateerr[i] = DATE_BAD;
        } else {
          double num;
          bool is_int;
          if (!sc->skip_number(&num, &is_int)) return false;
          f.tags[i] = is_int ? TAG_INT : TAG_NUMBER;
          f.nums[i] = num;
          if (f.date_hint) {
            // numbers pass through as already-parsed epoch seconds
            f.dateerr[i] = DATE_OK;
            f.datesecs[i] = num;
          }
        }
        goto next_member;
      }
    }

    if (child != nullptr && !child->children.empty() &&
        !sc->at_end() && sc->peek() == '{') {
      if (!parse_object(pr, sc, child, depth + 1)) return false;
    } else {
      if (!sc->skip_value()) return false;
    }

  next_member:
    sc->skip_ws();
    if (sc->at_end()) return false;
    if (sc->peek() == ',') {
      sc->p++;
      continue;
    }
    if (sc->peek() == '}') {
      sc->p++;
      return true;
    }
    return false;
  }
}

void fill_subtree_fields(TrieNode* node);

void build_trie(Parser* pr) {
  // jsprim-pluck lookup order: at every object level the literal
  // remaining path is checked before splitting on the first dot, so a
  // match's priority decreases with the number of splits taken
  // (255 = fully direct).  Higher priority overwrites lower; equal
  // priority keeps the last JSON occurrence (JSON.parse rule).
  for (size_t fi = 0; fi < pr->paths.size(); fi++) {
    const std::string& path = pr->paths[fi];
    struct Item { TrieNode* node; std::string rest; uint8_t splits; };
    std::vector<Item> frontier;
    frontier.push_back({&pr->root, path, 0});
    while (!frontier.empty()) {
      Item item = frontier.back();
      frontier.pop_back();
      // the full remaining path is a direct key at this level
      TrieNode* leaf = item.node->find_or_add(item.rest);
      uint8_t prio = static_cast<uint8_t>(255 - item.splits);
      if (leaf->field < 0 || prio > leaf->prio) {
        leaf->field = static_cast<int32_t>(fi);
        leaf->prio = prio;
      }
      size_t dot = item.rest.find('.');
      if (dot == std::string::npos) continue;
      std::string head = item.rest.substr(0, dot);
      std::string tail = item.rest.substr(dot + 1);
      TrieNode* sub = item.node->find_or_add(head);
      frontier.push_back({sub, tail,
                          static_cast<uint8_t>(item.splits + 1)});
    }
  }
  fill_subtree_fields(&pr->root);
}

void fill_subtree_fields(TrieNode* node) {
  node->build_first_map();
  if (node->field >= 0) {
    node->subtree_fields.emplace_back(node->field, node->prio);
  }
  for (auto& kv : node->children) {
    fill_subtree_fields(kv.second);
    for (const auto& fp : kv.second->subtree_fields) {
      node->subtree_fields.push_back(fp);
    }
  }
}

}  // namespace

extern "C" {

void* dn_parser_create(const char** paths, const uint8_t* date_hints,
                       int32_t nfields) {
  Parser* pr = new Parser();
  pr->fields.resize(nfields);
  for (int32_t i = 0; i < nfields; i++) {
    pr->paths.emplace_back(paths[i]);
    pr->fields[i].date_hint = date_hints[i] != 0;
  }
  build_trie(pr);
  pr->trie = &pr->root;
  return pr;
}

// Variant with per-field dictionary control: want_dict[i] == 0 means
// the engine never reads this field's string dictionary (date-only
// sources, consumed via the pre-parsed date columns) — string/array
// values then skip interning entirely (strcode -1), which for
// timestamp-like fields saves a hash + heap string per record.
void* dn_parser_create2(const char** paths, const uint8_t* date_hints,
                        const uint8_t* want_dict, int32_t nfields) {
  Parser* pr = static_cast<Parser*>(
      dn_parser_create(paths, date_hints, nfields));
  for (int32_t i = 0; i < nfields; i++)
    pr->fields[i].want_dict = want_dict[i] != 0;
  return pr;
}

void dn_parser_destroy(void* h) {
  delete static_cast<Parser*>(h);
}

// Parse a buffer of newline-separated JSON.  Appends one slot per valid
// record to every field's output arrays.  Returns the number of records
// appended in this call.
int64_t dn_parser_parse(void* h, const char* buf, int64_t len) {
  Parser* pr = static_cast<Parser*>(h);
  const char* p = buf;
  const char* end = buf + len;
  int64_t appended = 0;

  while (p < end) {
    const char* nl = static_cast<const char*>(
        memchr(p, '\n', end - p));
    const char* line_end = (nl != nullptr) ? nl : end;
    pr->nlines++;

    // provision a slot in every field
    for (auto& f : pr->fields) {
      f.tags.push_back(TAG_MISSING);
      f.nums.push_back(0.0);
      f.strcodes.push_back(-1);
      if (f.date_hint) {
        f.datesecs.push_back(0.0);
        f.dateerr.push_back(DATE_UNDEF);
      }
      f.cur_prio = 0;
    }

    Scanner sc{p, line_end};
    sc.skip_ws();
    bool ok;
    if (!sc.at_end() && sc.peek() == '{') {
      ok = parse_object(pr, &sc, pr->trie, 0);
    } else {
      // any valid JSON value is a record (JSON.parse-per-line
      // semantics); projected fields simply stay missing
      ok = !sc.at_end() && sc.skip_value();
    }
    if (ok) {
      sc.skip_ws();
      ok = sc.at_end();
    }
    if (!ok) {
      // roll back the slot
      for (auto& f : pr->fields) {
        f.tags.pop_back();
        f.nums.pop_back();
        f.strcodes.pop_back();
        if (f.date_hint) {
          f.datesecs.pop_back();
          f.dateerr.pop_back();
        }
      }
      pr->nbad++;
    } else {
      pr->nrecords++;
      pr->batch_records++;
      appended++;
    }

    if (nl == nullptr) break;
    p = nl + 1;
  }
  return appended;
}

void dn_parser_reset_batch(void* h);

// Multithreaded parse: splits the buffer at newline boundaries into
// nthreads chunks, parses each on a worker with its own field outputs
// and dictionaries, then appends worker results to the owner in chunk
// order.  Record order, counters, and dictionary-code assignment order
// are bit-identical to the single-threaded path: chunks merge in input
// order, and each worker's new dictionary entries (first-occurrence
// order within the chunk) are interned into the owner dictionary before
// any later chunk's.
int64_t dn_parser_parse_mt(void* h, const char* buf, int64_t len,
                           int32_t nthreads) {
  Parser* pr = static_cast<Parser*>(h);
  if (nthreads < 1) nthreads = 1;
  // small buffers: threading overhead dominates
  if (nthreads == 1 || len < (1 << 21)) {
    return dn_parser_parse(h, buf, len);
  }

  // chunk boundaries on newlines
  std::vector<std::pair<const char*, const char*>> chunks;
  const char* pos = buf;
  const char* end = buf + len;
  for (int32_t t = 0; t < nthreads && pos < end; t++) {
    const char* target = buf + (len * (t + 1)) / nthreads;
    if (t == nthreads - 1 || target >= end) {
      chunks.emplace_back(pos, end);
      pos = end;
      break;
    }
    const char* nl = static_cast<const char*>(
        memchr(target, '\n', end - target));
    const char* cend = (nl != nullptr) ? nl + 1 : end;
    if (cend > pos) chunks.emplace_back(pos, cend);
    pos = cend;
  }
  if (chunks.size() <= 1) return dn_parser_parse(h, buf, len);

  // lazily grow the persistent worker pool
  while (pr->workers.size() < chunks.size()) {
    Parser* w = new Parser();
    w->fields.resize(pr->fields.size());
    for (size_t i = 0; i < pr->fields.size(); i++) {
      w->fields[i].date_hint = pr->fields[i].date_hint;
      w->fields[i].want_dict = pr->fields[i].want_dict;
    }
    w->trie = &pr->root;
    pr->workers.push_back(w);
    pr->remaps.emplace_back(
        std::vector<std::vector<int32_t>>(pr->fields.size()));
  }

  std::vector<std::thread> threads;
  size_t spawned = 0;
  try {
    for (size_t t = 0; t < chunks.size(); t++) {
      Parser* w = pr->workers[t];
      const char* cbeg = chunks[t].first;
      const char* cend = chunks[t].second;
      threads.emplace_back([w, cbeg, cend]() {
        dn_parser_reset_batch(w);
        dn_parser_parse(w, cbeg,
                        static_cast<int64_t>(cend - cbeg));
      });
      spawned++;
    }
  } catch (...) {
    // thread creation failed (cgroup pid limit, EAGAIN): join what
    // started, run the rest inline, and merge as usual
    for (auto& th : threads) th.join();
    for (size_t t = spawned; t < chunks.size(); t++) {
      Parser* w = pr->workers[t];
      dn_parser_reset_batch(w);
      dn_parser_parse(w, chunks[t].first,
                      static_cast<int64_t>(
                          chunks[t].second - chunks[t].first));
    }
    threads.clear();
  }
  for (auto& th : threads) th.join();

  // ordered merge
  int64_t total = 0;
  for (size_t t = 0; t < chunks.size(); t++) {
    Parser* w = pr->workers[t];
    int64_t n = static_cast<int64_t>(w->batch_records);
    pr->nlines += w->nlines;
    pr->nbad += w->nbad;
    w->nlines = 0;
    w->nbad = 0;
    w->nrecords = 0;
    pr->nrecords += static_cast<uint64_t>(n);
    pr->batch_records += static_cast<uint64_t>(n);
    total += n;
    for (size_t fi = 0; fi < pr->fields.size(); fi++) {
      FieldOut& dst = pr->fields[fi];
      FieldOut& src = w->fields[fi];
      // extend the persistent code remap for this worker's new strings
      std::vector<int32_t>& remap = pr->remaps[t][fi];
      for (size_t c = remap.size(); c < src.dict.values.size(); c++) {
        remap.push_back(dst.dict.code(src.dict.values[c]));
      }
      dst.tags.insert(dst.tags.end(), src.tags.begin(), src.tags.end());
      dst.nums.insert(dst.nums.end(), src.nums.begin(), src.nums.end());
      size_t base = dst.strcodes.size();
      dst.strcodes.insert(dst.strcodes.end(), src.strcodes.begin(),
                          src.strcodes.end());
      for (size_t i = base; i < dst.strcodes.size(); i++) {
        int32_t c = dst.strcodes[i];
        if (c >= 0) dst.strcodes[i] = remap[c];
      }
      if (dst.date_hint) {
        dst.datesecs.insert(dst.datesecs.end(), src.datesecs.begin(),
                            src.datesecs.end());
        dst.dateerr.insert(dst.dateerr.end(), src.dateerr.begin(),
                           src.dateerr.end());
      }
    }
  }
  return total;
}

int64_t dn_parser_nlines(void* h) {
  return static_cast<Parser*>(h)->nlines;
}
int64_t dn_parser_nbad(void* h) {
  return static_cast<Parser*>(h)->nbad;
}

int64_t dn_parser_batch_size(void* h) {
  return static_cast<int64_t>(
      static_cast<Parser*>(h)->batch_records);
}

const uint8_t* dn_parser_tags(void* h, int32_t field) {
  return static_cast<Parser*>(h)->fields[field].tags.data();
}
const double* dn_parser_nums(void* h, int32_t field) {
  return static_cast<Parser*>(h)->fields[field].nums.data();
}
const int32_t* dn_parser_strcodes(void* h, int32_t field) {
  return static_cast<Parser*>(h)->fields[field].strcodes.data();
}
const double* dn_parser_datesecs(void* h, int32_t field) {
  return static_cast<Parser*>(h)->fields[field].datesecs.data();
}
const uint8_t* dn_parser_dateerr(void* h, int32_t field) {
  return static_cast<Parser*>(h)->fields[field].dateerr.data();
}

// One-pass per-field batch statistics for the device path's
// eligibility checks (replacing several numpy scans per batch):
//   out[0] = count of TAG_ARRAY rows
//   out[1] = 1 when every numeric row is a finite integer within int32
//   out[2] = numeric min (0 when no numeric rows)
//   out[3] = numeric max (0 when no numeric rows)
//   out[4] = count of numeric rows (TAG_INT | TAG_NUMBER)
//   out[5] = count of TAG_STRING rows
void dn_parser_field_stats(void* h, int32_t field, double* out) {
  Parser* pr = static_cast<Parser*>(h);
  FieldOut& f = pr->fields[field];
  size_t n = f.tags.size();
  int64_t narr = 0, nnum = 0, nstr = 0;
  int all_i32 = 1;
  double mn = 0.0, mx = 0.0;
  for (size_t i = 0; i < n; i++) {
    uint8_t t = f.tags[i];
    if (t == TAG_INT || t == TAG_NUMBER) {
      double v = f.nums[i];
      if (nnum == 0) {
        mn = mx = v;
      } else {
        if (v < mn) mn = v;
        if (v > mx) mx = v;
      }
      nnum++;
      // NaN/inf fail the comparisons, clearing the flag
      if (!(v >= -2147483648.0 && v <= 2147483647.0 &&
            v == std::floor(v))) {
        all_i32 = 0;
      }
    } else if (t == TAG_ARRAY) {
      narr++;
    } else if (t == TAG_STRING) {
      nstr++;
    }
  }
  out[0] = static_cast<double>(narr);
  out[1] = static_cast<double>(all_i32);
  out[2] = mn;
  out[3] = mx;
  out[4] = static_cast<double>(nnum);
  out[5] = static_cast<double>(nstr);
}

// Numeric rows cast to int32 (caller must have checked the all-i32
// stat); non-numeric rows are 0.
void dn_parser_nums_i32(void* h, int32_t field, int32_t* out) {
  Parser* pr = static_cast<Parser*>(h);
  FieldOut& f = pr->fields[field];
  size_t n = f.tags.size();
  for (size_t i = 0; i < n; i++) {
    uint8_t t = f.tags[i];
    out[i] = (t == TAG_INT || t == TAG_NUMBER)
                 ? static_cast<int32_t>(f.nums[i])
                 : 0;
  }
}

// Date-column stats over error-free rows:
//   out[0] = 1 when every ok row's epoch-seconds is an integer in i32
//   out[1] = count of ok rows
void dn_parser_date_stats(void* h, int32_t field, double* out) {
  Parser* pr = static_cast<Parser*>(h);
  FieldOut& f = pr->fields[field];
  size_t n = f.dateerr.size();
  int all_i32 = 1;
  int64_t nok = 0;
  for (size_t i = 0; i < n; i++) {
    if (f.dateerr[i] != 0) continue;
    nok++;
    double v = f.datesecs[i];
    if (!(v >= -2147483648.0 && v <= 2147483647.0 &&
          v == std::floor(v))) {
      all_i32 = 0;
    }
  }
  out[0] = static_cast<double>(all_i32);
  out[1] = static_cast<double>(nok);
}

// Epoch seconds as int32 (error rows 0); caller checks date_stats.
void dn_parser_date_i32(void* h, int32_t field, int32_t* out) {
  Parser* pr = static_cast<Parser*>(h);
  FieldOut& f = pr->fields[field];
  size_t n = f.dateerr.size();
  for (size_t i = 0; i < n; i++) {
    out[i] = (f.dateerr[i] == 0)
                 ? static_cast<int32_t>(f.datesecs[i])
                 : 0;
  }
}

int32_t dn_parser_dict_size(void* h, int32_t field) {
  return static_cast<int32_t>(
      static_cast<Parser*>(h)->fields[field].dict.values.size());
}
const char* dn_parser_dict_get(void* h, int32_t field, int32_t code,
                               int32_t* len) {
  const std::string& s =
      static_cast<Parser*>(h)->fields[field].dict.values[code];
  *len = static_cast<int32_t>(s.size());
  return s.data();
}

// Reset per-batch outputs (dictionaries persist across batches).
void dn_parser_reset_batch(void* h) {
  Parser* pr = static_cast<Parser*>(h);
  pr->batch_records = 0;
  for (auto& f : pr->fields) {
    f.tags.clear();
    f.nums.clear();
    f.strcodes.clear();
    f.datesecs.clear();
    f.dateerr.clear();
  }
}

}  // extern "C"
