// dngen: fast muskie-log-like JSON test-data generator.
//
// Same record shape and distributions as tools/mktestdata (itself the
// behavioral equivalent of the reference's tools/mktestdata:1-192):
// linearly increasing timestamps, small-cardinality discrete fields,
// operation dependent on req.method, nullable/omitted req.caller, fixed
// status codes, mixed-distribution latencies, large-range dataSize.
// Exists so benchmarks can generate data at ingest-comparable rates
// (the Python generator tops out around 100k records/s, which would
// dominate large-scale benchmark wall time).
//
// Exposed as a plain C ABI for ctypes.

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ? seed : 0x9e3779b97f4a7c15ull) {}
  uint64_t next() {
    // xorshift64*
    s ^= s >> 12;
    s ^= s << 25;
    s ^= s >> 27;
    return s * 0x2545F4914F6CDD1Dull;
  }
  // uniform in [0, n)
  uint64_t below(uint64_t n) { return next() % n; }
  double unit() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
};

const char* const kHosts[] = {"ralph", "janey", "kearney", "sherri",
                              "wendell"};
const char* const kMethods[] = {"HEAD", "GET", "PUT", "DELETE"};
const char* const kOpsHead[] = {"headstorage", "headpublicstorage"};
const char* const kOpsGet[] = {"getjoberrors", "getpublicstorage",
                               "getstorage"};
const char* const kOpsPut[] = {"putdirectory", "putpublicobject",
                               "putobject"};
const char* const kOpsDelete[] = {"deletestorage",
                                  "deletepublicstorage"};
const int kStatus[] = {200, 204, 400, 404, 499, 500, 503};

int probdist(Rng& rng) {
  // (0.4, 1, 5), (0.3, 20, 30), (0.1, 100, 200), (rest, 1024, 4096)
  double r = rng.unit();
  double lo, hi;
  if (r < 0.4) {
    lo = 1; hi = 5;
  } else if (r < 0.7) {
    lo = 20; hi = 30;
  } else if (r < 0.8) {
    lo = 100; hi = 200;
  } else {
    lo = 1024; hi = 4096;
  }
  double v = rng.unit() * (hi - lo) + lo;
  return static_cast<int>(v + 0.5);
}

// days_from_civil inverse: epoch day -> y/m/d (Howard Hinnant)
void civil_from_days(int64_t z, int* y, unsigned* m, unsigned* d) {
  z += 719468;
  const int64_t era = (z >= 0 ? z : z - 146096) / 146097;
  const unsigned doe = static_cast<unsigned>(z - era * 146097);
  const unsigned yoe =
      (doe - doe / 1460 + doe / 36524 - doe / 146096) / 365;
  const int64_t yy = static_cast<int64_t>(yoe) + era * 400;
  const unsigned doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
  const unsigned mp = (5 * doy + 2) / 153;
  *d = doy - (153 * mp + 2) / 5 + 1;
  *m = mp + (mp < 10 ? 3 : -9);
  *y = static_cast<int>(yy + (*m <= 2));
}

}  // namespace

extern "C" {

// Generates records [start, start+n) of nrecords into buf; returns
// bytes written, or -1 if the buffer is too small (the guard demands
// 512 bytes of headroom before each record, so size 512 bytes per
// record).
int64_t dn_gen(char* buf, int64_t bufcap, int64_t start, int64_t n,
               int64_t nrecords, int64_t mindate_ms, int64_t maxdate_ms,
               uint64_t seed) {
  char* p = buf;
  char* end = buf + bufcap;
  for (int64_t i = start; i < start + n; i++) {
    if (end - p < 512)
      return -1;
    Rng rng(seed * 0x9E3779B97F4A7C15ull + i * 0xBF58476D1CE4E5B9ull);
    rng.next();

    int64_t ts = mindate_ms +
        static_cast<int64_t>((static_cast<double>(i) / nrecords) *
                             (maxdate_ms - mindate_ms) + 0.5);
    int64_t secs = ts / 1000;
    int ms = static_cast<int>(ts % 1000);
    int64_t days = secs / 86400;
    int rem = static_cast<int>(secs % 86400);
    int y;
    unsigned mo, dd;
    civil_from_days(days, &y, &mo, &dd);

    const char* host = kHosts[rng.below(5)];
    unsigned mi = static_cast<unsigned>(rng.below(4));
    const char* method = kMethods[mi];
    const char* op;
    switch (mi) {
      case 0: op = kOpsHead[rng.below(2)]; break;
      case 1: op = kOpsGet[rng.below(3)]; break;
      case 2: op = kOpsPut[rng.below(3)]; break;
      default: op = kOpsDelete[rng.below(2)]; break;
    }
    unsigned caller = static_cast<unsigned>(rng.below(4));
    int url = static_cast<int>(rng.below(500));
    int status = kStatus[rng.below(7)];
    int latency = probdist(rng);
    int dlatency = probdist(rng);
    int64_t dsize =
        static_cast<int64_t>(rng.unit() * 1073741824.0 + 0.5);

    p += snprintf(
        p, static_cast<size_t>(end - p),
        "{\"time\":\"%04d-%02u-%02uT%02d:%02d:%02d.%03dZ\","
        "\"host\":\"%s\",\"req\":{\"method\":\"%s\","
        "\"url\":\"/random/url/number/%d\"",
        y, mo, dd, rem / 3600, (rem / 60) % 60, rem % 60, ms, host,
        method, url);
    if (caller == 0)
      p += snprintf(p, static_cast<size_t>(end - p),
                    ",\"caller\":\"admin\"");
    else if (caller == 1)
      p += snprintf(p, static_cast<size_t>(end - p),
                    ",\"caller\":\"poseidon\"");
    else if (caller == 2)
      p += snprintf(p, static_cast<size_t>(end - p),
                    ",\"caller\":null");
    // caller == 3: omitted
    p += snprintf(
        p, static_cast<size_t>(end - p),
        "},\"operation\":\"%s\",\"res\":{\"statusCode\":%d},"
        "\"latency\":%d,\"dataLatency\":%d,\"dataSize\":%lld}\n",
        op, status, latency, dlatency,
        static_cast<long long>(dsize));
  }
  return p - buf;
}

}  // extern "C"
