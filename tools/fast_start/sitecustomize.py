"""CLI fast start: shadow expensive site-customization hooks.

Some deployment environments install a ``sitecustomize`` that imports a
heavyweight accelerator runtime at interpreter start, adding seconds to
every ``dn`` invocation (the reference project called out exactly this
kind of startup cost, reference README.md:742-747).  ``bin/dn`` puts
this directory first on PYTHONPATH so that THIS module is the one
``site`` imports.

When the command actually needs device backends — ``DN_ENGINE=jax``,
a multi-process launch (``DN_COORDINATOR``), or fast start disabled via
``DN_FAST_START=0`` — the real ``sitecustomize`` found later on
``sys.path`` is loaded so accelerator registration still happens.
Otherwise interpreter start stays light; if a scan later reaches for
jax anyway, ``dragnet_tpu.ops.get_jax`` degrades to the host engine
(correct results, no device acceleration).
"""

import os


def _needs_real_site():
    if os.environ.get('DN_FAST_START', '1') == '0':
        return True
    if os.environ.get('DN_ENGINE') == 'jax':
        return True
    if os.environ.get('DN_COORDINATOR'):
        return True
    return False


def _chain():
    import importlib.util
    import sys
    here = os.path.dirname(os.path.abspath(__file__))
    for p in sys.path:
        if not p:
            continue
        if os.path.abspath(p) == here:
            continue
        f = os.path.join(p, 'sitecustomize.py')
        if os.path.exists(f):
            spec = importlib.util.spec_from_file_location(
                'sitecustomize_chained', f)
            mod = importlib.util.module_from_spec(spec)
            try:
                spec.loader.exec_module(mod)
            except Exception:
                # match CPython's execsitecustomize: report, continue
                import traceback
                sys.stderr.write('Error in chained sitecustomize '
                                 '(%s):\n' % f)
                traceback.print_exc()
            return


if _needs_real_site():
    _chain()
