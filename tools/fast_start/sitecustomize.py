"""CLI fast start: shadow expensive site-customization hooks (opt-in).

Some deployment environments install a ``sitecustomize`` that imports a
heavyweight accelerator runtime at interpreter start, adding seconds to
every ``dn`` invocation (the reference project called out exactly this
kind of startup cost, reference README.md:742-747).  When the operator
opts in with ``DN_FAST_START=1``, ``bin/dn`` puts this directory first
on PYTHONPATH so that THIS module is the one ``site`` imports.

When the command actually needs device backends — ``DN_ENGINE=jax`` or
a multi-process launch (``DN_COORDINATOR``) — the real
``sitecustomize`` found later on ``sys.path`` is loaded so accelerator
registration still happens.  Otherwise interpreter start stays light;
if a scan later reaches for jax anyway, ``dragnet_tpu.ops.get_jax``
degrades to the host engine (correct results, no device acceleration).
"""

import os


def _needs_real_site():
    if os.environ.get('DN_FAST_START', '0') != '1':
        return True
    if os.environ.get('DN_ENGINE') == 'jax':
        return True
    if os.environ.get('DN_COORDINATOR'):
        return True
    return False


def _chain():
    import importlib.util
    from importlib.machinery import PathFinder
    import sys
    here = os.path.dirname(os.path.abspath(__file__))
    search = [p for p in sys.path
              if p and os.path.abspath(p) != here]
    # find_spec handles every importable form (module, package,
    # compiled-only), not just a literal sitecustomize.py file
    spec = PathFinder.find_spec('sitecustomize', search)
    if spec is None or spec.loader is None:
        return
    mod = importlib.util.module_from_spec(spec)
    # replace this shim in sys.modules so package-relative imports
    # inside the chained module resolve against it (Python honors
    # self-replacement during module execution)
    prev = sys.modules.get('sitecustomize')
    sys.modules['sitecustomize'] = mod
    try:
        spec.loader.exec_module(mod)
    except Exception:
        # match CPython: report, drop the half-initialized module
        import traceback
        sys.stderr.write('Error in chained sitecustomize (%s):\n'
                         % (spec.origin or spec.name))
        traceback.print_exc()
        if prev is not None:
            sys.modules['sitecustomize'] = prev
        else:
            sys.modules.pop('sitecustomize', None)


if _needs_real_site():
    _chain()
