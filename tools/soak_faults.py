#!/usr/bin/env python3
"""Chaos soak: mixed scan/query/build traffic under deterministic
fault injection (DN_FAULTS), plus mid-flush SIGKILL crash drills —
asserting the repo's robustness contract end to end:

* zero torn shards: after every round the index trees contain no
  orphaned/torn tmp files outside the quarantine directory;
* byte-identity: every operation that reports success returns output
  byte-identical to a fault-free run, and every failure is a clean
  `dn: ...` error (never a traceback);
* crash atomicity: a `dn build` subprocess SIGKILLed mid-shard-flush
  (or mid-commit) leaves a tree whose query output byte-equals either
  the pre-build or the completed-build run — never a mix — once the
  recovery sweep has run;
* observability: injection/recovery counters appear in `dn serve`
  /stats and under DN_COUNTERS_ALL=1.

Run the full soak (>= 500 injected faults across all sites, both
DN_INDEX_FORMAT modes) via `make soak-faults`; `--fast` runs the
miniature tier-1 variant.  Exits non-zero on any violation.

`--cluster` runs the scatter-gather cluster drill instead (`make
soak-cluster`): 3 members x 2-replica partitions (one member a
SIGKILL-able subprocess), mixed routed-query traffic under armed
router/member/transport faults, a mid-query SIGKILL of a partition
owner, and a no-surviving-replica drill — asserting byte-identity vs
the single-process run whenever any replica survives, the clean
degraded-or-error contract (missing partitions NAMED, never a hang,
traceback, or silently short bytes) when none does, and
breaker/failover counters visible in /stats.

`--follow` runs the continuous-ingest drill instead (`make
soak-follow`): an appender subprocess grows a log while a `dn follow`
daemon subprocess tails it under armed
follow.read/checkpoint/publish faults; the follower is SIGKILLed
mid-batch (externally and via kill-kind faults at the publish seams),
restarted, and caught up — after EVERY kill the index tree must
byte-equal a from-scratch `dn build` over the exact checkpointed
input prefix (zero duplicated, zero lost points), with no litter.

`--compact` runs the background-compaction drill instead (`make
soak-compact`): `dn follow --once` rounds in append mode
(DN_FOLLOW_APPEND) land every batch as mini-generations while a
`dn serve` member — result cache on, 1-second maintenance timer —
compacts generation groups and refreshes rollup shards under armed
compact.publish/rollup.publish faults and a remote query flood;
separate `dn compact` / `dn rollup` subprocesses are SIGKILLed
mid-publish on both sides of the commit record.  Every accepted
response must byte-equal a from-scratch `dn build` (generations
pending, mid-rewrite, post-kill, post-compaction), failures must be
clean `dn:` errors, and after a final converge compaction the live
tree must byte-equal the from-scratch build shard for shard with
zero stranded tmps.

`--subscribe` runs the standing-query drill instead (`make
soak-subscribe`): a `dn subscribe` flood over the 3-member cluster
(in-process readers on every member plus a real `dn subscribe` CLI
subprocess) while publishes land under armed push/transport faults
(torn push frames force token-based resume); a `dn build` publisher
subprocess and the CLI subscriber are SIGKILLed mid-stream.  At every
quiescent epoch each subscriber's latest pushed payload must be
byte-identical to a `dn query --remote` poll, the killed publisher's
tree must converge with zero torn shards, the killed subscriber must
be shed without delaying the healthy flood, and nothing may wedge.
"""

import argparse
import json
import os
import subprocess
import sys
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from dragnet_tpu import cli                                # noqa: E402
from dragnet_tpu.errors import DNError                     # noqa: E402
from dragnet_tpu import faults as mod_faults               # noqa: E402
from dragnet_tpu import index_journal as mod_journal       # noqa: E402
from dragnet_tpu import vpipe as mod_vpipe                 # noqa: E402
from dragnet_tpu.serve import client as mod_client         # noqa: E402
from dragnet_tpu.serve import server as mod_server         # noqa: E402

FORMATS = ('dnc', 'sqlite')


def run_cli(args, env=None):
    """One in-process CLI run, stdout/stderr captured as bytes
    through the serve layer's thread-stdio router."""
    prior = {}
    for k, v in (env or {}).items():
        prior[k] = os.environ.get(k)
        os.environ[k] = v
    try:
        with mod_server.thread_stdio() as cap:
            rc = cli.main(list(args))
        out, err = cap.finish()
        return rc, out, err
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def gen_data(path, n, start=0, days=5):
    """Deterministic newline-JSON over `days` days of 2014-01."""
    import datetime
    t0 = 1388534400  # 2014-01-01T00:00:00Z
    mode = 'a' if start else 'w'
    span = days * 86400
    with open(path, mode) as f:
        for i in range(start, start + n):
            ts = datetime.datetime.utcfromtimestamp(
                t0 + (i * 4999) % span).strftime(
                    '%Y-%m-%dT%H:%M:%S.000Z')
            f.write(json.dumps({
                'time': ts,
                'host': 'host%d' % (i % 4),
                'operation': ('get', 'put', 'index')[i % 3],
                'req': {'method': ('GET', 'PUT')[i % 2]},
                'latency': (i * 7) % 230,
            }, separators=(',', ':')) + '\n')


def make_corpus(root, n=1200, days=5):
    """Data + one datasource per index format, returning the context
    the rounds use.  DRAGNET_CONFIG points at the corpus rc for the
    whole soak."""
    datafile = os.path.join(root, 'data.log')
    gen_data(datafile, n, days=days)
    rc_path = os.path.join(root, 'dragnetrc.json')
    os.environ['DRAGNET_CONFIG'] = rc_path
    ctx = {'root': root, 'rc_path': rc_path, 'datafile': datafile,
           'n': n, 'days': days, 'ds': {}, 'idx': {}}
    for fmt in FORMATS:
        ds = 'ds_' + fmt
        idx = os.path.join(root, 'idx_' + fmt)
        rc, out, err = run_cli([
            'datasource-add', '--path', datafile, '--index-path',
            idx, '--time-field', 'time', ds])
        assert rc == 0, err
        rc, out, err = run_cli([
            'metric-add', '-b',
            'timestamp[date,field=time,aggr=lquantize,step=86400],'
            'host,latency[aggr=quantize]', ds, 'm1'])
        assert rc == 0, err
        rc, out, err = run_cli([
            'metric-add', '-b', 'operation', '-f',
            '{"eq": ["req.method", "GET"]}', ds, 'm2'])
        assert rc == 0, err
        ctx['ds'][fmt] = ds
        ctx['idx'][fmt] = idx
    return ctx


def build(ctx, fmt):
    rc, out, err = run_cli(['build', ctx['ds'][fmt]],
                           env={'DN_INDEX_FORMAT': fmt})
    assert rc == 0, err
    return rc, out, err


def query_cases(ds):
    return [
        ['query', '-b', 'host', ds],
        ['query', '-b', 'host,latency[aggr=quantize]', '--raw', ds],
        ['query', '--points', '-b', 'operation', ds],
        ['query', '-b', 'host', '-A', '2014-01-02', '-B',
         '2014-01-04', ds],
    ]


def scan_cases(ds):
    return [
        ['scan', '-b', 'operation', '--raw', ds],
        ['scan', '-b', 'host,latency[aggr=quantize]', ds],
    ]


def goldens(ctx):
    """Fault-free reference bytes for every case x format."""
    table = {}
    for fmt in FORMATS:
        ds = ctx['ds'][fmt]
        for case in query_cases(ds) + scan_cases(ds):
            table[(fmt, tuple(case))] = run_cli(
                case, env={'DN_INDEX_FORMAT': fmt})
    return table


def tree_tmp_litter(idx):
    """Torn/orphaned tmp files anywhere in the tree OUTSIDE the
    quarantine directory — the soak's zero-torn-shards invariant.
    The committed integrity catalog (+ its flock sidecar) is durable
    tree metadata (readers filter it from shard walks, but it is not
    litter); its orphaned `.tmp`s still are."""
    bad = []
    for r, dirs, names in os.walk(idx):
        if mod_journal.QUARANTINE_DIR in dirs:
            dirs.remove(mod_journal.QUARANTINE_DIR)
        for name in names:
            if mod_journal.is_index_litter(name) and \
                    not mod_journal.is_durable_metadata(name):
                bad.append(os.path.join(r, name))
    return bad


class Soak(object):
    def __init__(self, ctx, verbose=True):
        self.ctx = ctx
        self.golden = goldens(ctx)
        self.violations = []
        self.ops = 0
        self.clean_errors = 0
        self.verbose = verbose

    def note(self, msg):
        if self.verbose:
            sys.stderr.write('soak: %s\n' % msg)

    def violate(self, msg):
        self.violations.append(msg)
        sys.stderr.write('soak: VIOLATION: %s\n' % msg)

    def check_result(self, fmt, case, got, remote=False):
        """A faulted operation must be byte-identical to the golden
        run, or a clean `dn: ...` failure."""
        self.ops += 1
        rc, out, err = got
        gold = self.golden[(fmt, tuple(case))]
        if rc == 0:
            # warnings (e.g. device-probe fallback) may precede the
            # output; stdout must match the golden exactly
            if out != gold[1]:
                self.violate('%s %s: success with divergent bytes'
                             % (fmt, ' '.join(case)))
            return
        text = err.decode('utf-8', 'replace')
        if 'Traceback' in text or 'dn:' not in text:
            self.violate('%s %s: unclean failure: %r'
                         % (fmt, ' '.join(case), text[-300:]))
            return
        self.clean_errors += 1

    def check_trees(self, when):
        """Zero-torn-shards invariant.  A commit-phase fault can leave
        this process's own journal + tmps behind as RECOVERABLE
        intent (by design); a clean superseding build retires it, so
        the scan below only ever flags genuinely leaked state."""
        mod_journal.reset_sweep_memo()
        for fmt in FORMATS:
            build(self.ctx, fmt)
            mod_journal.sweep_index_tree(self.ctx['idx'][fmt])
            litter = tree_tmp_litter(self.ctx['idx'][fmt])
            if litter:
                self.violate('%s: torn shards after %s: %s'
                             % (fmt, when, litter))

    # -- in-process fault rounds -------------------------------------

    def local_rounds(self, spec, rounds, include_build=True,
                     env=None):
        # DN_FAULTS is armed ONCE for the whole block: the per-site
        # PRNGs must keep drawing across operations (re-arming per op
        # would re-seed them, collapsing every draw to the first)
        prior = os.environ.get('DN_FAULTS')
        os.environ['DN_FAULTS'] = spec
        try:
            for r in range(rounds):
                for fmt in FORMATS:
                    ds = self.ctx['ds'][fmt]
                    cases = query_cases(ds) + scan_cases(ds)
                    for case in cases:
                        e = dict(env or {}, DN_INDEX_FORMAT=fmt)
                        self.check_result(fmt, case,
                                          run_cli(case, env=e))
                    if include_build:
                        e = dict(env or {}, DN_INDEX_FORMAT=fmt)
                        rc, out, err = run_cli(['build', ds], env=e)
                        self.ops += 1
                        if rc != 0:
                            text = err.decode('utf-8', 'replace')
                            if 'Traceback' in text or \
                                    'dn:' not in text:
                                self.violate('%s build: unclean: %r'
                                             % (fmt, text[-300:]))
                            else:
                                self.clean_errors += 1
        finally:
            if prior is None:
                os.environ.pop('DN_FAULTS', None)
            else:
                os.environ['DN_FAULTS'] = prior
        self.check_trees('local rounds [%s]' % spec)

    # -- remote (serve) fault rounds ---------------------------------

    def remote_rounds(self, spec, rounds, backoff_ms='5'):
        sock = os.path.join(self.ctx['root'], 'soak.sock')
        if os.path.exists(sock):
            os.unlink(sock)
        srv = mod_server.DnServer(
            socket_path=sock,
            conf={'max_inflight': 4, 'queue_depth': 16,
                  'deadline_ms': 0, 'coalesce': True,
                  'drain_s': 10}).start()
        prior = os.environ.get('DN_FAULTS')
        os.environ['DN_FAULTS'] = spec
        env = {'DN_REMOTE_RETRIES': '4',
               'DN_REMOTE_BACKOFF_MS': backoff_ms,
               # bound the exchange so even a pathological drop costs
               # the soak seconds, not the default interactive window
               'DN_SERVE_CLIENT_TIMEOUT_S': '30'}
        try:
            for r in range(rounds):
                for fmt in FORMATS:
                    ds = self.ctx['ds'][fmt]
                    for case in query_cases(ds) + scan_cases(ds):
                        e = dict(env, DN_INDEX_FORMAT=fmt)
                        got = run_cli(case[:1] + ['--remote', sock] +
                                      case[1:], env=e)
                        self.check_result(fmt, case, got)
        finally:
            if prior is None:
                os.environ.pop('DN_FAULTS', None)
            else:
                os.environ['DN_FAULTS'] = prior
            srv.stop()
        self.check_trees('remote rounds [%s]' % spec)

    # -- SIGKILL crash drills ----------------------------------------

    def kill_rounds(self, specs, per_format=1):
        """Subprocess `dn build` SIGKILLed mid-publish by each spec;
        the recovered tree must answer queries byte-equal to either
        the pre-build or the completed-build output."""
        datafile = self.ctx['datafile']
        n = self.ctx['n']
        # extend the corpus so the killed build differs from the
        # committed tree (otherwise pre == post and the assertion
        # proves nothing)
        gen_data(datafile, n // 2, start=n,
                 days=self.ctx.get('days', 5))
        self.ctx['n'] = n + n // 2
        post = {}

        def check_case(ds):
            return ['query', '-b', 'host', ds]

        pre = {fmt: self.golden[(fmt,
                                 tuple(check_case(self.ctx['ds'][fmt])))]
               for fmt in FORMATS}

        for fmt in FORMATS:
            ds = self.ctx['ds'][fmt]
            for spec in specs:
                for r in range(per_format):
                    env = dict(os.environ, DN_INDEX_FORMAT=fmt,
                               DN_FAULTS=spec, JAX_PLATFORMS='cpu')
                    proc = subprocess.run(
                        [sys.executable,
                         os.path.join(REPO_ROOT, 'bin', 'dn.py'),
                         'build', ds],
                        env=env, stdout=subprocess.PIPE,
                        stderr=subprocess.PIPE, timeout=300)
                    self.ops += 1
                    if proc.returncode != -9:
                        self.violate(
                            '%s kill drill [%s]: expected SIGKILL, '
                            'got rc=%s stderr=%r'
                            % (fmt, spec, proc.returncode,
                               proc.stderr[-200:]))
                        continue
                    self.note('killed build [%s] %s' % (spec, fmt))
                    # recovery: the sweep runs on the query path
                    mod_journal.reset_sweep_memo()
                    got = run_cli(check_case(ds),
                                  env={'DN_INDEX_FORMAT': fmt})
                    if fmt not in post:
                        # complete a clean build once to learn the
                        # post-build bytes
                        build(self.ctx, fmt)
                        post[fmt] = run_cli(
                            check_case(ds),
                            env={'DN_INDEX_FORMAT': fmt})
                        # rebuild happened AFTER `got` was measured;
                        # got must match pre or post
                    if got not in (pre[fmt], post[fmt]):
                        self.violate(
                            '%s kill drill [%s]: recovered query '
                            'matches neither pre- nor post-build '
                            'output' % (fmt, spec))
                    litter = tree_tmp_litter(self.ctx['idx'][fmt])
                    if litter:
                        self.violate('%s kill drill [%s]: torn '
                                     'shards: %s' % (fmt, spec,
                                                     litter))
            # leave the tree completed for the next spec/round
            if fmt in post:
                build(self.ctx, fmt)
        # the goldens now describe the extended corpus
        for fmt in FORMATS:
            build(self.ctx, fmt)
        self.golden = goldens(self.ctx)

    def summary(self):
        counters = mod_vpipe.global_counters()
        per_site = {k[len('fault injected '):]: v
                    for k, v in counters.items()
                    if k.startswith('fault injected ')}
        return {
            'ops': self.ops,
            'clean_errors': self.clean_errors,
            'violations': self.violations,
            'faults_injected_total': counters.get('faults injected',
                                                  0),
            'faults_by_site': per_site,
            'recovery': {
                k: counters.get(k, 0)
                for k in ('index recovery rollbacks',
                          'index recovery rollforwards',
                          'index tmps quarantined')},
            'remote_retries': counters.get('remote transport retries',
                                           0),
        }


class ClusterSoak(Soak):
    """The scatter-gather drill: members a/c in-process, member b a
    subprocess (so a partition owner can be SIGKILLed mid-query).
    Topology: 3 partitions x 2 replicas — (a,b), (b,c), (c,a) — so
    killing any ONE member leaves every partition a live replica."""

    def __init__(self, ctx, verbose=True):
        super(ClusterSoak, self).__init__(ctx, verbose=verbose)
        self.socks = {}
        self.servers = {}
        self.proc_b = None
        self.topo_path = None

    # -- lifecycle ----------------------------------------------------

    def start_cluster(self):
        root = self.ctx['root']
        self.socks = {m: os.path.join(root, 'dn-%s.sock' % m)
                      for m in 'abc'}
        self.topo_path = os.path.join(root, 'topo.json')
        with open(self.topo_path, 'w') as f:
            json.dump({
                'epoch': 1, 'assign': 'hash',
                'members': {m: {'endpoint': self.socks[m]}
                            for m in 'abc'},
                'partitions': [
                    {'id': 0, 'replicas': ['a', 'b']},
                    {'id': 1, 'replicas': ['b', 'c']},
                    {'id': 2, 'replicas': ['c', 'a']},
                ],
            }, f)
        from dragnet_tpu.serve import topology as mod_topology
        conf = {'max_inflight': 8, 'queue_depth': 32,
                'deadline_ms': 0, 'coalesce': True, 'drain_s': 10}
        for m in 'ac':
            topo = mod_topology.load_topology(self.topo_path,
                                              member=m)
            self.servers[m] = mod_server.DnServer(
                socket_path=self.socks[m], conf=dict(conf),
                cluster=topo, member=m).start()
        self.spawn_b()

    def spawn_b(self):
        if os.path.exists(self.socks['b']):
            os.unlink(self.socks['b'])
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        env.pop('DN_FAULTS', None)   # armed per-round via rounds' env
        self.proc_b = subprocess.Popen(
            [sys.executable, os.path.join(REPO_ROOT, 'bin', 'dn.py'),
             'serve', '--socket', self.socks['b'],
             '--cluster', self.topo_path, '--member', 'b'],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        deadline = time.time() + 30
        while time.time() < deadline:
            doc = mod_client.health(self.socks['b'], timeout_s=2.0)
            if doc.get('ok'):
                return
            time.sleep(0.1)
        raise RuntimeError('cluster member b never became healthy')

    def stop_cluster(self):
        for srv in self.servers.values():
            try:
                srv.stop()
            except Exception:
                pass
        self.servers = {}
        if self.proc_b is not None and self.proc_b.poll() is None:
            self.proc_b.kill()
            self.proc_b.wait()
        self.proc_b = None

    # -- checks -------------------------------------------------------

    def check_routed(self, fmt, case, got, degraded_ok=True):
        """The cluster contract: success must be byte-identical to
        the single-process golden; failure must be a clean `dn: ...`
        error (a degraded response names the missing partitions)."""
        self.ops += 1
        rc, out, err = got
        gold = self.golden[(fmt, tuple(case))]
        text = err.decode('utf-8', 'replace')
        if 'Traceback' in text:
            self.violate('%s %s: traceback in routed response: %r'
                         % (fmt, ' '.join(case), text[-300:]))
            return
        if rc == 0:
            if gold[0] != 0:
                self.violate('%s %s: routed success where the '
                             'single-process run fails'
                             % (fmt, ' '.join(case)))
            elif out != gold[1]:
                self.violate('%s %s: routed success with divergent '
                             'bytes' % (fmt, ' '.join(case)))
            return
        if 'dn:' not in text:
            self.violate('%s %s: unclean routed failure: %r'
                         % (fmt, ' '.join(case), text[-300:]))
            return
        if gold[0] != 0:
            # the single-process run fails this case too (e.g. no
            # metric can serve it): a clean routed failure IS the
            # byte-contract match
            self.clean_errors += 1
            return
        if not degraded_ok:
            self.violate('%s %s: unexpected failure with every '
                         'replica live: %r'
                         % (fmt, ' '.join(case), text[-300:]))
            return
        self.clean_errors += 1

    # -- rounds -------------------------------------------------------

    def routed_rounds(self, spec, rounds, degraded_ok=True,
                      env=None):
        """Mixed routed-query traffic through every member as router
        while `spec` is armed (in this process AND in member b, whose
        registry re-arms from its inherited environment per op is not
        possible — b runs armed only when spec was exported before
        spawn; the in-process seams cover router/client/serve sides
        deterministically)."""
        prior = os.environ.get('DN_FAULTS')
        if spec:
            os.environ['DN_FAULTS'] = spec
        base_env = {'DN_REMOTE_RETRIES': '3',
                    'DN_REMOTE_BACKOFF_MS': '5',
                    'DN_REMOTE_CONNECT_TIMEOUT_S': '5',
                    'DN_SERVE_CLIENT_TIMEOUT_S': '60'}
        base_env.update(env or {})
        try:
            for r in range(rounds):
                for fmt in FORMATS:
                    ds = self.ctx['ds'][fmt]
                    for i, case in enumerate(query_cases(ds)):
                        via = 'abc'[(r + i) % 3]
                        got = run_cli(
                            case[:1] + ['--remote', self.socks[via]] +
                            case[1:], env=dict(base_env))
                        self.check_routed(fmt, case, got,
                                          degraded_ok=degraded_ok)
        finally:
            if prior is None:
                os.environ.pop('DN_FAULTS', None)
            else:
                os.environ['DN_FAULTS'] = prior

    def degraded_header_drill(self):
        """router.dispatch at rate 1.0: every partition fails, and
        the response header must NAME the missing partitions and be
        retryable (DN_ROUTER_PARTIAL=error default)."""
        prior = os.environ.get('DN_FAULTS')
        os.environ['DN_FAULTS'] = 'router.dispatch:error:1.0'
        try:
            ds = self.ctx['ds'][FORMATS[0]]
            rc, header, out, err = mod_client.request_bytes(
                self.socks['a'],
                {'op': 'query', 'ds': ds,
                 'config': self.ctx['rc_path'],
                 'queryconfig': {'breakdowns': [
                     {'name': 'host', 'field': 'host'}]},
                 'interval': 'day', 'opts': {}}, timeout_s=120.0)
            self.ops += 1
            if rc == 0:
                self.violate('degraded drill: rc=0 with every '
                             'partition dead')
            elif not header.get('retryable'):
                self.violate('degraded drill: response not marked '
                             'retryable')
            elif header.get('stats', {}).get('missing_partitions') \
                    != [0, 1, 2]:
                self.violate('degraded drill: missing partitions not '
                             'named: %r' % header.get('stats'))
            else:
                self.clean_errors += 1
        finally:
            if prior is None:
                os.environ.pop('DN_FAULTS', None)
            else:
                os.environ['DN_FAULTS'] = prior

    def kill_owner_drill(self, nthreads=3, per_thread=4):
        """SIGKILL member b while routed queries are in flight: every
        in-flight and subsequent query must fail over to the
        surviving replica of each partition (byte-identical) or fail
        clean — never hang, never return short bytes."""
        import threading
        results = []
        lock = threading.Lock()
        # run_cli's per-call env install/restore mutates the PROCESS
        # environment — concurrent workers must not each do it (the
        # first finisher would strip the retry knobs out from under
        # the others mid-failover).  Install once around the whole
        # drill instead.
        env = {'DN_REMOTE_RETRIES': '3', 'DN_REMOTE_BACKOFF_MS': '5',
               'DN_REMOTE_CONNECT_TIMEOUT_S': '5',
               'DN_SERVE_CLIENT_TIMEOUT_S': '60'}
        prior = {}
        for k, v in env.items():
            prior[k] = os.environ.get(k)
            os.environ[k] = v
        started = threading.Barrier(nthreads + 1)

        def worker(tid):
            started.wait()
            for i in range(per_thread):
                fmt = FORMATS[(tid + i) % len(FORMATS)]
                ds = self.ctx['ds'][fmt]
                case = query_cases(ds)[(tid + i) %
                                       len(query_cases(ds))]
                got = run_cli(case[:1] +
                              ['--remote', self.socks['a']] +
                              case[1:])
                with lock:
                    results.append((fmt, case, got))

        try:
            threads = [threading.Thread(target=worker, args=(t,))
                       for t in range(nthreads)]
            for t in threads:
                t.start()
            started.wait()
            time.sleep(0.05)     # let queries get in flight
            self.proc_b.kill()   # SIGKILL the partition owner
            self.proc_b.wait()
            self.note('SIGKILLed member b mid-query')
            for t in threads:
                t.join(120)
                if t.is_alive():
                    self.violate('kill drill: query thread hung')
            for fmt, case, got in results:
                self.check_routed(fmt, case, got)
            # after the kill: every partition still has a live
            # replica (a or c), so routed queries must be
            # BYTE-IDENTICAL again
            for fmt in FORMATS:
                ds = self.ctx['ds'][fmt]
                for case in query_cases(ds):
                    got = run_cli(case[:1] +
                                  ['--remote', self.socks['a']] +
                                  case[1:])
                    self.check_routed(fmt, case, got,
                                      degraded_ok=False)
        finally:
            for k, v in prior.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        doc = mod_client.stats(self.socks['a'], timeout_s=30.0)
        cl = doc.get('cluster') or {}
        counters = cl.get('counters') or {}
        if counters.get('failovers', 0) < 1:
            self.violate('kill drill: no failovers recorded in '
                         '/stats after a dead partition owner')
        if 'members' not in cl:
            self.violate('kill drill: /stats cluster section missing '
                         'member breaker states')
        self.cluster_counters = counters

    def fleet_obs_drill(self):
        """Fleet observability mid-drill (member b is DEAD here):
        `dn stats --cluster` through a surviving member must return a
        COMPLETE fleet document — live members merged, the SIGKILLed
        member marked unreachable, never a hang or a partial doc
        presented as complete — and the event journal must have
        captured the drill's failover and SIGKILL-recovery
        (breaker-open) events with trace ids."""
        self.ops += 1
        t0 = time.time()
        rc, out, err = run_cli(['stats', '--cluster', '--remote',
                                self.socks['a']])
        elapsed = time.time() - t0
        if rc != 0:
            self.violate('fleet drill: dn stats --cluster failed: %r'
                         % err[-300:])
            return
        if elapsed > 60:
            self.violate('fleet drill: fleet view took %.1fs with a '
                         'dead member' % elapsed)
        try:
            doc = json.loads(out.decode('utf-8'))
        except ValueError:
            self.violate('fleet drill: malformed fleet doc')
            return
        if 'b' not in doc.get('unreachable', []):
            self.violate('fleet drill: SIGKILLed member b not '
                         'reported unreachable: %r'
                         % doc.get('unreachable'))
        if doc.get('complete'):
            self.violate('fleet drill: fleet doc claims complete '
                         'with a dead member')
        for m in 'ac':
            row = (doc.get('members') or {}).get(m) or {}
            if not row.get('ok'):
                self.violate('fleet drill: live member %s not '
                             'merged: %r' % (m, row))
        if not (doc.get('aggregate') or {}).get('latency'):
            self.violate('fleet drill: no aggregate latency '
                         'quantiles in the fleet doc')
        if set(doc.get('epochs') or {}) < {'a', 'c'}:
            self.violate('fleet drill: epoch table missing live '
                         'members: %r' % doc.get('epochs'))
        # the event journal captured the drill (the in-process
        # members share the process journal; DN_SLOW_MS armed trace
        # contexts, so request-path events carry trace ids)
        rc, header, out, err = mod_client.request_bytes(
            self.socks['a'], {'op': 'events'}, timeout_s=30.0)
        if rc != 0:
            self.violate('fleet drill: events op failed: %r'
                         % err[-300:])
            return
        doc = json.loads(out.decode('utf-8'))
        if not doc.get('enabled'):
            self.violate('fleet drill: event journal not enabled')
            return
        events = doc.get('events') or []
        failovers = [e for e in events
                     if e.get('type') == 'router.failover']
        if not failovers:
            self.violate('fleet drill: no router.failover events in '
                         'the journal after the kill drill')
        elif not any(e.get('trace') for e in failovers):
            self.violate('fleet drill: failover events captured '
                         'without trace ids')
        if not any(e.get('type') == 'breaker.open' and
                   e.get('member') == 'b' for e in events):
            self.violate('fleet drill: no breaker.open event for the '
                         'SIGKILLed member')
        self.note('fleet drill: %d journal events, %d failovers '
                  'with trace ids'
                  % (len(events), len(failovers)))

    def no_replica_drill(self):
        """Member b is dead; stop c too — partition 1 (replicas b,c)
        has no survivor.  The response must be the clean degraded
        error NAMING partition 1, and the header must be retryable."""
        self.servers['c'].stop()
        ds = self.ctx['ds'][FORMATS[0]]
        rc, header, out, err = mod_client.request_bytes(
            self.socks['a'],
            {'op': 'query', 'ds': ds, 'config': self.ctx['rc_path'],
             'queryconfig': {'breakdowns': [
                 {'name': 'host', 'field': 'host'}]},
             'interval': 'day', 'opts': {}}, timeout_s=120.0)
        self.ops += 1
        text = err.decode('utf-8', 'replace')
        if rc == 0:
            self.violate('no-replica drill: rc=0 with partition 1 '
                         'dead')
        elif 'Traceback' in text or 'dn:' not in text:
            self.violate('no-replica drill: unclean failure: %r'
                         % text[-300:])
        elif header.get('stats', {}).get('missing_partitions') \
                != [1]:
            self.violate('no-replica drill: missing partition not '
                         'named: %r' % header.get('stats'))
        elif not header.get('retryable'):
            self.violate('no-replica drill: degraded response not '
                         'retryable')
        else:
            self.clean_errors += 1

    def summary(self):
        doc = super(ClusterSoak, self).summary()
        doc['cluster'] = getattr(self, 'cluster_counters', {})
        return doc


# router/member/transport chaos for the cluster drill: dispatch and
# merge faults surface the degraded contract, health faults churn the
# breakers (probes + half-open recovery), transport faults drive
# failover and the client retry loop
CLUSTER_SPEC = ('router.dispatch:error:0.04:41,'
                'router.merge:error:0.02:42,'
                'member.health:error:0.15:43,'
                'client.connect:error:0.06:44,'
                'client.recv:error:0.05:45,'
                'serve.accept:error:0.05:46,'
                'serve.write:error:0.04:47')
CLUSTER_DELAY_SPEC = ('router.dispatch:delay:0.3:48,'
                      'iq.shard_read:delay:0.2:49')


def soak_cluster(root, fast=False, verbose=True, floor=None):
    """The cluster drill under `root`; returns the summary dict."""
    mod_faults.reset()
    ctx = make_corpus(root, n=400 if fast else 1200,
                      days=5 if fast else 10)
    for fmt in FORMATS:
        build(ctx, fmt)
    # router knobs for churn: fast probes, small breaker thresholds,
    # hedging ON so delay faults exercise the hedge path (read at
    # server construction)
    os.environ.update({
        'DN_ROUTER_PROBE_MS': '200', 'DN_ROUTER_FAILURES': '2',
        'DN_ROUTER_COOLDOWN_MS': '500', 'DN_ROUTER_HEDGE_MS': '40',
        'DN_ROUTER_FETCH_TIMEOUT_S': '30',
        # fleet observability under the drill: the event journal
        # (in-process members + the SIGKILL-able subprocess inherit
        # it) plus armed-but-silent tracing so journal entries carry
        # trace ids (DN_SLOW_MS high enough that the slow log itself
        # never fires)
        'DN_EVENTS': '4096', 'DN_SLOW_MS': '86400000',
        'DN_SERVE_FLEET_TIMEOUT_S': '5'})
    s = ClusterSoak(ctx, verbose=verbose)
    s.start_cluster()
    try:
        s.note('fault-free routed byte-identity round')
        s.routed_rounds('', 1, degraded_ok=False)
        rounds = 3 if fast else 12
        s.note('armed routed rounds (%d) [%s]'
               % (rounds, CLUSTER_SPEC))
        s.routed_rounds(CLUSTER_SPEC, rounds)
        s.note('delay + hedge rounds')
        s.routed_rounds(CLUSTER_DELAY_SPEC, 1 if fast else 2)
        s.note('degraded header drill')
        s.degraded_header_drill()
        if floor:
            extra = 0
            while extra < 60:
                total = mod_vpipe.global_counters().get(
                    'faults injected', 0)
                if total >= floor:
                    break
                extra += 1
                s.note('top-up round %d (%d/%d faults)'
                       % (extra, total, floor))
                s.routed_rounds(CLUSTER_SPEC, 1)
        s.note('SIGKILL partition-owner drill')
        s.kill_owner_drill(nthreads=2 if fast else 3,
                           per_thread=2 if fast else 4)
        s.note('fleet observability drill (member b dead)')
        s.fleet_obs_drill()
        s.note('no-surviving-replica drill')
        s.no_replica_drill()
    finally:
        s.stop_cluster()
    return s.summary()


# -- standing-query drill (dn subscribe flood) ------------------------------

# faults armed while publishes land and pushes fan out: torn push
# frames (the subscriber must detect the short frame and resume from
# its last acked token), failed push writes, and client-side read
# chaos on the subscriber connections
SUBSCRIBE_SPEC = ('serve.push_torn:error:0.25:91,'
                  'serve.write:error:0.08:92,'
                  'client.recv:error:0.05:93')


class _SubReader(threading.Thread):
    """One standing-query subscriber: a dedicated push connection
    whose frames are acked by the client loop, resumed with the last
    frame's token after torn frames or transport faults, and whose
    latest payload is what the quiescent byte-identity checks
    compare against a poll."""

    def __init__(self, sock, req, fmt):
        super(_SubReader, self).__init__(daemon=True)
        self.sock = sock
        self.req = req
        self.fmt = fmt
        self.lock = threading.Lock()
        self.latest = None
        self.frames = 0
        self.resumes = 0
        self.stream_errors = 0
        self.hard_errors = []
        self.stop_ev = threading.Event()

    def run(self):
        resume = None
        failures = 0
        while not self.stop_ev.is_set():
            stream = mod_client.subscribe_stream(
                self.sock, dict(self.req), resume=resume)
            try:
                for fr in stream:
                    with self.lock:
                        self.latest = fr['payload']
                        self.frames += 1
                    resume = (fr['token'], fr['payload'])
                    failures = 0
                return          # 'end' frame: the member drained
            except DNError as e:
                # a torn push, a faulted write, or read chaos: the
                # stream dies CLEANLY and the resume token skips the
                # reseed (RemoteTransportError is a DNError)
                self.stream_errors += 1
                failures += 1
                if failures > 10:
                    self.hard_errors.append(
                        'gave up after %d stream failures: %r'
                        % (failures, e))
                    return
                if resume is not None:
                    self.resumes += 1
                time.sleep(0.05 * failures)
            except Exception as e:
                self.hard_errors.append(repr(e))
                return
            finally:
                try:
                    stream.close()
                except Exception:
                    pass


class SubscribeSoak(ClusterSoak):
    """The standing-query drill: a `dn subscribe` flood over the
    3-member cluster (members a/c in-process, member b the
    SIGKILL-able subprocess) while publishes land under armed
    push/transport faults.  The contract: at every quiescent epoch
    each subscriber's latest pushed payload is BYTE-IDENTICAL to a
    `dn query --remote` poll, a SIGKILLed publisher leaves a tree
    the next build converges (subscribers re-converge, zero torn
    shards), a SIGKILLed subscriber is shed without delaying the
    healthy flood, and nothing ever wedges."""

    def __init__(self, ctx, fast=False, verbose=True):
        super(SubscribeSoak, self).__init__(ctx, verbose=verbose)
        self.fast = fast
        self.readers = []
        self.cli_sub = None
        self.cli_out = None
        self.cli_seed = None
        self.sub_counters = {}

    # -- flood lifecycle ----------------------------------------------

    def sub_req(self, fmt):
        return {'op': 'subscribe', 'ds': self.ctx['ds'][fmt],
                'config': self.ctx['rc_path'], 'interval': 'day',
                'queryconfig': {'breakdowns': [
                    {'name': 'host', 'field': 'host'}]},
                'opts': {}}

    def start_flood(self):
        per = 1 if self.fast else 2
        for m in 'abc':
            for fmt in FORMATS:
                for _ in range(per):
                    rd = _SubReader(self.socks[m],
                                    self.sub_req(fmt), fmt)
                    rd.start()
                    self.readers.append(rd)
        deadline = time.time() + 60
        for rd in self.readers:
            while time.time() < deadline:
                with rd.lock:
                    if rd.latest is not None:
                        break
                time.sleep(0.05)
            else:
                self.violate('subscribe flood: a reader on %s '
                             'never received its seed frame'
                             % rd.sock)

    def start_cli_subscriber(self):
        """`dn subscribe` as a real subprocess against member b —
        the JSONL stream the subscriber SIGKILL drill tears down."""
        fmt = FORMATS[0]
        self.cli_seed = self.poll(fmt)
        self.cli_out = open(os.path.join(self.ctx['root'],
                                         'sub_cli.jsonl'), 'wb')
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        env.pop('DN_FAULTS', None)
        self.cli_sub = subprocess.Popen(
            [sys.executable, os.path.join(REPO_ROOT, 'bin', 'dn.py'),
             'subscribe', '--remote', self.socks['b'],
             '-b', 'host', self.ctx['ds'][fmt]],
            env=env, stdout=self.cli_out,
            stderr=subprocess.DEVNULL)
        # wait for the seed line so the registration happens at THIS
        # quiescent epoch — the seed-vs-poll identity check depends
        # on no publish racing the subprocess startup
        deadline = time.time() + 60
        while time.time() < deadline:
            if os.path.getsize(self.cli_out.name) > 0:
                return
            time.sleep(0.1)
        self.violate('subscribe: CLI subscriber never emitted its '
                     'seed frame')

    def stop_flood(self):
        for rd in self.readers:
            rd.stop_ev.set()
        if self.cli_sub is not None and self.cli_sub.poll() is None:
            self.cli_sub.kill()
            self.cli_sub.wait()
        if self.cli_out is not None:
            self.cli_out.close()
        # stopping the members drains every group: subscribers get a
        # final 'end' frame, so every reader generator exhausts —
        # a reader still alive after that is a wedge
        self.stop_cluster()
        for rd in self.readers:
            rd.join(30)
            if rd.is_alive():
                self.violate('subscribe: reader on %s wedged '
                             '(never exited after the drain)'
                             % rd.sock)

    # -- publishes + identity -----------------------------------------

    def publish_round(self, n, spec=None):
        """Append + rebuild both formats while `spec` is armed, then
        hold the faults through the coalesce window so the push
        fan-out itself runs under chaos."""
        prior = os.environ.get('DN_FAULTS')
        if spec:
            os.environ['DN_FAULTS'] = spec
        try:
            start = self.ctx['n']
            gen_data(self.ctx['datafile'], n, start=start,
                     days=self.ctx['days'])
            self.ctx['n'] += n
            for fmt in FORMATS:
                rc, out, err = run_cli(
                    ['build', self.ctx['ds'][fmt]],
                    env={'DN_INDEX_FORMAT': fmt})
                if rc != 0:
                    self.violate('subscribe: publish build (%s) '
                                 'failed: %r' % (fmt, err[-200:]))
            if spec:
                time.sleep(0.6)     # pushes land while armed
        finally:
            if prior is None:
                os.environ.pop('DN_FAULTS', None)
            else:
                os.environ['DN_FAULTS'] = prior

    def _try_poll(self, fmt):
        rc, out, err = run_cli(['query', '--remote',
                                self.socks['a'], '-b', 'host',
                                self.ctx['ds'][fmt]])
        return out if rc == 0 else None

    def poll(self, fmt):
        err = b''
        for _ in range(3):
            out = self._try_poll(fmt)
            if out is not None:
                return out
            time.sleep(0.2)
        self.violate('subscribe: identity poll (%s) failed' % fmt)
        return None

    def settle_identity(self, label, timeout_s=45.0):
        """The pinned contract at a quiescent epoch: every
        subscriber's latest pushed payload and a poll converge to
        EXACTLY the same bytes — never a hang, never divergent
        bytes.  The poll is re-taken while waiting: a poll fired
        inside the post-publish window can coalesce onto a compute
        that began mid-publish and legitimately carry bytes one
        frame behind the committed tree."""
        deadline = time.time() + timeout_s
        pending = list(self.readers)
        golden = {}
        while True:
            for fmt in FORMATS:
                got = self._try_poll(fmt)
                if got is not None:
                    golden[fmt] = got
            pending = [
                rd for rd in pending
                if golden.get(rd.fmt) is None or
                rd.latest != golden[rd.fmt]]
            if not pending or time.time() >= deadline:
                break
            time.sleep(0.25)
        self.ops += len(self.readers)
        for fmt in FORMATS:
            if golden.get(fmt) is None:
                self.violate('subscribe [%s]: identity poll (%s) '
                             'kept failing' % (label, fmt))
        for rd in pending:
            if golden.get(rd.fmt) is None:
                continue
            if rd.hard_errors:
                self.violate('subscribe [%s]: reader on %s died: %s'
                             % (label, rd.sock, rd.hard_errors[-1]))
            else:
                with rd.lock:
                    latest = rd.latest
                    frames = rd.frames
                self.violate('subscribe [%s]: pushed payload '
                             '(%s via %s) never converged to the '
                             'polled bytes (alive=%r frames=%d '
                             'got=%r want=%r)'
                             % (label, rd.fmt, rd.sock,
                                rd.is_alive(), frames,
                                (latest or b'')[:200],
                                golden[rd.fmt][:200]))

    # -- drills -------------------------------------------------------

    def kill_publisher_drill(self):
        """SIGKILL a `dn build` subprocess mid-publish: the next
        clean build must converge the tree (recovery sweep, zero
        torn shards) and every subscriber must re-converge to the
        committed bytes."""
        fmt = FORMATS[0]
        start = self.ctx['n']
        gen_data(self.ctx['datafile'], 400, start=start,
                 days=self.ctx['days'])
        self.ctx['n'] += 400
        env = dict(os.environ, JAX_PLATFORMS='cpu',
                   DN_INDEX_FORMAT=fmt)
        env.pop('DN_FAULTS', None)
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO_ROOT, 'bin', 'dn.py'),
             'build', self.ctx['ds'][fmt]],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        time.sleep(0.4)         # let shard flushes get in flight
        proc.kill()
        proc.wait()
        self.note('SIGKILLed publisher mid-build')
        for f2 in FORMATS:
            build(self.ctx, f2)
        for f2 in FORMATS:
            litter = tree_tmp_litter(self.ctx['idx'][f2])
            if litter:
                self.violate('subscribe publisher kill: torn '
                             'shards (%s): %s' % (f2, litter))
        self.settle_identity('post-publisher-kill')

    def kill_subscriber_drill(self):
        """SIGKILL the CLI subscriber mid-stream: member b must shed
        the dead subscription, its JSONL prefix must be well-formed
        with a seq-1 seed frame byte-identical to the registration
        poll, and the healthy flood must keep converging."""
        before = self.active_subs(self.socks['b'])
        self.cli_sub.kill()
        self.cli_sub.wait()
        self.note('SIGKILLed CLI subscriber mid-stream')
        self.check_cli_stream()
        deadline = time.time() + 20
        after = before
        while time.time() < deadline:
            after = self.active_subs(self.socks['b'])
            if before is not None and after is not None and \
                    after < before:
                break
            time.sleep(0.2)
        self.ops += 1
        if not (before is not None and after is not None and
                after < before):
            self.violate('subscribe: member b never shed the '
                         'SIGKILLed subscriber (active %r -> %r)'
                         % (before, after))
        self.publish_round(60)
        self.settle_identity('post-subscriber-kill')

    def check_cli_stream(self):
        self.cli_out.flush()
        self.ops += 1
        with open(self.cli_out.name, 'rb') as f:
            lines = f.read().splitlines()
        if not lines:
            self.violate('subscribe: CLI subscriber emitted no '
                         'frames before the kill')
            return
        try:
            docs = [json.loads(ln.decode('utf-8')) for ln in lines]
        except ValueError:
            self.violate('subscribe: malformed CLI subscriber '
                         'JSONL: %r' % lines[-1][-200:])
            return
        if docs[0].get('seq') != 1 or docs[0].get('kind') != 'full':
            self.violate('subscribe: CLI stream did not start with '
                         'the seq-1 seed frame: %r'
                         % {k: docs[0].get(k)
                            for k in ('seq', 'kind')})
        elif self.cli_seed is not None and \
                docs[0].get('payload') != \
                self.cli_seed.decode('utf-8'):
            self.violate('subscribe: CLI seed frame diverges from '
                         'the polled bytes')

    # -- observability ------------------------------------------------

    def active_subs(self, sock):
        try:
            doc = mod_client.stats(sock, timeout_s=30.0)
        except Exception:
            return None
        return (doc.get('subscriptions') or {}).get('active')

    def fleet_obs_check(self):
        """`dn stats --cluster` must carry the merged subscriber
        count (honest absence would mean a member lost its
        manager)."""
        self.ops += 1
        rc, out, err = run_cli(['stats', '--cluster', '--remote',
                                self.socks['a']])
        if rc != 0:
            self.violate('subscribe: dn stats --cluster failed: %r'
                         % err[-200:])
            return
        try:
            doc = json.loads(out.decode('utf-8'))
        except ValueError:
            self.violate('subscribe: malformed fleet doc')
            return
        agg = (doc.get('aggregate') or {}).get('subscriptions')
        if agg is None or agg < len(self.readers):
            self.violate('subscribe: fleet doc merges %r active '
                         'subscriptions; flood holds %d'
                         % (agg, len(self.readers)))

    def collect_counters(self):
        agg = {}
        for sock in self.socks.values():
            try:
                doc = mod_client.stats(sock, timeout_s=10.0)
            except Exception:
                continue
            counters = ((doc.get('subscriptions') or {})
                        .get('counters')) or {}
            for k, v in counters.items():
                agg[k] = agg.get(k, 0) + (v or 0)
        self.sub_counters = agg
        if agg.get('pushes', 0) < len(self.readers):
            self.violate('subscribe: push counters never moved: %r'
                         % agg)

    def summary(self):
        doc = super(SubscribeSoak, self).summary()
        doc['subscribe'] = {
            'counters': self.sub_counters,
            'readers': len(self.readers),
            'frames': sum(r.frames for r in self.readers),
            'stream_errors': sum(r.stream_errors
                                 for r in self.readers),
            'resumes': sum(r.resumes for r in self.readers),
        }
        return doc


def soak_subscribe(root, fast=False, verbose=True, floor=None):
    """The standing-query drill under `root`; returns the summary."""
    mod_faults.reset()
    ctx = make_corpus(root, n=400 if fast else 1200,
                      days=5 if fast else 10)
    for fmt in FORMATS:
        build(ctx, fmt)
    # fast sweep cadence so publishes push inside the drill's
    # timeouts; the subprocess member and CLI subscriber inherit the
    # knobs from the environment
    os.environ.update({
        'DN_SUB_COALESCE_MS': '50', 'DN_SUB_MAX': '64',
        'DN_SUB_QUEUE_DEPTH': '8',
        'DN_ROUTER_PROBE_MS': '200', 'DN_ROUTER_FAILURES': '2',
        'DN_ROUTER_COOLDOWN_MS': '500',
        'DN_ROUTER_FETCH_TIMEOUT_S': '30',
        'DN_SERVE_FLEET_TIMEOUT_S': '5'})
    s = SubscribeSoak(ctx, fast=fast, verbose=verbose)
    s.start_cluster()
    try:
        s.note('subscriber flood (%d in-process readers + 1 CLI '
               'subscriber)' % (6 if fast else 12))
        s.start_flood()
        s.settle_identity('seed')
        s.start_cli_subscriber()
        s.note('fault-free publish round')
        s.publish_round(120)
        s.settle_identity('fault-free publish')
        rounds = 3 if fast else 8
        s.note('armed publish rounds (%d) [%s]'
               % (rounds, SUBSCRIBE_SPEC))
        for _ in range(rounds):
            s.publish_round(80, spec=SUBSCRIBE_SPEC)
        s.settle_identity('armed publishes')
        if floor:
            extra = 0
            while extra < 60:
                total = mod_vpipe.global_counters().get(
                    'faults injected', 0)
                if total >= floor:
                    break
                extra += 1
                s.note('top-up round %d (%d/%d faults)'
                       % (extra, total, floor))
                s.publish_round(40, spec=SUBSCRIBE_SPEC)
            s.settle_identity('top-up')
        s.note('fleet observability check')
        s.fleet_obs_check()
        s.note('SIGKILL publisher drill')
        s.kill_publisher_drill()
        s.note('SIGKILL subscriber drill')
        s.kill_subscriber_drill()
        s.collect_counters()
    finally:
        s.stop_flood()
    return s.summary()


# -- overload drill (multi-tenant flood at ~5x capacity) --------------------

# faults armed during the flood: torn v2 response frames, per-request
# stalls, and injected tenant-flood rejections — the protocol/overload
# seams this drill exists to prove out (plus a little transport chaos)
OVERLOAD_SPEC = ('serve.frame_torn:error:0.02:71,'
                 'serve.stall:delay:0.12:72,'
                 'tenant.flood:error:0.03:73')


class OverloadSoak(ClusterSoak):
    """Multi-tenant flood at ~5x capacity against the 3-member
    cluster (member b the SIGKILL-able subprocess), tenant weights
    alpha:3 beta:1, torn-frame/stall/flood faults armed, one SIGKILL
    mid-flood.  The contract: every request RESOLVES inside
    deadline + grace (no hangs), accepted responses are
    byte-identical to the fault-free golden, rejections are clean
    retryable errors (busy/overloaded ones carrying retry_after_ms),
    and per-tenant completion ratios land within 2x of the
    configured weights."""

    TENANT_WEIGHTS = {'alpha': 3, 'beta': 1}
    MAX_INFLIGHT = 2
    OP_GRACE_S = 30.0       # per-op resolve bound (deadline + grace)

    def start_cluster(self):
        root = self.ctx['root']
        self.socks = {m: os.path.join(root, 'dn-%s.sock' % m)
                      for m in 'abc'}
        self.topo_path = os.path.join(root, 'topo.json')
        with open(self.topo_path, 'w') as f:
            json.dump({
                'epoch': 1, 'assign': 'hash',
                'members': {m: {'endpoint': self.socks[m]}
                            for m in 'abc'},
                'partitions': [
                    {'id': 0, 'replicas': ['a', 'b']},
                    {'id': 1, 'replicas': ['b', 'c']},
                    {'id': 2, 'replicas': ['c', 'a']},
                ],
            }, f)
        from dragnet_tpu.serve import topology as mod_topology
        weights_spec = ','.join(
            '%s:%d' % (n, w)
            for n, w in sorted(self.TENANT_WEIGHTS.items()))
        # capacity is deliberately TINY (the flood must be ~5x it);
        # coalescing is off so identical flood queries cannot share
        # one execution and fake infinite capacity
        conf = {'max_inflight': self.MAX_INFLIGHT, 'queue_depth': 10,
                'deadline_ms': 0, 'coalesce': False, 'drain_s': 10,
                'tenant_quota': 4,
                'tenant_weights': dict(self.TENANT_WEIGHTS)}
        # member b (subprocess) reads the same knobs from env
        os.environ.update({
            'DN_SERVE_MAX_INFLIGHT': str(self.MAX_INFLIGHT),
            'DN_SERVE_QUEUE_DEPTH': '10',
            'DN_SERVE_COALESCE': '0',
            'DN_SERVE_TENANT_QUOTA': '4',
            'DN_SERVE_TENANT_WEIGHTS': weights_spec})
        for m in 'ac':
            topo = mod_topology.load_topology(self.topo_path,
                                              member=m)
            self.servers[m] = mod_server.DnServer(
                socket_path=self.socks[m], conf=dict(conf),
                cluster=topo, member=m).start()
        self.spawn_b()

    # -- the flood ----------------------------------------------------

    def flood_docs(self, fmt):
        """Request documents paired with the CLI case whose golden
        bytes an accepted response must match."""
        ds = self.ctx['ds'][fmt]
        return [
            (tuple(['query', '-b', 'host', ds]),
             {'op': 'query', 'ds': ds,
              'config': self.ctx['rc_path'], 'interval': 'day',
              'queryconfig': {'breakdowns': [
                  {'name': 'host', 'field': 'host'}]},
              'opts': {}}),
            (tuple(['query', '-b', 'host,latency[aggr=quantize]',
                    '--raw', ds]),
             {'op': 'query', 'ds': ds,
              'config': self.ctx['rc_path'], 'interval': 'day',
              'queryconfig': {'breakdowns': [
                  {'name': 'host', 'field': 'host'},
                  {'name': 'latency', 'field': 'latency',
                   'aggr': 'quantize'}]},
              'opts': {'raw': True}}),
        ]

    def verify_doc_equivalence(self, fmt):
        """Prove (fault-free) that each flood document's routed bytes
        equal the golden CLI bytes — the flood's byte checks then
        compare against the same goldens."""
        for case, doc in self.flood_docs(fmt):
            rc, hd, out, err = mod_client.request_bytes(
                self.socks['a'], dict(doc), timeout_s=60.0,
                pooled=True)
            self.ops += 1
            gold = self.golden[(fmt, case)]
            if rc != 0 or out != gold[1]:
                self.violate('flood doc %s: fault-free routed bytes '
                             'diverge from golden (rc=%d)'
                             % (' '.join(case), rc))

    def flood(self, seconds, kill_at_s=None, fmt='dnc'):
        """`seconds` of sustained flood: tenants alpha/beta 8 threads
        each, gamma 4 (~20 concurrent vs capacity 2x3 members = ~5x
        when >= half the member slots serve partials), every request
        carrying tenant + deadline_ms; optional SIGKILL of member b
        at `kill_at_s`."""
        import threading
        docs = self.flood_docs(fmt)
        counts = {t: {'completed': 0, 'shed': 0, 'transport': 0}
                  for t in ('alpha', 'beta', 'gamma')}
        lock = threading.Lock()
        stop_at = time.monotonic() + seconds
        slowest = [0.0]

        def worker(tenant, tid):
            i = 0
            while time.monotonic() < stop_at:
                case, doc = docs[(tid + i) % len(docs)]
                i += 1
                via = self.socks['a' if (tid + i) % 2 else 'c']
                req = dict(doc, tenant=tenant, deadline_ms=20000)
                t0 = time.monotonic()
                try:
                    rc, hd, out, err = mod_client.request_bytes(
                        via, req, timeout_s=self.OP_GRACE_S + 15,
                        pooled=True)
                except (OSError, ValueError, DNError):
                    # torn frames / broken pooled conns: a resolved,
                    # clean transport failure — retry-safe, not a
                    # violation
                    with lock:
                        counts[tenant]['transport'] += 1
                        self.ops += 1
                        slowest[0] = max(slowest[0],
                                         time.monotonic() - t0)
                    continue
                dt = time.monotonic() - t0
                with lock:
                    self.ops += 1
                    slowest[0] = max(slowest[0], dt)
                if dt > self.OP_GRACE_S:
                    self.violate('flood: request took %.1fs '
                                 '(> deadline + grace)' % dt)
                if rc == 0:
                    gold = self.golden[(fmt, case)]
                    if out != gold[1]:
                        self.violate('flood: accepted request with '
                                     'divergent bytes (%s)'
                                     % ' '.join(case))
                    with lock:
                        counts[tenant]['completed'] += 1
                    continue
                text = err.decode('utf-8', 'replace')
                if 'Traceback' in text or 'dn:' not in text:
                    self.violate('flood: unclean rejection: %r'
                                 % text[-300:])
                    continue
                if not hd.get('retryable'):
                    self.violate('flood: non-retryable rejection '
                                 'under overload: %r' % text[-200:])
                    continue
                if ('busy' in text or 'overloaded' in text) and \
                        hd.get('retry_after_ms') is None:
                    self.violate('flood: busy/overloaded rejection '
                                 'without retry_after_ms')
                    continue
                with lock:
                    counts[tenant]['shed'] += 1
                    self.clean_errors += 1

        threads = []
        for tenant, n in (('alpha', 10), ('beta', 10), ('gamma', 4)):
            for tid in range(n):
                t = threading.Thread(target=worker,
                                     args=(tenant, tid), daemon=True)
                threads.append(t)
                t.start()
        if kill_at_s is not None:
            time.sleep(kill_at_s)
            self.proc_b.kill()
            self.proc_b.wait()
            self.note('SIGKILLed member b mid-flood')
        for t in threads:
            t.join(seconds + self.OP_GRACE_S + 30)
            if t.is_alive():
                self.violate('flood: worker thread hung')
        return counts

    def check_fairness(self, counts):
        """Completion ratio alpha:beta within 2x of the 3:1 weights
        (both tenants issued identical demand)."""
        a = counts['alpha']['completed']
        b = counts['beta']['completed']
        shed = sum(c['shed'] for c in counts.values())
        self.note('flood counts: %s (total shed %d)'
                  % (counts, shed))
        if shed == 0:
            self.violate('flood never saturated the cluster: no '
                         'request was shed at ~5x capacity')
        if b < 3:
            # too few completions to measure a ratio honestly: the
            # flood is misconfigured for this rig
            self.violate('flood: tenant beta completed only %d '
                         'request(s); fairness unmeasurable' % b)
            return
        want = (self.TENANT_WEIGHTS['alpha'] /
                float(self.TENANT_WEIGHTS['beta']))
        ratio = a / float(b)
        if not (want / 2.0 <= ratio <= want * 2.0):
            self.violate('fairness: alpha:beta completion ratio '
                         '%.2f outside 2x of configured %.1f'
                         % (ratio, want))
        else:
            self.note('fairness ok: alpha:beta %.2f (configured '
                      '%.1f)' % (ratio, want))
        self.flood_counts = counts

    def summary(self):
        doc = super(OverloadSoak, self).summary()
        doc['flood'] = getattr(self, 'flood_counts', {})
        return doc


def soak_overload(root, fast=False, verbose=True, floor=None):
    """The overload drill under `root`; returns the summary dict."""
    mod_faults.reset()
    ctx = make_corpus(root, n=400 if fast else 1200,
                      days=5 if fast else 10)
    for fmt in FORMATS:
        build(ctx, fmt)
    os.environ.update({
        'DN_ROUTER_PROBE_MS': '200', 'DN_ROUTER_FAILURES': '3',
        'DN_ROUTER_COOLDOWN_MS': '500', 'DN_ROUTER_HEDGE_MS': '0',
        'DN_ROUTER_FETCH_TIMEOUT_S': '30',
        'DN_REMOTE_RETRIES': '2', 'DN_REMOTE_BACKOFF_MS': '10',
        'DN_REMOTE_CONNECT_TIMEOUT_S': '5'})
    s = OverloadSoak(ctx, verbose=verbose)
    s.start_cluster()
    prior_faults = os.environ.get('DN_FAULTS')
    try:
        s.note('fault-free flood-doc byte-equivalence check')
        for fmt in FORMATS:
            s.verify_doc_equivalence(fmt)
        seconds = 12 if fast else 30
        os.environ['DN_FAULTS'] = OVERLOAD_SPEC
        mod_faults.reset()
        s.note('multi-tenant flood (%ds, ~5x capacity, faults '
               'armed [%s], SIGKILL of b mid-flood)'
               % (seconds, OVERLOAD_SPEC))
        counts = s.flood(seconds, kill_at_s=seconds / 2.0)
        os.environ.pop('DN_FAULTS', None)
        mod_faults.reset()
        s.check_fairness(counts)
        s.note('post-flood fault-free byte-identity round (b dead, '
               'replicas serve)')
        for fmt in FORMATS:
            s.verify_doc_equivalence(fmt)
    finally:
        if prior_faults is None:
            os.environ.pop('DN_FAULTS', None)
        else:
            os.environ['DN_FAULTS'] = prior_faults
        s.stop_cluster()
    return s.summary()


# -- dynamic-topology (live resize) drill ------------------------------------

# armed while the cluster resizes under flood: handoff fetch/manifest
# failures (the joiner must retry/fail over), topology-poll failures
# (a member must keep serving its last good map), plus transport
# chaos on the routed path
REBALANCE_SPEC = ('handoff.fetch:error:0.12:81,'
                  'handoff.manifest:error:0.08:82,'
                  'topo.poll:error:0.15:83,'
                  'client.connect:error:0.03:84,'
                  'serve.write:error:0.03:85')


class RebalanceSoak(ClusterSoak):
    """Live-resize drill: a serving cluster grows 3 -> 5 members and
    shrinks 5 -> 2 under sustained routed-query flood with handoff/
    topology faults armed, a joiner SIGKILLed mid-handoff (restarted,
    re-pulls idempotently), and a donor SIGKILLed mid-flood.  The
    joiners own PRIVATE index trees that start EMPTY — their shards
    genuinely stream from the committed owners.  Contract: zero
    byte-diffs vs the single-process goldens on every accepted
    response, zero dropped partitions (full-query byte-identity
    proves every partition served), zero hangs."""

    POLL_MS = '150'

    def __init__(self, ctx, verbose=True):
        super(RebalanceSoak, self).__init__(ctx, verbose=verbose)
        self.procs = {}          # subprocess members: name -> Popen
        self.member_rc = {}      # per-member config paths (joiners)
        self.flood_results = []
        self.flood_stop = None
        self.flood_threads = []

    # -- lifecycle ----------------------------------------------------

    def write_member_rc(self, name):
        """A joiner's private config: the shared datasources
        re-pointed at empty per-member index trees."""
        with open(self.ctx['rc_path'], 'r') as f:
            doc = json.load(f)
        for ds in doc.get('datasources', []):
            bc = ds.get('backend_config') or {}
            if bc.get('indexPath'):
                bc['indexPath'] = os.path.join(
                    self.ctx['root'],
                    'idx_%s_%s' % (ds['name'], name))
        path = os.path.join(self.ctx['root'], 'rc_%s.json' % name)
        with open(path, 'w') as f:
            json.dump(doc, f)
        self.member_rc[name] = path
        return path

    def start_cluster(self):
        root = self.ctx['root']
        self.socks = {m: os.path.join(root, 'dn-%s.sock' % m)
                      for m in 'abcde'}
        self.topo_path = os.path.join(root, 'topo.json')
        from dragnet_tpu.serve import coordinator as mod_coord
        mod_coord.publish_topology(self.topo_path, {
            'epoch': 1, 'assign': 'hash',
            'members': {m: {'endpoint': self.socks[m]}
                        for m in 'abc'},
            'partitions': [
                {'id': 0, 'replicas': ['a', 'b']},
                {'id': 1, 'replicas': ['b', 'c']},
                {'id': 2, 'replicas': ['c', 'a']},
            ],
        })
        from dragnet_tpu.serve import topology as mod_topology
        conf = {'max_inflight': 8, 'queue_depth': 32,
                'deadline_ms': 0, 'coalesce': True, 'drain_s': 10}
        for m in 'ac':
            topo = mod_topology.load_topology(self.topo_path,
                                              member=m)
            self.servers[m] = mod_server.DnServer(
                socket_path=self.socks[m], conf=dict(conf),
                cluster=topo, member=m).start()
        self.spawn_member('b')

    def spawn_member(self, name, extra_env=None):
        if os.path.exists(self.socks[name]):
            os.unlink(self.socks[name])
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        env.pop('DN_FAULTS', None)
        env.update(extra_env or {})
        self.procs[name] = subprocess.Popen(
            [sys.executable, os.path.join(REPO_ROOT, 'bin', 'dn.py'),
             'serve', '--socket', self.socks[name],
             '--cluster', self.topo_path, '--member', name],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        deadline = time.time() + 30
        while time.time() < deadline:
            doc = mod_client.health(self.socks[name], timeout_s=2.0)
            if doc.get('ok'):
                return
            time.sleep(0.1)
        raise RuntimeError('member %s never became healthy' % name)

    def stop_cluster(self):
        for srv in self.servers.values():
            try:
                srv.stop()
            except Exception:
                pass
        self.servers = {}
        for proc in self.procs.values():
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()
        self.procs = {}

    # -- the flood ----------------------------------------------------

    def start_flood(self, nthreads=3):
        import threading
        self.flood_stop = threading.Event()
        self.flood_results = []
        lock = threading.Lock()

        def worker(tid):
            i = 0
            while not self.flood_stop.is_set():
                fmt = FORMATS[(tid + i) % len(FORMATS)]
                ds = self.ctx['ds'][fmt]
                cases = query_cases(ds)
                case = cases[(tid + i) % len(cases)]
                i += 1
                got = run_cli(case[:1] +
                              ['--remote', self.socks['a']] +
                              case[1:])
                with lock:
                    self.flood_results.append((fmt, case, got))

        self.flood_threads = [
            threading.Thread(target=worker, args=(t,), daemon=True)
            for t in range(nthreads)]
        for t in self.flood_threads:
            t.start()

    def stop_flood(self):
        self.flood_stop.set()
        for t in self.flood_threads:
            t.join(120)
            if t.is_alive():
                self.violate('resize flood: query thread hung')
        for fmt, case, got in self.flood_results:
            self.check_routed(fmt, case, got)
        self.note('flood: %d routed queries checked'
                  % len(self.flood_results))
        self.flood_threads = []

    # -- epoch helpers ------------------------------------------------

    def wait_epoch(self, names, epoch, timeout_s=30.0):
        """Every named member reports `epoch` committed (the watcher
        cadence propagates commits asynchronously)."""
        deadline = time.time() + timeout_s
        lag = list(names)
        while time.time() < deadline and lag:
            lag = []
            for name in names:
                try:
                    doc = mod_client.stats(self.socks[name],
                                           timeout_s=10.0)
                    if (doc.get('topology') or {}).get('epoch') \
                            != epoch:
                        lag.append(name)
                except Exception:
                    lag.append(name)
            if lag:
                time.sleep(0.2)
        if lag:
            self.violate('members %s never reached epoch %d'
                         % (','.join(lag), epoch))

    def resize(self, new_doc, joiners=(), ready_timeout_s=90.0,
               kill_joiner=None):
        """One transition: publish pending, (optionally) SIGKILL a
        subprocess joiner mid-handoff and restart it, wait for
        readiness, commit."""
        from dragnet_tpu.serve import coordinator as mod_coord
        committed, pending = mod_coord.begin_transition(
            self.topo_path, new_doc)
        self.note('pending epoch %d published' % pending.epoch)
        if kill_joiner is not None:
            time.sleep(0.4)      # let its pull get in flight
            proc = self.procs[kill_joiner]
            proc.kill()
            proc.wait()
            self.note('SIGKILLed joiner %s mid-handoff'
                      % kill_joiner)
            # committed ownership is untouched: queries keep
            # answering byte-identically while the joiner is down
            ds = self.ctx['ds'][FORMATS[0]]
            case = query_cases(ds)[0]
            got = run_cli(case[:1] + ['--remote', self.socks['a']] +
                          case[1:])
            self.check_routed(FORMATS[0], case, got,
                              degraded_ok=False)
            self.spawn_member(kill_joiner)   # restart: re-pull
            self.note('restarted joiner %s' % kill_joiner)
        status = mod_coord.wait_ready(self.topo_path,
                                      timeout_s=ready_timeout_s,
                                      poll_s=0.25)
        if not status.get('ready'):
            self.violate('transition to epoch %d never became '
                         'ready: %s'
                         % (pending.epoch, json.dumps(status)))
            return None
        mod_coord.commit_transition(self.topo_path)
        self.note('epoch %d committed' % pending.epoch)
        return pending

    # -- summary ------------------------------------------------------

    def summary(self):
        doc = super(RebalanceSoak, self).summary()
        doc['rebalance'] = getattr(self, 'rebalance_doc', {})
        doc['handoff'] = getattr(self, 'handoff_doc', {})
        return doc


def soak_rebalance(root, fast=False, verbose=True, floor=None):
    """The live-resize drill under `root`; returns the summary
    dict."""
    mod_faults.reset()
    ctx = make_corpus(root, n=400 if fast else 1200,
                      days=5 if fast else 10)
    for fmt in FORMATS:
        build(ctx, fmt)
    os.environ.update({
        'DN_ROUTER_PROBE_MS': '200', 'DN_ROUTER_FAILURES': '3',
        'DN_ROUTER_COOLDOWN_MS': '500', 'DN_ROUTER_HEDGE_MS': '0',
        'DN_ROUTER_FETCH_TIMEOUT_S': '30',
        'DN_REMOTE_RETRIES': '3', 'DN_REMOTE_BACKOFF_MS': '10',
        'DN_REMOTE_CONNECT_TIMEOUT_S': '5',
        'DN_SERVE_CLIENT_TIMEOUT_S': '60',
        'DN_TOPO_POLL_MS': RebalanceSoak.POLL_MS,
        'DN_TOPO_HANDOFF_RETRIES': '3'})
    s = RebalanceSoak(ctx, verbose=verbose)
    s.start_cluster()
    prior_faults = os.environ.get('DN_FAULTS')
    from dragnet_tpu.serve import topology as mod_topology
    try:
        s.note('fault-free routed byte-identity round (epoch 1)')
        s.routed_rounds('', 1, degraded_ok=False)
        rc_d = s.write_member_rc('d')
        rc_e = s.write_member_rc('e')
        os.environ['DN_FAULTS'] = REBALANCE_SPEC
        mod_faults.reset()
        s.note('flood starts (faults armed [%s])' % REBALANCE_SPEC)
        s.start_flood(nthreads=2 if fast else 3)

        # -- grow 3 -> 5: d and e join with EMPTY private trees;
        # their shards stream from the committed owners.  e is a
        # subprocess, SIGKILLed mid-handoff and restarted.
        grow = {
            'assign': 'hash',
            'members': {
                'a': {'endpoint': s.socks['a']},
                'b': {'endpoint': s.socks['b']},
                'c': {'endpoint': s.socks['c']},
                'd': {'endpoint': s.socks['d'], 'config': rc_d},
                'e': {'endpoint': s.socks['e'], 'config': rc_e},
            },
            'partitions': [
                {'id': 0, 'replicas': ['a', 'b']},
                {'id': 1, 'replicas': ['d', 'e']},
                {'id': 2, 'replicas': ['c', 'd']},
            ],
        }
        # publish first so the joiners' startup path reads the
        # pending file (the fresh-joiner contract); slow e's fetches
        # so the SIGKILL lands mid-pull
        from dragnet_tpu.serve import coordinator as mod_coord
        committed, pending = mod_coord.begin_transition(
            s.topo_path, grow)
        s.note('pending epoch %d published (grow 3 -> 5)'
               % pending.epoch)
        topo_d, pend_d = mod_topology.load_topology_state(
            s.topo_path, member='d')
        s.servers['d'] = mod_server.DnServer(
            socket_path=s.socks['d'],
            conf={'max_inflight': 8, 'queue_depth': 32,
                  'deadline_ms': 0, 'coalesce': True,
                  'drain_s': 10},
            cluster=topo_d, member='d', pending=pend_d).start()
        s.spawn_member('e', extra_env={
            'DN_FAULTS': 'handoff.fetch:delay:1.0',
            'DN_FAULT_DELAY_MS': '120'})
        time.sleep(0.5)
        proc = s.procs['e']
        proc.kill()
        proc.wait()
        s.note('SIGKILLed joiner e mid-handoff')
        ds0 = ctx['ds'][FORMATS[0]]
        case = query_cases(ds0)[0]
        got = run_cli(case[:1] + ['--remote', s.socks['a']] +
                      case[1:])
        s.check_routed(FORMATS[0], case, got, degraded_ok=False)
        s.spawn_member('e')
        s.note('restarted joiner e (re-pulls idempotently)')
        status = mod_coord.wait_ready(s.topo_path,
                                      timeout_s=60 if fast else 120,
                                      poll_s=0.25)
        if not status.get('ready'):
            s.violate('grow transition never became ready: %s'
                      % json.dumps(status))
        else:
            mod_coord.commit_transition(s.topo_path)
            s.note('epoch 2 committed (5 members)')
        s.wait_epoch('abcde', 2)
        s.handoff_doc = (s.servers['d'].puller.status()
                         if s.servers['d'].puller else {})
        if not (s.handoff_doc.get('counters') or {}).get(
                'shards_streamed'):
            s.violate('joiner d streamed no shards into its empty '
                      'tree: %s' % json.dumps(s.handoff_doc))

        # -- the rebalance planner reads live member loads
        from dragnet_tpu.serve import rebalance as mod_rebalance
        topo_now = mod_topology.load_topology(s.topo_path)
        loads = mod_rebalance.collect_loads(topo_now, timeout_s=10.0)
        doc, decisions = mod_rebalance.propose_moves(topo_now, loads)
        s.rebalance_doc = {'loads': {k: v for k, v in loads.items()},
                           'decisions': decisions}
        s.note('rebalance planner: %d move(s) proposed'
               % len(decisions))

        # -- SIGKILL a donor mid-flood (partition 0 fails over to a)
        s.procs['b'].kill()
        s.procs['b'].wait()
        s.note('SIGKILLed member b (donor) mid-flood')

        # -- shrink 5 -> 2: only a and d remain; d pulls everything
        # it is missing (donors: the other committed owners)
        shrink = {
            'assign': 'hash',
            'members': {
                'a': {'endpoint': s.socks['a']},
                'd': {'endpoint': s.socks['d'], 'config': rc_d},
            },
            'partitions': [
                {'id': 0, 'replicas': ['a', 'd']},
                {'id': 1, 'replicas': ['d', 'a']},
                {'id': 2, 'replicas': ['a', 'd']},
            ],
        }
        if s.resize(shrink,
                    ready_timeout_s=90 if fast else 180) is not None:
            s.wait_epoch('ad', 3)

        s.stop_flood()
        os.environ.pop('DN_FAULTS', None)
        mod_faults.reset()

        # -- retire the departed members; a + d own the world
        s.servers['c'].stop()
        s.procs['e'].kill()
        s.procs['e'].wait()
        s.note('departed members stopped (c, e; b already dead)')
        s.note('final fault-free byte-identity via a and d')
        for via in 'ad':
            for fmt in FORMATS:
                ds = ctx['ds'][fmt]
                for case in query_cases(ds):
                    got = run_cli(case[:1] +
                                  ['--remote', s.socks[via]] +
                                  case[1:])
                    s.check_routed(fmt, case, got,
                                   degraded_ok=False)
        # topology telemetry reached /stats
        doc = mod_client.stats(s.socks['a'], timeout_s=30.0)
        topo_sec = doc.get('topology') or {}
        if topo_sec.get('epoch') != 3:
            s.violate('/stats topology epoch %r != 3'
                      % topo_sec.get('epoch'))
        if (topo_sec.get('counters') or {}).get('transitions', 0) \
                < 2:
            s.violate('/stats topology transitions < 2: %s'
                      % json.dumps(topo_sec.get('counters')))
        if floor:
            extra = 0
            while extra < 60:
                total = mod_vpipe.global_counters().get(
                    'faults injected', 0)
                if total >= floor:
                    break
                extra += 1
                os.environ['DN_FAULTS'] = REBALANCE_SPEC
                mod_faults.reset()
                s.note('top-up round %d (%d/%d faults)'
                       % (extra, total, floor))
                s.routed_rounds(REBALANCE_SPEC, 1)
                os.environ.pop('DN_FAULTS', None)
                mod_faults.reset()
    finally:
        if prior_faults is None:
            os.environ.pop('DN_FAULTS', None)
        else:
            os.environ['DN_FAULTS'] = prior_faults
        s.stop_cluster()
    return s.summary()


# -- shard-integrity (scrub/repair) drill -----------------------------------


class ScrubSoak(ClusterSoak):
    """The corruption drill (`--scrub` / `make soak-scrub`): a
    3-member cluster with PRIVATE byte-identical trees (topology
    members[].config), DN_VERIFY=open and a 1-second background
    scrub on every member.  The harness flips random bytes in
    committed shards across all three trees (the rot the integrity
    catalog exists to catch), floods routed queries, and asserts the
    acceptance contract: every accepted result byte-identical to the
    clean golden, every failure a clean retryable/degraded `dn:`
    error, and every injected corruption eventually repaired from a
    co-replica — byte-identity restored, verified against the
    catalog the donor's copy still satisfies.  Zero silently wrong
    result bytes."""

    def __init__(self, ctx, verbose=True):
        super(ScrubSoak, self).__init__(ctx, verbose=verbose)
        self.member_rc = {}
        self.flips = []          # (member, abspath, rel, (size, crc))
        self.flip_rng = None
        # each (dsname, rel) is corrupted on at most ONE member:
        # repair pulls from a committed co-replica, so flipping the
        # same shard on every replica of its partition manufactures
        # unrepairable loss — a real deployment's replicas fail
        # independently, and that independence is the redundancy the
        # integrity model explicitly leans on (docs/robustness.md)
        self._flipped_keys = set()

    def write_member_rc(self, name):
        """A member's private config: the shared datasources
        re-pointed at per-member COPIES of the built trees."""
        import shutil
        with open(self.ctx['rc_path'], 'r') as f:
            doc = json.load(f)
        for ds in doc.get('datasources', []):
            bc = ds.get('backend_config') or {}
            if bc.get('indexPath'):
                dst = os.path.join(
                    self.ctx['root'],
                    'idx_%s_%s' % (ds['name'], name))
                shutil.copytree(bc['indexPath'], dst)
                bc['indexPath'] = dst
        path = os.path.join(self.ctx['root'], 'rc_%s.json' % name)
        with open(path, 'w') as f:
            json.dump(doc, f)
        self.member_rc[name] = path
        return path

    def start_cluster(self):
        root = self.ctx['root']
        self.socks = {m: os.path.join(root, 'dn-%s.sock' % m)
                      for m in 'abc'}
        self.topo_path = os.path.join(root, 'topo.json')
        for m in 'abc':
            self.write_member_rc(m)
        with open(self.topo_path, 'w') as f:
            json.dump({
                'epoch': 1, 'assign': 'hash',
                'members': {m: {'endpoint': self.socks[m],
                                'config': self.member_rc[m]}
                            for m in 'abc'},
                'partitions': [
                    {'id': 0, 'replicas': ['a', 'b']},
                    {'id': 1, 'replicas': ['b', 'c']},
                    {'id': 2, 'replicas': ['c', 'a']},
                ],
            }, f)
        from dragnet_tpu.serve import topology as mod_topology
        conf = {'max_inflight': 8, 'queue_depth': 32,
                'deadline_ms': 0, 'coalesce': True, 'drain_s': 10}
        for m in 'ac':
            topo = mod_topology.load_topology(self.topo_path,
                                              member=m)
            self.servers[m] = mod_server.DnServer(
                socket_path=self.socks[m], conf=dict(conf),
                cluster=topo, member=m).start()
        self.spawn_b()

    def member_trees(self, member):
        """[(dsname, indexroot)] of one member's private trees."""
        with open(self.member_rc[member]) as f:
            doc = json.load(f)
        return [(d['name'], d['backend_config']['indexPath'])
                for d in doc['datasources']
                if (d.get('backend_config') or {}).get('indexPath')]

    def flip_round(self, per_member=2):
        """XOR one byte in `per_member` randomly chosen committed
        shards of every member's trees (deterministic RNG), recording
        the catalog entry each must be restored to."""
        from dragnet_tpu import integrity as mod_integrity
        for member in 'abc':
            trees = self.member_trees(member)
            for k in range(per_member):
                dsname = idx = rel = None
                for attempt in range(32):
                    dsname, idx = trees[self.flip_rng.randrange(
                        len(trees))]
                    catalog = mod_integrity.load_catalog(idx)
                    rels = sorted(catalog)
                    rel = rels[self.flip_rng.randrange(len(rels))]
                    if (dsname, rel) not in self._flipped_keys:
                        break
                else:
                    continue     # every candidate already in flight
                self._flipped_keys.add((dsname, rel))
                path = os.path.join(idx, rel)
                try:
                    size = os.path.getsize(path)
                    off = self.flip_rng.randrange(size)
                    mask = self.flip_rng.randrange(1, 256)
                    with open(path, 'r+b') as f:
                        f.seek(off)
                        byte = f.read(1)
                        f.seek(off)
                        f.write(bytes([byte[0] ^ mask]))
                except OSError:
                    continue     # already quarantined by a scrubber
                self.flips.append((member, path, rel, catalog[rel]))
        self.note('flipped %d shard bytes (total %d)'
                  % (3 * per_member, len(self.flips)))

    def wait_all_healed(self, timeout_s=120.0):
        """Every flipped shard must return to its catalog bytes — the
        repair path (read-detect or background scrub, pulling the
        good copy from a committed co-replica) closes the loop."""
        from dragnet_tpu import integrity as mod_integrity
        deadline = time.time() + timeout_s
        pending = list(self.flips)
        while pending and time.time() < deadline:
            still = []
            for member, path, rel, expected in pending:
                try:
                    if mod_integrity.file_crc(path) == \
                            tuple(expected):
                        continue
                except OSError:
                    pass          # quarantined; repair not landed yet
                still.append((member, path, rel, expected))
            pending = still
            if pending:
                time.sleep(0.5)
        for member, path, rel, expected in pending:
            self.violate('corruption never repaired: member %s '
                         'shard %s' % (member, rel))
        self.note('%d/%d corruptions repaired byte-identical'
                  % (len(self.flips) - len(pending),
                     len(self.flips)))
        return not pending

    def scrub_remote_clean(self, member):
        got = run_cli(['scrub', '--remote', self.socks[member]])
        rc, out, err = got
        self.ops += 1
        if rc != 0:
            self.violate('dn scrub --remote %s reported diffs on a '
                         'healed cluster: %s'
                         % (member, out.decode('utf-8',
                                               'replace')[:400]))
            return
        doc = json.loads(out.decode('utf-8'))
        for dsname, t in (doc.get('trees') or {}).items():
            if t.get('corrupt') or t.get('missing'):
                self.violate('member %s tree %s not clean after '
                             'repair: %s' % (member, dsname,
                                             json.dumps(t)))


def soak_scrub(root, fast=False, verbose=True, floor=None):
    """The corruption/self-healing drill under `root`; returns the
    summary dict."""
    import random
    mod_faults.reset()
    from dragnet_tpu import integrity as mod_integrity
    ctx = make_corpus(root, n=400 if fast else 1200,
                      days=5 if fast else 10)
    for fmt in FORMATS:
        build(ctx, fmt)
    os.environ.update({
        'DN_ROUTER_PROBE_MS': '200', 'DN_ROUTER_FAILURES': '3',
        'DN_ROUTER_COOLDOWN_MS': '500', 'DN_ROUTER_HEDGE_MS': '0',
        'DN_ROUTER_FETCH_TIMEOUT_S': '30',
        'DN_REMOTE_RETRIES': '3', 'DN_REMOTE_BACKOFF_MS': '10',
        'DN_REMOTE_CONNECT_TIMEOUT_S': '5',
        'DN_SERVE_CLIENT_TIMEOUT_S': '60',
        'DN_VERIFY': 'open', 'DN_SCRUB_INTERVAL_S': '1',
        'DN_SCRUB_RATE_MB_S': '0'})
    mod_integrity.reset_memo()
    s = ScrubSoak(ctx, verbose=verbose)
    s.flip_rng = random.Random(1234)
    s.start_cluster()
    try:
        s.note('fault-free routed byte-identity round '
               '(verify=open)')
        s.routed_rounds('', 1, degraded_ok=False)

        # -- corruption flood: flip committed bytes across all three
        # members' private trees, keep routed traffic flowing, and
        # demand byte-identical-or-clean on every single response
        flood_rounds = 4 if fast else 13
        for burst in range(2 if fast else 3):
            s.flip_round(per_member=1 if fast else 2)
            from dragnet_tpu import index_query_mt as mod_iqmt
            mod_iqmt.shard_cache_clear()   # the rot must be SEEN
            s.routed_rounds('', flood_rounds, degraded_ok=True)
        s.wait_all_healed(timeout_s=90 if fast else 180)

        # -- post-heal: byte identity restored on every router, and
        # an on-demand remote scrub reports zero diffs
        s.routed_rounds('', 2 if fast else 4, degraded_ok=False)
        for member in 'ac':
            s.scrub_remote_clean(member)

        # -- single-process leg: the flip FAULT KIND corrupts a
        # publish in flight (checksums rode the commit record first);
        # verified reads surface every one as a clean error, the
        # scrub quarantines the rest, `dn quarantine` prunes, and a
        # clean rebuild restores golden bytes
        s.note('single-process flip-fault leg')
        for fmt in FORMATS:
            ds = ctx['ds'][fmt]
            idx = ctx['idx'][fmt]
            rc, out, err = run_cli(
                ['build', ds],
                env={'DN_INDEX_FORMAT': fmt,
                     'DN_FAULTS': 'sink.rename:flip:0.6:21'})
            s.ops += 1
            if rc != 0:
                s.violate('%s: flip-armed build failed: %r'
                          % (fmt, err[-200:]))
            mod_faults.reset()
            from dragnet_tpu import index_query_mt as mod_iqmt
            mod_iqmt.shard_cache_clear()
            mod_integrity.reset_memo()
            got = run_cli(['query', '-b', 'host', ds],
                          env={'DN_INDEX_FORMAT': fmt})
            s.ops += 1
            rc, out, err = got
            text = err.decode('utf-8', 'replace')
            if rc == 0:
                # the draws may have spared every shard this build —
                # then bytes must equal the golden exactly
                gold = s.golden[(fmt, ('query', '-b', 'host', ds))]
                if out != gold[1]:
                    s.violate('%s: silently wrong bytes from a '
                              'flip-corrupted tree' % fmt)
            elif 'Traceback' in text or 'dn:' not in text:
                s.violate('%s: unclean corrupt-detect: %r'
                          % (fmt, text[-300:]))
            else:
                s.clean_errors += 1
            rc, out, err = run_cli(['scrub', '--tree', idx])
            s.ops += 1
            rc, out, err = run_cli(['scrub', '--tree', idx,
                                    '--forget-missing'])
            s.ops += 1
            rc, out, err = run_cli(['quarantine', 'clean',
                                    '--tree', idx])
            s.ops += 1
            if rc != 0:
                s.violate('%s: quarantine clean failed: %r'
                          % (fmt, err[-200:]))
            # clean rebuild: golden bytes and a clean scrub again
            build(ctx, fmt)
            mod_iqmt.shard_cache_clear()
            mod_integrity.reset_memo()
            got = run_cli(['query', '-b', 'host', ds],
                          env={'DN_INDEX_FORMAT': fmt})
            s.check_result(fmt, ['query', '-b', 'host', ds], got)
            rc, out, err = run_cli(['scrub', '--tree', idx])
            s.ops += 1
            if rc != 0:
                s.violate('%s: rebuilt tree not scrub-clean: %s'
                          % (fmt, out.decode('utf-8',
                                             'replace')[:300]))
        if floor:
            extra = 0
            while extra < 60:
                total = mod_vpipe.global_counters().get(
                    'faults injected', 0)
                if total >= floor:
                    break
                extra += 1
                s.note('top-up flip build %d (%d/%d faults)'
                       % (extra, total, floor))
                rc, out, err = run_cli(
                    ['build', ctx['ds'][FORMATS[0]]],
                    env={'DN_INDEX_FORMAT': FORMATS[0],
                         'DN_FAULTS':
                         'sink.rename:flip:1.0:%d' % (100 + extra)})
                s.ops += 1
                mod_faults.reset()
            # leave the shared tree clean for the record
            build(ctx, FORMATS[0])
    finally:
        for k in ('DN_VERIFY', 'DN_SCRUB_INTERVAL_S',
                  'DN_SCRUB_RATE_MB_S'):
            os.environ.pop(k, None)
        mod_integrity.reset_memo()
        s.stop_cluster()
    summary = s.summary()
    summary['corruptions_injected'] = len(s.flips)
    return summary


# -- continuous-ingest (dn follow) drill ------------------------------------

# the appender: grows the log in fsynced bursts so the follower's
# reads race real in-flight writes (partial trailing lines included)
APPENDER_SRC = r'''
import datetime, json, os, sys, time
path, total, per, sleep_ms = (sys.argv[1], int(sys.argv[2]),
                              int(sys.argv[3]), float(sys.argv[4]))
t0 = 1388534400
i = 0
while i < total:
    with open(path, 'a') as f:
        for j in range(per):
            if i >= total:
                break
            ts = datetime.datetime.utcfromtimestamp(
                t0 + (i * 4999) % (5 * 86400)).strftime(
                    '%Y-%m-%dT%H:%M:%S.000Z')
            f.write(json.dumps({
                'time': ts, 'host': 'host%d' % (i % 4),
                'operation': ('get', 'put', 'index')[i % 3],
                'latency': (i * 7) % 230,
            }, separators=(',', ':')) + '\n')
            i += 1
        f.flush()
        os.fsync(f.fileno())
    time.sleep(sleep_ms / 1000.0)
'''

# error-kind chaos the follower runs under the whole drill (it must
# retry through these without duplicating or losing a point)
FOLLOW_ERR_SPEC = ('follow.read:error:0.03:61,'
                   'follow.checkpoint:error:0.2:62,'
                   'follow.publish:error:0.2:63,'
                   'sink.flush:error:0.05:64')
# per-cycle kill placement: None = external SIGKILL at a random
# moment; the kill-kind specs land the SIGKILL exactly mid-publish
# (between prepare and commit) and mid-rename (after the commit
# record) — the two halves of the atomicity argument
FOLLOW_KILL_CYCLE = (None, 'follow.publish:kill:1.0',
                     'sink.rename:kill:1.0')


class FollowSoak(object):
    """One format's appender + follower + kill/verify cycles."""

    def __init__(self, root, fmt, verbose=True):
        self.root = root
        self.fmt = fmt
        self.verbose = verbose
        self.violations = []
        self.ops = 0
        self.kills = 0
        self.follower_faults = 0
        self.datafile = os.path.join(root, 'follow_data_%s.log' % fmt)
        self.prefix = os.path.join(root, 'follow_prefix_%s.log' % fmt)
        self.idx = os.path.join(root, 'idx_follow_%s' % fmt)
        self.ref_idx = os.path.join(root, 'idx_fref_%s' % fmt)
        self.ds = 'dsfollow_' + fmt
        self.ref_ds = 'dsfref_' + fmt
        self.stderr_log = os.path.join(root, 'follower_%s.log' % fmt)
        self.proc = None
        open(self.datafile, 'w').close()
        for ds, path, idx in ((self.ds, self.datafile, self.idx),
                              (self.ref_ds, self.prefix,
                               self.ref_idx)):
            rc, out, err = run_cli([
                'datasource-add', '--path', path, '--index-path',
                idx, '--time-field', 'time', ds])
            assert rc == 0, err
            rc, out, err = run_cli([
                'metric-add', '-b',
                'timestamp[date,field=time,aggr=lquantize,'
                'step=86400],host,latency[aggr=quantize]', ds, 'm1'])
            assert rc == 0, err
            rc, out, err = run_cli([
                'metric-add', '-b', 'operation', '-f',
                '{"eq": ["operation", "get"]}', ds, 'm2'])
            assert rc == 0, err

    def note(self, msg):
        if self.verbose:
            sys.stderr.write('soak: [%s] %s\n' % (self.fmt, msg))

    def violate(self, msg):
        self.violations.append('[%s] %s' % (self.fmt, msg))
        sys.stderr.write('soak: VIOLATION: [%s] %s\n'
                         % (self.fmt, msg))

    def _follow_env(self, extra_spec=None):
        env = dict(os.environ, JAX_PLATFORMS='cpu',
                   DN_INDEX_FORMAT=self.fmt,
                   DN_FOLLOW_LATENCY_MS='50',
                   DN_FOLLOW_MAX_BYTES='4096',
                   DN_FOLLOW_POLL_MS='10')
        spec = FOLLOW_ERR_SPEC
        if extra_spec:
            # DN_FAULTS rejects a site armed twice: a kill-kind cycle
            # spec replaces the base error entry for its site
            extra_sites = {e.split(':', 1)[0]
                           for e in extra_spec.split(',')}
            kept = [e for e in FOLLOW_ERR_SPEC.split(',')
                    if e.split(':', 1)[0] not in extra_sites]
            spec = ','.join(kept + [extra_spec])
        env['DN_FAULTS'] = spec
        return env

    def spawn_follower(self, extra_spec=None):
        self.proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO_ROOT, 'bin', 'dn.py'),
             'follow', self.ds, self.datafile],
            env=self._follow_env(extra_spec),
            stdout=subprocess.DEVNULL,
            stderr=open(self.stderr_log, 'ab'))

    def kill_follower(self):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
        if self.proc is not None:
            self.proc.wait()
        self.proc = None
        self.kills += 1

    def count_follower_faults(self):
        """error-kind firings surface as the follower's retry warnings
        (one line per injected fault); kill firings as dead
        processes.  Parsed from the captured stderr."""
        try:
            with open(self.stderr_log, 'rb') as f:
                text = f.read().decode('utf-8', 'replace')
        except OSError:
            return
        self.follower_faults = text.count('injected')

    def catch_up(self):
        """`dn follow --once` in-process (armed with the error spec
        via the environment) until it converges — a drain-phase
        failure streak returns 1 with the batch retained, so another
        pass continues exactly where it left off."""
        env = {'DN_INDEX_FORMAT': self.fmt,
               'DN_FOLLOW_LATENCY_MS': '0',
               'DN_FOLLOW_MAX_BYTES': '4096',
               'DN_FOLLOW_POLL_MS': '10',
               'DN_FAULTS': FOLLOW_ERR_SPEC}
        for attempt in range(6):
            rc, out, err = run_cli(['follow', '--once', self.ds],
                                   env=env)
            self.ops += 1
            if rc == 0:
                return True
            text = err.decode('utf-8', 'replace')
            if 'Traceback' in text:
                self.violate('catch-up traceback: %r' % text[-300:])
                return False
        self.violate('catch-up never converged: %r' % text[-300:])
        return False

    def verify_prefix(self, when, full=False):
        """THE exactly-once check: the checkpointed offset names the
        published input prefix; a from-scratch build over exactly
        that prefix must answer queries byte-identically.  `full`
        additionally pins the offset to the completed stream's size —
        without it a follower that silently stopped short of EOF
        (rc 0, tiny checkpoint) would pass every prefix comparison
        and the 'zero lost points' gate would be vacuous."""
        from dragnet_tpu.follow.checkpoint import Checkpointer
        doc = Checkpointer(self.idx).load()
        if doc is None:
            self.violate('%s: no checkpoint after catch-up' % when)
            return
        offset = 0
        for s in doc['sources']:
            if s.get('path') == self.datafile:
                offset = int(s.get('offset') or 0)
        if full:
            size = os.path.getsize(self.datafile)
            if offset != size:
                self.violate('%s: checkpoint offset %d != completed '
                             'stream size %d (lost suffix)'
                             % (when, offset, size))
                return
        with open(self.datafile, 'rb') as f:
            blob = f.read(offset)
        if len(blob) != offset:
            self.violate('%s: checkpoint offset %d beyond file'
                         % (when, offset))
            return
        with open(self.prefix, 'wb') as f:
            f.write(blob)
        import shutil
        shutil.rmtree(self.ref_idx, ignore_errors=True)
        mod_journal.reset_sweep_memo()
        rc, out, err = run_cli(['build', self.ref_ds],
                               env={'DN_INDEX_FORMAT': self.fmt})
        self.ops += 1
        if rc != 0:
            self.violate('%s: reference build failed: %r'
                         % (when, err[-300:]))
            return
        # DN_IQ_STAT_TTL_MS=0: the soak process is an EXTERNAL
        # observer of shards the follower subprocess rewrites; the
        # handle cache's 1 s stat amortization is documented serving
        # staleness, and a verify must re-stat to see the tree as it
        # is on disk (a fresh process would)
        qenv = {'DN_INDEX_FORMAT': self.fmt,
                'DN_IQ_STAT_TTL_MS': '0'}
        for case in (['query', '-b', 'host'],
                     ['query', '-b', 'host,latency[aggr=quantize]',
                      '--raw'],
                     ['query', '--points', '-b', 'operation', '-f',
                      '{"eq": ["operation", "get"]}']):
            got = run_cli(case + [self.ds], env=qenv)
            ref = run_cli(case + [self.ref_ds], env=qenv)
            self.ops += 2
            if got[0] != 0 or ref[0] != 0 or got[1] != ref[1]:
                self.violate(
                    '%s: %s: follow tree diverges from the '
                    'from-scratch build over the checkpointed '
                    'prefix' % (when, ' '.join(case)))
        litter = tree_tmp_litter(self.idx)
        litter = [p for p in litter
                  if mod_journal.FOLLOW_DIR not in p]
        if litter:
            self.violate('%s: litter after recovery: %s'
                         % (when, litter))

    def append_burst(self, n):
        """Synchronously append `n` fresh records (same shape as the
        appender's, distinct value range) so a kill-spec cycle always
        has pending input to publish — the racing appender may have
        finished while an earlier cycle caught up and verified."""
        import datetime
        t0 = 1388534400
        with open(self.datafile, 'a') as f:
            for _ in range(n):
                i = self.burst_i
                self.burst_i += 1
                ts = datetime.datetime.utcfromtimestamp(
                    t0 + (i * 4999) % (5 * 86400)).strftime(
                        '%Y-%m-%dT%H:%M:%S.000Z')
                f.write(json.dumps({
                    'time': ts, 'host': 'host%d' % (i % 4),
                    'operation': ('get', 'put', 'index')[i % 3],
                    'latency': (i * 7) % 230,
                }, separators=(',', ':')) + '\n')
            f.flush()
            os.fsync(f.fileno())

    def run(self, fast=False):
        total = 900 if fast else 4000
        self.burst_i = total
        appender = subprocess.Popen(
            [sys.executable, '-c', APPENDER_SRC, self.datafile,
             str(total), '30', '20' if fast else '30'],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        cycles = 3 if fast else 9
        try:
            for i in range(cycles):
                spec = FOLLOW_KILL_CYCLE[i % len(FOLLOW_KILL_CYCLE)]
                self.append_burst(120)
                self.spawn_follower(extra_spec=spec)
                if spec is None:
                    time.sleep(0.8 + 0.4 * (i % 3))
                    self.kill_follower()
                    self.note('external SIGKILL mid-stream')
                else:
                    deadline = time.time() + 120
                    while time.time() < deadline and \
                            self.proc.poll() is None:
                        time.sleep(0.05)
                    rc = self.proc.poll()
                    if rc is None:
                        self.kill_follower()
                        self.violate('kill spec [%s] never fired'
                                     % spec)
                    else:
                        self.proc = None
                        self.kills += 1
                        if rc != -9:
                            self.violate(
                                'kill spec [%s]: rc=%s' % (spec, rc))
                        self.note('fault SIGKILL [%s]' % spec)
                mod_journal.reset_sweep_memo()
                mod_faults.reset()
                if self.catch_up():
                    self.verify_prefix('kill cycle %d' % i)
            # pure chaos rounds: append + catch up under the armed
            # error spec, no kills — volume for the retry paths
            # (publish/checkpoint/read failures must retry exactly,
            # never duplicate); verified once at the end
            rounds = 20 if fast else 60
            for r in range(rounds):
                self.append_burst(100)
                mod_journal.reset_sweep_memo()
                if not self.catch_up():
                    break
            self.verify_prefix('chaos rounds')
        finally:
            if appender.poll() is None:
                appender.kill()
            appender.wait()
            self.kill_follower()
        # final convergence over the completed stream: drain-stop a
        # live follower (SIGTERM path), then verify the whole file
        self.spawn_follower()
        time.sleep(1.0)
        self.proc.terminate()
        try:
            self.proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            self.violate('drain-stop hung')
            self.kill_follower()
        self.proc = None
        mod_journal.reset_sweep_memo()
        mod_faults.reset()
        if self.catch_up():
            self.verify_prefix('final', full=True)
        self.count_follower_faults()


def soak_follow(root, fast=False, verbose=True, floor=None):
    """The continuous-ingest drill; returns the summary dict."""
    mod_faults.reset()
    rc_path = os.path.join(root, 'dragnetrc.json')
    os.environ['DRAGNET_CONFIG'] = rc_path
    formats = ('dnc',) if fast else FORMATS
    soaks = []
    for fmt in formats:
        s = FollowSoak(root, fmt, verbose=verbose)
        s.run(fast=fast)
        soaks.append(s)
    if floor:
        # top-up: more append+catch-up chaos rounds until the
        # injected-fault floor is met (the error rates are
        # probabilistic; a lucky run must not fail the gate)
        s = soaks[-1]
        subproc = sum(x.follower_faults for x in soaks)
        extra = 0
        while extra < 300 and subproc + mod_vpipe.global_counters() \
                .get('faults injected', 0) < floor:
            s.append_burst(100)
            mod_journal.reset_sweep_memo()
            if not s.catch_up():
                break
            extra += 1
        if extra:
            s.note('%d top-up chaos rounds' % extra)
            s.verify_prefix('top-up rounds')
    counters = mod_vpipe.global_counters()
    inproc = counters.get('faults injected', 0)
    summary = {
        'ops': sum(s.ops for s in soaks),
        'kills': sum(s.kills for s in soaks),
        'clean_errors': 0,
        'violations': sum((s.violations for s in soaks), []),
        'faults_injected_total': inproc + sum(
            s.follower_faults for s in soaks),
        'faults_injected_in_process': inproc,
        'faults_injected_follower': sum(
            s.follower_faults for s in soaks),
        'batches_published': counters.get('follow batches published',
                                          0),
        'recovery': {
            k: counters.get(k, 0)
            for k in ('index recovery rollbacks',
                      'index recovery rollforwards',
                      'index tmps quarantined')},
    }
    return summary


# -- background-compaction (append + compact + rollup) drill ----------------

# error-kind chaos armed while the serve-resident maintenance timer
# rewrites the tree under flood: each firing aborts one group/shard
# publish cleanly (prepared tmps discarded via sink.abort) and the
# next tick retries until the pass lands
COMPACT_ERR_SPEC = ('compact.publish:error:0.35:91,'
                    'rollup.publish:error:0.35:92')
# subprocess kill placement: compact.publish lands the SIGKILL after
# the compacted shard is prepared but before the commit record
# (rollback side), sink.rename after the commit record (roll-forward
# side), rollup.publish mid-rollup-build — a recovered tree must keep
# answering byte-identically in every case (compaction and rollups
# never change query bytes)
COMPACT_KILL_SPECS = ('compact.publish:kill:1.0',
                      'sink.rename:kill:1.0',
                      'rollup.publish:kill:1.0')


class CompactSoak(object):
    """One format's append/compact/rollup drill (`--compact`): `dn
    follow --once` rounds in append mode land every batch as
    mini-generations while a `dn serve` member (result cache on, a
    1-second maintenance timer) compacts generation groups under the
    tree write lock and refreshes rollup shards, with the publish
    seams armed and a remote query flood running; separate subprocess
    `dn compact` / `dn rollup` runs are SIGKILLed mid-publish.  The
    contract: every accepted response is byte-identical to a
    from-scratch `dn build` over the same input — with generations
    pending, mid-rewrite, after every kill, after compaction —
    failures are clean `dn:` errors, zero stranded tmps, and the
    final compacted tree byte-equals the from-scratch build shard
    for shard."""

    def __init__(self, root, fmt, verbose=True):
        self.root = root
        self.fmt = fmt
        self.verbose = verbose
        self.violations = []
        self.ops = 0
        self.kills = 0
        self.clean_errors = 0
        self.n = 0
        self.golden = []
        self.datafile = os.path.join(root, 'compact_data_%s.log' % fmt)
        self.prefix = os.path.join(root, 'compact_prefix_%s.log' % fmt)
        self.idx = os.path.join(root, 'idx_compact_%s' % fmt)
        self.ref_idx = os.path.join(root, 'idx_cref_%s' % fmt)
        self.ds = 'dscomp_' + fmt
        self.ref_ds = 'dscref_' + fmt
        self._flood_threads = []
        open(self.datafile, 'w').close()
        for ds, path, idx in ((self.ds, self.datafile, self.idx),
                              (self.ref_ds, self.prefix,
                               self.ref_idx)):
            rc, out, err = run_cli([
                'datasource-add', '--path', path, '--index-path',
                idx, '--time-field', 'time', ds])
            assert rc == 0, err
            rc, out, err = run_cli([
                'metric-add', '-b',
                'timestamp[date,field=time,aggr=lquantize,'
                'step=86400],host,latency[aggr=quantize]', ds, 'm1'])
            assert rc == 0, err
            rc, out, err = run_cli([
                'metric-add', '-b', 'operation', '-f',
                '{"eq": ["operation", "get"]}', ds, 'm2'])
            assert rc == 0, err

    def note(self, msg):
        if self.verbose:
            sys.stderr.write('soak: [%s] %s\n' % (self.fmt, msg))

    def violate(self, msg):
        self.violations.append('[%s] %s' % (self.fmt, msg))
        sys.stderr.write('soak: VIOLATION: [%s] %s\n'
                         % (self.fmt, msg))

    def _env_block(self):
        """Installed once for the whole drill (run_cli's per-call env
        install mutates the process environment, so the flood threads
        must never depend on a per-call env)."""
        return {'DN_INDEX_FORMAT': self.fmt,
                'DN_IQ_STAT_TTL_MS': '0',
                'DN_FOLLOW_LATENCY_MS': '0',
                'DN_FOLLOW_MAX_BYTES': '65536',
                'DN_FOLLOW_POLL_MS': '5',
                'DN_FOLLOW_APPEND': '1',
                'DN_REMOTE_RETRIES': '3',
                'DN_REMOTE_BACKOFF_MS': '5',
                'DN_SERVE_CLIENT_TIMEOUT_S': '60',
                # the serve member's maintenance timer + result cache
                # knobs (read at server construction)
                'DN_ROLLUP_INTERVAL_S': '1',
                'DN_COMPACT_INTERVAL_S': '1',
                'DN_COMPACT_MIN_GENS': '1'}

    def case_args(self):
        return [
            ['-b', 'host'],
            ['-b', 'host,latency[aggr=quantize]', '--raw'],
            ['--points', '-b', 'operation', '-f',
             '{"eq": ["operation", "get"]}'],
            ['-b', 'host', '-A', '2014-01-02', '-B', '2014-01-04'],
        ]

    def append_round(self, n):
        """Append `n` records and land them: the first round creates
        the base shards, every later round's batch publishes as one
        mini-generation per touched base (DN_FOLLOW_APPEND)."""
        gen_data(self.datafile, n, start=self.n, days=5)
        self.n += n
        rc, out, err = run_cli(['follow', '--once', self.ds])
        self.ops += 1
        if rc != 0:
            self.violate('follow --once failed: %r' % err[-300:])

    def refresh_ref(self):
        """Rebuild the from-scratch reference over the full appended
        input and re-capture the golden bytes for every query case."""
        import shutil
        shutil.copyfile(self.datafile, self.prefix)
        shutil.rmtree(self.ref_idx, ignore_errors=True)
        mod_journal.reset_sweep_memo()
        rc, out, err = run_cli(['build', self.ref_ds])
        self.ops += 1
        if rc != 0:
            self.violate('reference build failed: %r' % err[-300:])
            return
        self.golden = []
        for args in self.case_args():
            ref = run_cli(['query'] + args + [self.ref_ds])
            self.ops += 1
            if ref[0] != 0:
                self.violate('golden query failed: %r' % ref[2][-300:])
                continue
            self.golden.append((args, ref[1]))

    def verify(self, when, remote=None):
        """Byte-identity against the from-scratch reference — local
        reads when the tree is quiesced, `--remote` through the serve
        member (whose tree lock serializes against the compactor)
        while the maintenance timer is live."""
        for args, gold in self.golden:
            case = ['query'] + (['--remote', remote]
                                if remote else []) + args + [self.ds]
            got = run_cli(case)
            self.ops += 1
            if got[0] != 0 or got[1] != gold:
                self.violate('%s: query %s diverges from the '
                             'from-scratch build (rc=%d)'
                             % (when, ' '.join(args), got[0]))

    def check_litter(self, when):
        mod_journal.reset_sweep_memo()
        mod_journal.sweep_index_tree(self.idx)
        bad = [p for p in tree_tmp_litter(self.idx)
               if mod_journal.FOLLOW_DIR not in p]
        if bad:
            self.violate('%s: stranded tmps: %s' % (when, bad))

    # -- the serve phase: flood + armed maintenance rewrites ----------

    def start_flood(self, sock, nthreads=2):
        self._stop_flood = threading.Event()
        self._flood_results = []
        lock = threading.Lock()
        golden = list(self.golden)

        def worker(tid):
            i = tid
            while not self._stop_flood.is_set():
                args, gold = golden[i % len(golden)]
                got = run_cli(['query', '--remote', sock] + args +
                              [self.ds])
                with lock:
                    self._flood_results.append((args, gold, got))
                i += 1

        self._flood_threads = [
            threading.Thread(target=worker, args=(t,), daemon=True)
            for t in range(nthreads)]
        for t in self._flood_threads:
            t.start()

    def stop_flood(self):
        self._stop_flood.set()
        for t in self._flood_threads:
            t.join(120)
            if t.is_alive():
                self.violate('flood: query thread hung')
        self._flood_threads = []
        served = errors = 0
        for args, gold, (rc, out, err) in self._flood_results:
            self.ops += 1
            if rc == 0:
                if out != gold:
                    self.violate('flood: accepted response with '
                                 'divergent bytes (%s)'
                                 % ' '.join(args))
                else:
                    served += 1
                continue
            text = err.decode('utf-8', 'replace')
            if 'Traceback' in text or 'dn:' not in text:
                self.violate('flood: unclean failure: %r'
                             % text[-300:])
            else:
                self.clean_errors += 1
                errors += 1
        self.note('flood: %d byte-identical responses, %d clean '
                  'errors' % (served, errors))

    def wait_drained(self, timeout_s):
        """Block until the serve member's compactor has folded every
        pending mini-generation (the soak process runs no compactor
        of its own here, so a drained backlog PROVES the server-side
        rewrite happened)."""
        from dragnet_tpu import rollup as mod_rollup
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if sum(mod_rollup.compaction_backlog(self.idx, iv)
                   for iv in ('hour', 'day')) == 0:
                return True
            time.sleep(0.25)
        return False

    def serve_phase(self, fast=False):
        sock = os.path.join(self.root,
                            'dn-compact-%s.sock' % self.fmt)
        if os.path.exists(sock):
            os.unlink(sock)
        srv = mod_server.DnServer(
            socket_path=sock,
            conf={'max_inflight': 4, 'queue_depth': 16,
                  'deadline_ms': 0, 'coalesce': False, 'drain_s': 10,
                  'cache_mb': 8}).start()
        prior = os.environ.get('DN_FAULTS')
        os.environ['DN_FAULTS'] = COMPACT_ERR_SPEC
        mod_faults.reset()
        rounds = 2 if fast else 5
        try:
            for r in range(rounds):
                self.append_round(150)
                self.refresh_ref()
                self.verify('round %d generations pending' % r,
                            remote=sock)
                self.start_flood(sock, nthreads=2)
                drained = self.wait_drained(90)
                time.sleep(0.5)
                self.stop_flood()
                if not drained:
                    self.violate('round %d: compaction backlog never '
                                 'drained under armed faults' % r)
                # backlog 0: no compaction can race these local reads
                self.verify('round %d compacted' % r)
            doc = mod_client.stats(sock, timeout_s=30.0)
            self.ops += 1
            rcache = (doc.get('caches') or {}).get('results') or {}
            if not rcache.get('enabled') or not rcache.get('hits'):
                self.violate('serve phase: result cache recorded no '
                             'hits: %r' % (rcache,))
            maint = doc.get('maintenance') or {}
            if not maint.get('runs'):
                self.violate('serve phase: maintenance timer never '
                             'ran: %r' % (maint,))
            counters = doc.get('counters') or {}
            if not counters.get('follow generations appended'):
                self.violate('serve phase: no mini-generations were '
                             'appended')
            if not counters.get('rollup shards built'):
                self.violate('serve phase: no rollup shards built')
        finally:
            if prior is None:
                os.environ.pop('DN_FAULTS', None)
            else:
                os.environ['DN_FAULTS'] = prior
            mod_faults.reset()
            srv.stop()
        self.check_litter('serve phase')

    # -- the kill phase: subprocess maintenance SIGKILLed mid-publish -

    def kill_phase(self, fast=False):
        specs = COMPACT_KILL_SPECS[:2] if fast else COMPACT_KILL_SPECS
        for spec in specs:
            self.append_round(120)
            self.refresh_ref()
            self.verify('pre-kill [%s]' % spec)
            if spec.startswith('rollup.'):
                cmd = ['rollup', '--tree', self.idx,
                       '--interval', 'day']
            else:
                cmd = ['compact', '--tree', self.idx,
                       '--interval', 'day', '--min-gens', '1']
            env = dict(os.environ, JAX_PLATFORMS='cpu',
                       DN_FAULTS=spec, DN_INDEX_FORMAT=self.fmt)
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(REPO_ROOT, 'bin', 'dn.py')] + cmd,
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, timeout=300)
            self.ops += 1
            if proc.returncode != -9:
                self.violate('kill drill [%s]: expected SIGKILL, '
                             'got rc=%s stderr=%r'
                             % (spec, proc.returncode,
                                proc.stderr[-200:]))
                continue
            self.kills += 1
            self.note('SIGKILLed dn %s mid-publish [%s]'
                      % (cmd[0], spec))
            mod_journal.reset_sweep_memo()
            mod_faults.reset()
            # the recovery sweep runs on the query path; rolled back
            # OR rolled forward, the bytes must not move
            self.verify('post-kill [%s]' % spec)
            self.check_litter('post-kill [%s]' % spec)

    # -- the final seal: compacted tree == from-scratch build ---------

    def check_tree_equality(self):
        """After a clean converge compaction the live tree's shards
        byte-equal the from-scratch build, name for name (follow/
        quarantine/rollup state and durable metadata excluded — the
        reference tree has none)."""
        def tree_bytes(idx):
            out = {}
            for r, dirs, names in os.walk(idx):
                for skip in (mod_journal.FOLLOW_DIR,
                             mod_journal.QUARANTINE_DIR,
                             mod_journal.ROLLUP_DIR):
                    if skip in dirs:
                        dirs.remove(skip)
                for name in sorted(names):
                    if mod_journal.is_durable_metadata(name):
                        continue
                    p = os.path.join(r, name)
                    with open(p, 'rb') as f:
                        out[os.path.relpath(p, idx)] = f.read()
            return out

        mod_journal.reset_sweep_memo()
        got = tree_bytes(self.idx)
        ref = tree_bytes(self.ref_idx)
        if sorted(got) != sorted(ref):
            self.violate('compacted tree shard set differs from the '
                         'from-scratch build: %d vs %d shards'
                         % (len(got), len(ref)))
            return
        diff = [k for k in ref if got[k] != ref[k]]
        if diff:
            self.violate('compacted shard bytes diverge from the '
                         'from-scratch build: %s' % diff[:4])
        else:
            self.note('compacted tree byte-equals the from-scratch '
                      'build (%d shards)' % len(ref))

    def armed_offline_round(self):
        """One append + armed offline compaction — top-up volume for
        the injected-fault floor; retries until the pass lands."""
        env = self._env_block()
        prior = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        arm = os.environ.get('DN_FAULTS')
        os.environ['DN_FAULTS'] = COMPACT_ERR_SPEC
        mod_faults.reset()
        try:
            self.append_round(120)
            for attempt in range(10):
                rc, out, err = run_cli(['compact', '--tree', self.idx,
                                        '--interval', 'day',
                                        '--min-gens', '1'])
                self.ops += 1
                if rc == 0:
                    return
                text = err.decode('utf-8', 'replace')
                if 'Traceback' in text or 'dn:' not in text:
                    self.violate('top-up compact unclean: %r'
                                 % text[-300:])
                    return
                self.clean_errors += 1
            self.violate('top-up compact never converged')
        finally:
            if arm is None:
                os.environ.pop('DN_FAULTS', None)
            else:
                os.environ['DN_FAULTS'] = arm
            mod_faults.reset()
            for k, v in prior.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def final_seal(self):
        """Re-verify + tree equality with the drill env installed
        (used after top-up rounds mutate the tree again)."""
        env = self._env_block()
        prior = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            self.refresh_ref()
            self.verify('final')
            self.check_litter('final')
            self.check_tree_equality()
        finally:
            for k, v in prior.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def run(self, fast=False):
        env = self._env_block()
        prior = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            self.append_round(400 if fast else 900)   # base shards
            self.refresh_ref()
            self.verify('seed')
            self.serve_phase(fast=fast)
            self.kill_phase(fast=fast)
            # converge: a clean offline compaction of whatever the
            # kill drills left pending, then the seal
            for interval in ('day', 'hour'):
                rc, out, err = run_cli(['compact', '--tree', self.idx,
                                        '--interval', interval,
                                        '--min-gens', '1'])
                self.ops += 1
                if rc != 0:
                    self.violate('converge compact (%s) failed: %r'
                                 % (interval, err[-300:]))
            self.refresh_ref()
            self.verify('converged')
            self.check_litter('converged')
            self.check_tree_equality()
        finally:
            for k, v in prior.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v


def soak_compact(root, fast=False, verbose=True, floor=None):
    """The background-compaction drill; returns the summary dict."""
    mod_faults.reset()
    rc_path = os.path.join(root, 'dragnetrc.json')
    os.environ['DRAGNET_CONFIG'] = rc_path
    formats = ('dnc',) if fast else FORMATS
    soaks = []
    for fmt in formats:
        s = CompactSoak(root, fmt, verbose=verbose)
        s.run(fast=fast)
        soaks.append(s)
    kills = sum(s.kills for s in soaks)
    if floor:
        # top-up: armed offline compaction rounds until the
        # injected-fault floor is met (each round re-creates
        # generation groups for the armed pass to chew through)
        s = soaks[-1]
        extra = 0
        while extra < 60 and kills + mod_vpipe.global_counters() \
                .get('faults injected', 0) < floor:
            s.armed_offline_round()
            extra += 1
        if extra:
            s.note('%d top-up armed compaction rounds' % extra)
            s.final_seal()
    counters = mod_vpipe.global_counters()
    inproc = counters.get('faults injected', 0)
    summary = {
        'ops': sum(s.ops for s in soaks),
        'kills': kills,
        'clean_errors': sum(s.clean_errors for s in soaks),
        'violations': sum((s.violations for s in soaks), []),
        'faults_injected_total': inproc + kills,
        'faults_injected_in_process': inproc,
        'generations_appended':
            counters.get('follow generations appended', 0),
        'shards_compacted':
            counters.get('index shards compacted', 0),
        'generations_removed':
            counters.get('index generations removed', 0),
        'rollup_shards_built':
            counters.get('rollup shards built', 0),
        'recovery': {
            k: counters.get(k, 0)
            for k in ('index recovery rollbacks',
                      'index recovery rollforwards',
                      'index tmps quarantined')},
    }
    return summary


# -- resource-exhaustion drill (disk governance + read-only serving) --------

class ResourceSoak(ClusterSoak):
    """The resource-exhaustion survival drill (`make soak-resources`):
    a 3-member routed cluster under continuous query flood while the
    simulated disk (DN_DISK_SIM_FILE) is forced through a full
    low -> critical -> recovered cycle, plus enospc/emfile faults
    armed at every write seam.  The contract:

    * queries stay BYTE-IDENTICAL to the single-process goldens
      through every mode, including the read-only window;
    * during critical, builds reject on every member with the clean
      retryable `disk full` error (header disk_full, never a
      traceback) and health reports degraded_ro;
    * recovery is automatic: once space frees, builds succeed again
      with no restart;
    * armed enospc/emfile at each write seam leaves a recoverable
      tree — zero torn shards, zero stranded tmps."""

    def __init__(self, ctx, verbose=True):
        super(ResourceSoak, self).__init__(ctx, verbose=verbose)
        self.sim_path = os.path.join(ctx['root'], 'disk_sim')
        self._flood_stop = None
        self._flood_threads = []

    # -- the simulated disk -------------------------------------------

    def set_free_pct(self, pct):
        with open(self.sim_path + '.w', 'w') as f:
            f.write('%g\n' % pct)
        os.replace(self.sim_path + '.w', self.sim_path)

    def wait_mode(self, mode, timeout_s=30.0):
        """Block until every member reports `mode` (in-process
        governors directly; subprocess b via its health op, which
        only distinguishes read-only)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            ok = all(srv.governor.mode() == mode
                     for srv in self.servers.values())
            if ok and mode in ('ok', 'critical'):
                doc = mod_client.health(self.socks['b'],
                                        timeout_s=2.0)
                want_ro = mode == 'critical'
                ok = doc.get('ok') and \
                    bool(doc.get('degraded_ro')) == want_ro
            if ok:
                return True
            time.sleep(0.1)
        self.violate('members never reached resource mode %r' % mode)
        return False

    # -- flood --------------------------------------------------------

    def start_flood(self, nthreads=2):
        self._flood_stop = threading.Event()

        def worker(tid):
            i = tid
            while not self._flood_stop.is_set():
                fmt = FORMATS[i % len(FORMATS)]
                ds = self.ctx['ds'][fmt]
                cases = query_cases(ds)
                case = cases[i % len(cases)]
                via = 'abc'[i % 3]
                got = run_cli(case[:1] +
                              ['--remote', self.socks[via]] +
                              case[1:])
                self.check_routed(fmt, case, got)
                i += nthreads

        self._flood_threads = [
            threading.Thread(target=worker, args=(t,), daemon=True)
            for t in range(nthreads)]
        for t in self._flood_threads:
            t.start()

    def stop_flood(self):
        if self._flood_stop is not None:
            self._flood_stop.set()
        for t in self._flood_threads:
            t.join(60)
            if t.is_alive():
                self.violate('resource drill: flood thread hung')
        self._flood_threads = []

    # -- checks -------------------------------------------------------

    def read_only_byte_identity(self):
        """The read-only window's core contract: every query case
        through every member must SUCCEED byte-identically while
        builds are rejected."""
        for fmt in FORMATS:
            ds = self.ctx['ds'][fmt]
            for i, case in enumerate(query_cases(ds)):
                via = 'abc'[i % 3]
                got = run_cli(case[:1] +
                              ['--remote', self.socks[via]] +
                              case[1:])
                self.check_routed(fmt, case, got, degraded_ok=False)

    def build_remote(self, member, fmt):
        return run_cli(['build', self.ctx['ds'][fmt], '--remote',
                        self.socks[member]],
                       env={'DN_INDEX_FORMAT': fmt,
                            'DN_REMOTE_RETRIES': '0'})

    def check_builds(self, expect_ok, when):
        for member in 'abc':
            fmt = FORMATS[ord(member) % len(FORMATS)]
            rc, out, err = self.build_remote(member, fmt)
            self.ops += 1
            text = err.decode('utf-8', 'replace')
            if 'Traceback' in text:
                self.violate('build via %s %s: traceback: %r'
                             % (member, when, text[-300:]))
            elif expect_ok and rc != 0:
                self.violate('build via %s %s: rejected: %r'
                             % (member, when, text[-300:]))
            elif not expect_ok:
                if rc == 0:
                    self.violate('build via %s %s: succeeded on a '
                                 'read-only member' % (member, when))
                elif 'disk full' not in text:
                    self.violate('build via %s %s: rejection does '
                                 'not name disk full: %r'
                                 % (member, when, text[-300:]))
                else:
                    self.clean_errors += 1

    def check_stats_surface(self):
        """/stats must carry the resources section and the governor
        gauges must ride the Prometheus exposition."""
        self.ops += 1
        doc = mod_client.stats(self.socks['a'], timeout_s=30.0)
        res = doc.get('resources') or {}
        if res.get('mode') not in ('ok', 'low', 'critical'):
            self.violate('/stats resources section missing or '
                         'malformed: %r' % (res,))
        rc, out, err = run_cli(['stats', '--prom', '--remote',
                                self.socks['a']])
        if rc != 0 or b'disk_mode' not in out or \
                b'disk_free_bytes' not in out:
            self.violate('resource gauges missing from the '
                         'Prometheus exposition')

    def enospc_seam_drills(self):
        """enospc/emfile at rate 1.0, seam by seam: every local build
        must fail CLEAN (no traceback), leave zero stranded tmps once
        superseded, and a disarmed rebuild must succeed."""
        specs = ('sink.create:emfile:1.0',
                 'sink.flush:enospc:1.0',
                 'sink.rename:enospc:1.0',
                 'journal.commit:enospc:1.0',
                 'integrity.catalog:enospc:1.0')
        for spec in specs:
            for fmt in FORMATS:
                mod_faults.reset()
                rc, out, err = run_cli(
                    ['build', self.ctx['ds'][fmt]],
                    env={'DN_INDEX_FORMAT': fmt, 'DN_FAULTS': spec})
                self.ops += 1
                text = err.decode('utf-8', 'replace')
                if rc == 0:
                    self.violate('%s %s: build succeeded with the '
                                 'seam armed at 1.0' % (fmt, spec))
                elif 'Traceback' in text or 'dn:' not in text:
                    self.violate('%s %s: unclean resource failure: '
                                 '%r' % (fmt, spec, text[-300:]))
                else:
                    self.clean_errors += 1
            mod_faults.reset()
        self.check_trees('enospc seam drills')


def soak_resources(root, fast=False, verbose=True, floor=None):
    """The resource-exhaustion drill under `root`; returns the
    summary dict."""
    mod_faults.reset()
    sim_path = os.path.join(root, 'disk_sim')
    with open(sim_path, 'w') as f:
        f.write('60\n')
    os.environ.update({
        'DN_DISK_SIM_FILE': sim_path,
        'DN_RESOURCE_POLL_MS': '100',
        # the fd table of a soak process (pools, members, spools) is
        # noise here — the disk cycle is the drill
        'DN_FD_HEADROOM': '0',
        'DN_ROUTER_PROBE_MS': '150',
        'DN_EVENTS': '4096'})
    ctx = make_corpus(root, n=400 if fast else 1200,
                      days=5 if fast else 10)
    for fmt in FORMATS:
        build(ctx, fmt)
    s = ResourceSoak(ctx, verbose=verbose)
    s.start_cluster()
    try:
        s.note('flood up; baseline byte-identity + builds (mode ok)')
        s.start_flood(nthreads=2)
        s.read_only_byte_identity()
        s.check_builds(expect_ok=True, when='at mode ok')
        s.note('forcing disk low (8% free)')
        s.set_free_pct(8)
        s.wait_mode('low')
        # low pauses BACKGROUND consumers only: foreground builds
        # and queries must be untouched
        s.read_only_byte_identity()
        s.check_builds(expect_ok=True, when='at mode low')
        s.note('forcing disk critical (2% free): read-only window')
        s.set_free_pct(2)
        s.wait_mode('critical')
        s.read_only_byte_identity()
        s.check_builds(expect_ok=False, when='at mode critical')
        s.check_stats_surface()
        s.note('freeing space: automatic recovery')
        s.set_free_pct(60)
        s.wait_mode('ok')
        s.read_only_byte_identity()
        s.check_builds(expect_ok=True, when='after recovery')
        s.stop_flood()
        s.note('enospc/emfile write-seam drills')
        s.enospc_seam_drills()
        if floor:
            extra = 0
            while extra < 60:
                total = mod_vpipe.global_counters().get(
                    'faults injected', 0)
                if total >= floor:
                    break
                extra += 1
                s.note('top-up seam round %d (%d/%d faults)'
                       % (extra, total, floor))
                s.enospc_seam_drills()
        s.check_trees('resource drill')
    finally:
        s.stop_flood()
        s.stop_cluster()
    return s.summary()


# the in-process mixed-fault spec: every site that can fire without
# killing the soak process (kill/torn run under the subprocess drills)
LOCAL_SPEC = ('sink.create:error:0.08:11,sink.flush:error:0.08:12,'
              'sink.rename:error:0.05:13,iq.shard_read:error:0.10:14')
DELAY_SPEC = 'iq.shard_read:delay:0.25:15,sink.flush:delay:0.2:16'
REMOTE_SPEC = ('client.connect:error:0.12:21,client.send:error:0.08:22,'
               'client.recv:error:0.10:23,serve.accept:error:0.08:24,'
               'serve.read:error:0.06:25,serve.write:error:0.10:26')
PROBE_SPEC = 'device.probe:error:1.0:31'
# rate 1.0: the FIRST prepare/commit in the killed subprocess fires
# deterministically — flush-phase kills drill the rollback (no commit
# record yet; torn additionally leaves half-written bytes), rename-
# phase kills drill the roll-forward (commit record on disk)
KILL_SPECS = ('sink.flush:kill:1.0', 'sink.flush:torn:1.0',
              'sink.rename:kill:1.0')
KILL_SPECS_FAST = ('sink.flush:torn:1.0', 'sink.rename:kill:1.0')


def soak(root, fast=False, verbose=True, floor=None):
    """Run the soak under `root`; returns the summary dict.  `floor`
    (injected-fault minimum) adds top-up local rounds until met."""
    mod_faults.reset()
    ctx = make_corpus(root, n=600 if fast else 2000,
                      days=5 if fast else 16)
    for fmt in FORMATS:
        build(ctx, fmt)
    s = Soak(ctx, verbose=verbose)

    local_rounds = 3 if fast else 10
    remote_rounds = 2 if fast else 8
    s.note('local fault rounds (%d)' % local_rounds)
    s.local_rounds(LOCAL_SPEC, local_rounds)
    s.note('delay rounds')
    s.local_rounds(DELAY_SPEC, 1 if fast else 2)
    s.note('device-probe fault rounds')
    s.local_rounds(PROBE_SPEC, 1, include_build=False,
                   env={'DN_ENGINE': 'jax'})
    s.note('remote fault rounds (%d)' % remote_rounds)
    s.remote_rounds(REMOTE_SPEC, remote_rounds)
    s.note('SIGKILL crash drills')
    s.kill_rounds(KILL_SPECS_FAST if fast else KILL_SPECS,
                  per_format=1 if fast else 2)
    if floor:
        # top up until the injected-fault floor is met (the PRNGs
        # keep drawing, so extra rounds add fresh chaos)
        extra = 0
        while extra < 60:
            total = mod_vpipe.global_counters().get('faults injected',
                                                    0)
            if total >= floor:
                break
            extra += 1
            s.note('top-up round %d (%d/%d faults)'
                   % (extra, total, floor))
            s.local_rounds(LOCAL_SPEC, 1)
    return s.summary()


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument('--fast', action='store_true',
                   help='miniature tier-1 variant')
    p.add_argument('--cluster', action='store_true',
                   help='run the scatter-gather cluster drill '
                        'instead of the single-process soak')
    p.add_argument('--follow', action='store_true',
                   help='run the continuous-ingest (dn follow) '
                        'drill instead of the single-process soak')
    p.add_argument('--compact', action='store_true',
                   help='run the background-compaction drill '
                        '(follow --append mini-generations under '
                        'remote query flood while a serve-resident '
                        'compactor and rollup builder rewrite the '
                        'tree with armed publish faults; subprocess '
                        'dn compact/rollup SIGKILLed mid-publish) '
                        'instead of the single-process soak')
    p.add_argument('--overload', action='store_true',
                   help='run the multi-tenant overload flood '
                        '(~5x capacity, tenant weights, torn-frame/'
                        'stall/flood faults, mid-flood SIGKILL) '
                        'instead of the single-process soak')
    p.add_argument('--rebalance', action='store_true',
                   help='run the live-resize drill (grow 3->5 and '
                        'shrink 5->2 members under flood with armed '
                        'handoff/topology faults and mid-handoff '
                        'SIGKILLs) instead of the single-process '
                        'soak')
    p.add_argument('--resources', action='store_true',
                   help='run the resource-exhaustion drill (forced '
                        'low->critical->recovered disk cycle under '
                        'routed flood via DN_DISK_SIM_FILE, builds '
                        'rejected read-only with queries '
                        'byte-identical, automatic write '
                        'resumption, enospc/emfile armed at every '
                        'write seam) instead of the single-process '
                        'soak')
    p.add_argument('--scrub', action='store_true',
                   help='run the corruption/self-healing drill '
                        '(flip bytes in committed shards across a '
                        '3-member cluster under routed flood with '
                        'DN_VERIFY=open and a 1s background scrub; '
                        'assert zero silently wrong bytes and every '
                        'corruption repaired from a co-replica) '
                        'instead of the single-process soak')
    p.add_argument('--subscribe', action='store_true',
                   help='run the standing-query drill (a `dn '
                        'subscribe` flood over the 3-member cluster '
                        'under armed push/transport faults, a '
                        'publisher and a subscriber SIGKILLed '
                        'mid-stream, pushed-vs-polled byte identity '
                        'at every quiescent epoch) instead of the '
                        'single-process soak')
    p.add_argument('--min-faults', type=int, default=None,
                   help='required injected-fault floor '
                        '(default: 500, or 50 with --fast; the '
                        'follow drill defaults to 100/20, the '
                        'overload drill to 60/15, the rebalance '
                        'drill to 40/10)')
    args = p.parse_args(argv)
    if args.follow:
        default_floor = 20 if args.fast else 100
    elif args.compact:
        default_floor = 4 if args.fast else 20
    elif args.overload:
        default_floor = 15 if args.fast else 60
    elif args.rebalance:
        default_floor = 10 if args.fast else 40
    elif args.scrub:
        default_floor = 4 if args.fast else 10
    elif args.resources:
        default_floor = 10 if args.fast else 20
    elif args.subscribe:
        default_floor = 4 if args.fast else 12
    else:
        default_floor = 50 if args.fast else 500
    floor = args.min_faults if args.min_faults is not None \
        else default_floor

    import tempfile
    t0 = time.time()
    runner = soak_cluster if args.cluster \
        else soak_follow if args.follow \
        else soak_compact if args.compact \
        else soak_overload if args.overload \
        else soak_rebalance if args.rebalance \
        else soak_scrub if args.scrub \
        else soak_subscribe if args.subscribe \
        else soak_resources if args.resources else soak
    with tempfile.TemporaryDirectory(prefix='dn_soak_') as root:
        summary = runner(root, fast=args.fast, floor=floor)
    summary['elapsed_s'] = round(time.time() - t0, 1)
    print(json.dumps(summary, indent=2, sort_keys=True))
    if summary['violations']:
        print('soak: FAILED (%d violation(s))'
              % len(summary['violations']), file=sys.stderr)
        return 1
    if summary['faults_injected_total'] < floor:
        print('soak: FAILED (only %d faults injected; floor %d)'
              % (summary['faults_injected_total'], floor),
              file=sys.stderr)
        return 1
    print('soak: OK (%d ops, %d faults injected, 0 torn shards)'
          % (summary['ops'], summary['faults_injected_total']),
          file=sys.stderr)
    return 0


if __name__ == '__main__':
    sys.exit(main())
