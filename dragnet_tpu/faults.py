"""Deterministic fault injection for chaos soaks and crash drills.

The robustness machinery this repo now carries — journaled index
publishing, the recovery sweep, the retry-hardened remote client —
is only trustworthy if failure paths are *exercised on purpose*.
This module is the single switchboard: named injection sites threaded
through the hot seams (index sink create/flush/rename, shard reads,
serve socket accept/read/write, client connect/send/recv, the device
probe), armed via one env knob:

    DN_FAULTS=site:kind:rate[:seed],site:kind:rate[:seed],...

Each armed site draws from its OWN seeded PRNG, so a chaos soak with a
given spec is replayable: the k-th check at a site fires (or not)
identically run over run.  (Cross-thread interleaving can reorder
which *operation* meets the k-th draw; rate=1.0 specs are fully
deterministic regardless.)  Kinds:

* ``error`` — raise FaultInjected (a DNError: callers' existing error
  contracts wrap and report it cleanly, never a traceback).
* ``delay`` — sleep DN_FAULT_DELAY_MS (default 25) and continue; for
  shaking out timeout/retry paths without failing the operation.
* ``torn``  — partial bytes then crash: at sites that hand a
  ``torn_path`` (the sink rename seam), truncate the tmp file to half
  its bytes and SIGKILL the process — the classic mid-write power
  cut.  Sites without a torn_path degrade to ``error``.
* ``kill``  — SIGKILL the process at the seam (mid-flush crash
  drills; only meaningful under a subprocess harness).
* ``enospc`` / ``emfile`` — resource exhaustion: raise
  ``OSError(ENOSPC)`` / ``OSError(EMFILE)`` at the seam, exactly what
  a full disk or an exhausted fd table produces mid-write.  Armed at
  every write seam (sink create/flush/rename, journal commit record,
  follow checkpoint, integrity catalog update, events spill, handoff
  apply, repair land) to prove each leaves a recoverable tree —
  journal rolls back, no torn shards, no stranded tmps
  (docs/robustness.md, the resource-governance section).
* ``flip``  — silent corruption: at sites that hand a file path
  (``flip_path``, or ``torn_path`` where no safer target exists),
  XOR one seeded-random byte of the target file and CONTINUE — the
  bit rot the integrity catalog (integrity.py) exists to catch.
  Armed at ``sink.rename`` (the file flipped is the prepared tmp,
  AFTER its checksum landed in the commit record, so the committed
  shard disagrees with the catalog exactly like post-publish rot)
  and ``handoff.apply``; sites without a path degrade to ``error``,
  mirroring ``torn``.

Every check and every firing is counted per site (stats(), plus the
hidden 'fault injected <site>' global counters `dn serve` surfaces in
/stats), so a soak can assert exactly how much chaos it generated.

The spec is validated through config.faults_config (the shared DNError
contract `dn serve --validate` checks); a malformed DN_FAULTS raises
that DNError at the first armed-site check rather than silently
injecting nothing.
"""

import os
import random
import signal
import threading
import time

from .errors import DNError
from .vpipe import counter_bump

KINDS = ('error', 'torn', 'delay', 'kill', 'flip', 'enospc',
         'emfile')

# the injection-site catalog (docs/robustness.md documents each seam)
SITES = (
    'sink.create',      # index sink creation (index_sink/index_dnc)
    'sink.flush',       # sink prepare: tmp-file body write
    'sink.rename',      # sink commit: the atomic rename (torn_path)
    'iq.shard_read',    # per-shard index reads (index_query_mt)
    'serve.accept',     # dn serve: accepted-connection handling
    'serve.read',       # dn serve: request read/parse
    'serve.write',      # dn serve: response write
    'serve.frame_torn',  # dn serve: v2 response framing (torn frame)
    'serve.push_torn',  # dn serve: subscription push framing (torn)
    'serve.stall',      # dn serve: per-request handling stall
    'tenant.flood',     # admission: per-tenant enqueue (overload)
    'client.connect',   # remote client: connect()
    'client.send',      # remote client: request send
    'client.recv',      # remote client: response header/payload read
    'device.probe',     # device backend probe (device_scan)
    'router.dispatch',  # scatter-gather: per-partition dispatch
    'router.merge',     # scatter-gather: partial-aggregate merge
    'member.health',    # dn serve: the health op a router probes
    'follow.read',      # dn follow: tailer source reads
    'follow.checkpoint',  # dn follow: checkpoint tmp write
    'follow.publish',   # dn follow: batch publish (pre-commit)
    'topo.poll',        # dynamic topology: coordinator-file poll
    'handoff.manifest',  # handoff: donor shard-manifest build
    'handoff.fetch',    # handoff: joiner per-shard fetch
    'handoff.apply',    # handoff: joiner shard rename-into-place
    'journal.commit',   # index journal: the commit-record write
    'integrity.catalog',  # integrity: catalog read-modify-write
    'events.spill',     # obs/events: the JSONL spill append
    'repair.land',      # serve/scrub: replica-repair shard landing
    'rollup.publish',   # rollup: per-shard rollup build/publish
    'compact.publish',  # rollup: compacted-group publish (pre-commit)
)


class FaultInjected(DNError):
    """An injected 'error'-kind fault.  A DNError so every existing
    error contract (index "<path>" wrapping, dn: framing, the remote
    client's retry classification) handles it like a real failure."""


class _Site(object):
    __slots__ = ('site', 'kind', 'rate', 'seed', 'rng', 'lock',
                 'checked', 'fired')

    def __init__(self, site, kind, rate, seed):
        self.site = site
        self.kind = kind
        self.rate = rate
        self.seed = seed
        # seeded per (site, seed): replayable draws, independent sites
        self.rng = random.Random('%s:%d' % (site, seed))
        self.lock = threading.Lock()
        self.checked = 0
        self.fired = 0


_REG_LOCK = threading.Lock()
# one atomically-replaced (env spec string, {site: _Site} | DNError)
# pair: fire() sits on per-shard hot seams, so the unarmed case must
# cost one env lookup + one atomic list read — no lock
_REG = [(None, {})]


def _registry():
    spec = os.environ.get('DN_FAULTS', '')
    cached_spec, table = _REG[0]
    if cached_spec == spec:
        return table
    with _REG_LOCK:
        cached_spec, table = _REG[0]
        if cached_spec == spec:
            return table
        from .config import faults_config
        parsed = faults_config()
        if isinstance(parsed, DNError):
            table = parsed
        else:
            table = {site: _Site(site, kind, rate, seed)
                     for site, (kind, rate, seed)
                     in parsed['sites'].items()}
        _REG[0] = (spec, table)
    return table


def reset():
    """Drop the parsed registry (tests: re-seed PRNGs / re-read a
    monkeypatched DN_FAULTS immediately)."""
    with _REG_LOCK:
        _REG[0] = (None, {})


def enabled():
    table = _registry()
    return bool(table) and not isinstance(table, DNError)


def _delay_s():
    try:
        return max(0.0, float(os.environ.get('DN_FAULT_DELAY_MS',
                                             '25'))) / 1000.0
    except ValueError:
        return 0.025


def fire(site, torn_path=None, flip_path=None):
    """The injection seam: no-op unless DN_FAULTS arms `site`; on a
    hit, act per the armed kind (see module docstring).  `torn_path`
    names the bytes a 'torn' kind may cut short (the sink's tmp
    file); `flip_path` the bytes a 'flip' kind may corrupt in place
    (falling back to torn_path — distinct parameters because a site
    where a torn tmp would be rolled FORWARD by recovery, like the
    sink commit seam, can safely hand flip a target it must never
    hand torn)."""
    table = _registry()
    if isinstance(table, DNError):
        raise table
    ent = table.get(site)
    if ent is None:
        return
    with ent.lock:
        ent.checked += 1
        hit = ent.rng.random() < ent.rate
        if hit:
            ent.fired += 1
            if ent.kind == 'flip':
                # the flip's offset/mask draws come off the same
                # seeded stream, so a given spec corrupts replayably
                flip_draw = (ent.rng.random(),
                             ent.rng.randrange(1, 256))
    if not hit:
        return
    counter_bump('faults injected')
    counter_bump('fault injected %s' % site)
    # observability: firings land as span events (chaos soaks become
    # traceable — the trace shows exactly which request absorbed which
    # injection) and as a typed counter in /stats `metrics`
    from .obs import metrics as obs_metrics
    from .obs import trace as obs_trace
    obs_metrics.inc('faults_injected_total', site=site, kind=ent.kind)
    obs_trace.event('fault.injected', site=site, kind=ent.kind)
    kind = ent.kind
    if kind == 'delay':
        time.sleep(_delay_s())
        return
    if kind in ('enospc', 'emfile'):
        import errno
        code = errno.ENOSPC if kind == 'enospc' else errno.EMFILE
        raise OSError(code, 'injected %s at "%s"'
                      % (kind.upper(), site))
    if kind == 'kill':
        os.kill(os.getpid(), signal.SIGKILL)
    if kind == 'torn' and torn_path is not None:
        _tear(torn_path)
        os.kill(os.getpid(), signal.SIGKILL)
    if kind == 'flip':
        target = flip_path if flip_path is not None else torn_path
        if target is not None:
            _flip(target, flip_draw[0], flip_draw[1])
            return           # silent: the corruption IS the fault
    raise FaultInjected('injected %s fault at "%s"' % (kind, site))


def _flip(path, offset_frac, mask):
    """XOR one byte of `path` at a seeded-random offset — silent bit
    rot, injected (best-effort: an unreadable target simply stays
    uncorrupted; the draw already happened so replay is intact)."""
    try:
        size = os.path.getsize(path)
        if size == 0:
            return
        off = min(size - 1, int(offset_frac * size))
        with open(path, 'r+b') as f:
            f.seek(off)
            b = f.read(1)
            if not b:
                return
            f.seek(off)
            f.write(bytes([b[0] ^ mask]))
            f.flush()
            os.fsync(f.fileno())
    except OSError:
        pass


def _tear(path):
    """Cut `path` to half its bytes — the partial write a power cut
    leaves behind (best-effort: the crash is the point)."""
    try:
        size = os.path.getsize(path)
        with open(path, 'r+b') as f:
            f.truncate(size // 2)
            f.flush()
            os.fsync(f.fileno())
    except OSError:
        pass


def stats():
    """Per-site injection telemetry: {site: {kind, rate, seed,
    checked, fired}} for the armed sites (empty when DN_FAULTS is
    unset/malformed) — `dn serve` /stats and the chaos soak's
    assertions read this."""
    table = _registry()
    if isinstance(table, DNError):
        return {}
    out = {}
    for site, ent in table.items():
        with ent.lock:
            out[site] = {'kind': ent.kind, 'rate': ent.rate,
                         'seed': ent.seed, 'checked': ent.checked,
                         'fired': ent.fired}
    return out


def total_fired():
    return sum(s['fired'] for s in stats().values())
