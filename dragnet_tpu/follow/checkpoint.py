"""The durable follow checkpoint: source identity + byte offset +
published-batch seq, updated atomically WITH each batch's shards.

`<indexroot>/.dn_follow/checkpoint.json` records, per source, the
file's stat identity (dev, ino) and the line-boundary byte offset
covered by every published batch, plus the monotonically increasing
batch seq.  The update never lands on its own: publisher.py writes
the new record to a journal-suffixed tmp (fsynced, like the commit
record itself) and hands it to publish_prepared's extra_paths, so it
renames into place under the SAME commit record as the batch's
shards.  Kill -9 anywhere leaves the recovery sweep exactly one
choice — roll the whole batch (shards AND checkpoint) forward, or
none of it — which is the entire exactly-once argument: the resume
offset and the published data cannot disagree.

Checkpoint-read errors on a tree that HAS follow state are fatal
(DNError), not a silent restart-from-zero: resuming at 0 over
already-published shards would duplicate every point."""

import json
import os
import time

from ..errors import DNError
from .. import faults as mod_faults
from ..index_journal import FOLLOW_DIR, _pid_alive

CHECKPOINT_VERSION = 1


class Checkpointer(object):
    def __init__(self, indexroot):
        self.indexroot = os.path.abspath(indexroot)
        self.dir = os.path.join(self.indexroot, FOLLOW_DIR)
        self.path = os.path.join(self.dir, 'checkpoint.json')

    def load(self):
        """The last committed checkpoint doc, or None when the tree
        has never been followed.  Malformed state raises DNError (see
        module docstring)."""
        try:
            with open(self.path) as f:
                doc = json.loads(f.read())
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            raise DNError('follow checkpoint "%s" unreadable: %s'
                          % (self.path, e))
        if not isinstance(doc, dict) or \
                not isinstance(doc.get('sources'), list):
            raise DNError('follow checkpoint "%s" malformed'
                          % self.path)
        return doc

    def clean_stale_tmps(self):
        """Unlink checkpoint tmps of dead writers that never reached a
        commit record (the journal sweep also quarantines these; this
        keeps the state dir tidy when no journal ever existed)."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for name in names:
            if not name.startswith('checkpoint.json.'):
                continue
            parts = name.split('.')
            pid = int(parts[2]) if len(parts) > 2 and \
                parts[2].isdigit() else None
            if pid is None or _pid_alive(pid):
                continue
            try:
                os.unlink(os.path.join(self.dir, name))
            except OSError:
                pass

    def prepare(self, journal, seq, sources):
        """Write the post-batch checkpoint to the journal's tmp name
        (fsynced tmp, no rename — publish_prepared renames it with the
        shard set).  `sources` is [(path, dev, ino, offset)].  Returns
        the final path for extra_paths."""
        mod_faults.fire('follow.checkpoint')
        os.makedirs(self.dir, exist_ok=True)
        doc = {
            'version': CHECKPOINT_VERSION,
            'pid': os.getpid(),
            'seq': seq,
            'build_id': journal.build_id,
            # wall clock ON PURPOSE (clock-audit, PR 7): a persisted
            # forensic timestamp read across processes (checkpoint
            # age in /stats), never a duration
            'time': time.time(),
            'sources': [{'path': p, 'dev': dev, 'ino': ino,
                         'offset': off}
                        for p, dev, ino, off in sources],
        }
        tmp = journal.tmp_for(self.path)
        try:
            with open(tmp, 'w') as f:
                f.write(json.dumps(doc))
                f.flush()
                os.fsync(f.fileno())
        except BaseException:
            # a half-written checkpoint tmp (ENOSPC mid-write) is
            # pre-commit litter, not recoverable intent — the retry
            # re-prepares from scratch; never strand it
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return self.path
