"""The `dn follow` daemon loop: poll sources -> cut mini-batches ->
scan -> merge-publish -> checkpoint, forever (or --once: catch up to
current EOF and exit).

Failure discipline: a failed publish keeps the cut batch pending and
retries with backoff — nothing landed (pre-commit failures abort
their tmps; post-commit failures leave recoverable intent the retry
completes and then skips via the checkpoint seq), so a retry is
exact.  A SIGTERM/SIGINT drain publishes the final batch and exits
only once the checkpoint covers every published byte; a held partial
line stays held for resumable files (it may still be mid-write —
only stdin, which cannot resume, flushes it at stop).

Telemetry: follow_* counters/gauges/histograms in the PR 7 registry
(Prometheus-exported), follow.scan / follow.publish spans, and the
process-wide `follow` stats section `/stats` and `dn stats` embed
(stats_doc below)."""

import json
import os
import signal
import sys
import threading
import time

from ..errors import DNError
from .. import jsvalues as jsv
from ..datasource_file import DatasourceFile
from ..obs import events as obs_events
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..vpipe import counter_bump
from .. import index_journal as mod_journal
from .. import resources as mod_resources
from .batcher import MiniBatcher
from .checkpoint import Checkpointer
from .publisher import merge_publish
from .tailer import STDIN, SourceTailer

_STATS_LOCK = threading.Lock()
_STATS = None                 # the live FollowLoop's stats snapshot


def stats_doc():
    """The `follow` stats section (None when no follow loop ever ran
    in this process) — `dn serve` /stats and `dn stats` embed it."""
    with _STATS_LOCK:
        return dict(_STATS) if _STATS is not None else None


def _publish_stats(doc):
    global _STATS
    with _STATS_LOCK:
        _STATS = doc


class FollowLoop(object):
    # consecutive publish failures tolerated while draining before
    # giving up with an error (a fault-armed soak must not wedge the
    # drain forever)
    DRAIN_PUBLISH_RETRIES = 3
    # consecutive all-error zero-byte poll passes tolerated in --once
    # before draining with exit code 1 instead of claiming caught-up
    ONCE_POLL_RETRIES = 5
    # disk-pressure pauses tolerated while DRAINING before giving up
    # (a transient full disk must not turn a drain into rc=1, but an
    # operator's SIGTERM must still win against a permanently full
    # one) — deliberately larger than the failure-streak budget:
    # pauses are EXPECTED under pressure, failures are not
    DRAIN_PAUSE_RETRIES = 10
    # publish-pause backoff ceiling (seconds)
    PAUSE_BACKOFF_MAX_S = 5.0
    # while paused, sources keep tailing only until the pending queue
    # holds this many mini-batches' worth of bytes — the follower
    # must not become its own memory exhaustion under a full disk
    PAUSE_QUEUE_BATCHES = 4

    def __init__(self, ds, metrics, interval, sources, conf,
                 once=False, warn=None):
        self.ds = ds
        self.metrics = metrics
        self.interval = interval
        self.conf = conf
        self.once = once
        self.warn = warn or (lambda msg: sys.stderr.write(
            'dn follow: %s\n' % msg))
        self.indexroot = ds.ds_indexpath
        self.ckpt = Checkpointer(self.indexroot)
        self.spool_path = os.path.join(self.ckpt.dir, 'spool.json')
        # the spool datasource: the batch bytes as a one-file corpus
        # under the follow datasource's format/timefield/filter — the
        # scan path (byteparse lanes included) is the build's own
        self.spool_ds = DatasourceFile({
            'ds_backend_config': {'path': self.spool_path,
                                  'indexPath': None,
                                  'timeFormat': None,
                                  'timeField': ds.ds_timefield},
            'ds_format': ds.ds_format,
            'ds_filter': ds.ds_filter,
        })
        self.batcher = MiniBatcher(conf['latency_ms'],
                                   conf['max_bytes'])
        self.tailers = [SourceTailer(p) for p in sources]
        self.seq = 0
        self.batches = 0
        self.records = 0
        self.nbytes = 0
        self.ckpt_wall = None
        self.lag_ms = 0.0
        self._stop = threading.Event()
        # resource governance (resources.py): low/critical disk
        # pressure PAUSES publishing — checkpoint held, sources keep
        # tailing into the bounded queue, automatic resume when space
        # frees — instead of burning the failure streak on a
        # transient full disk
        from .. import config as mod_config
        res_conf = mod_config.resources_config()
        if isinstance(res_conf, DNError):
            # the CLI validates up front; an embedder's bad env must
            # not crash the loop — fall back to defaults
            res_conf = mod_config.resources_config(env={})
        self.governor = mod_resources.ResourceGovernor(
            res_conf, paths=[self.indexroot])
        self.pauses = 0

    def request_stop(self):
        self._stop.set()

    # -- resume -----------------------------------------------------------

    def resume(self):
        """Recover the tree (roll any dead batch forward/back), then
        position every tailer from the committed checkpoint: matching
        identity resumes at its offset; a changed identity (rotated
        while down) or a fresh source starts at 0."""
        mod_journal.sweep_index_tree(self.indexroot)
        os.makedirs(self.ckpt.dir, exist_ok=True)
        self.ckpt.clean_stale_tmps()
        doc = self.ckpt.load()
        bysrc = {}
        if doc is not None:
            self.seq = int(doc.get('seq') or 0)
            self.ckpt_wall = doc.get('time')
            bysrc = {s.get('path'): s for s in doc['sources']}
        for t in self.tailers:
            if t.is_stdin:
                if bysrc.get(STDIN):
                    self.warn('stdin source cannot resume from a '
                              'checkpoint; reading from the current '
                              'position')
                continue
            ent = bysrc.get(t.path)
            ident = t.identity()
            if ident is None:
                continue             # created later; opens lazily
            if ent is not None and ident == (ent.get('dev'),
                                             ent.get('ino')):
                t.open_at(int(ent.get('offset') or 0))
            else:
                if ent is not None:
                    self.warn('source "%s" rotated while down; '
                              'restarting from offset 0' % t.path)
                t.open_at(0)

    # -- one batch --------------------------------------------------------

    def _offsets(self):
        return [(t.path, t.dev, t.ino, t.line_off)
                for t in self.tailers]

    def _scan(self, batch):
        """The batch through the build's own scan path: spool file +
        index_scan -> tagged aggregated points."""
        with open(self.spool_path + '.w', 'wb') as f:
            f.write(batch.data)
        os.replace(self.spool_path + '.w', self.spool_path)
        result = self.spool_ds.index_scan(self.metrics, self.interval,
                                          filter=self.ds.ds_filter)
        return result.points or []

    def publish_batch(self, batch, recover=True):
        """Scan + merge-publish + checkpoint one batch (raises on
        failure with nothing landed or recoverable intent only).
        `recover=False` skips merge_publish's sweep/own-journal
        recovery — the loop passes it on the clean path (resume()
        already swept; see publisher.merge_publish)."""
        with obs_metrics.timed_stage('follow.scan',
                                     metric='follow_scan_ms',
                                     labels={},
                                     nbytes=batch.nbytes):
            tagged = self._scan(batch)
        new_seq = self.seq + 1
        sources = [(p, dev, ino, off)
                   for p, dev, ino, off in batch.offsets]
        with obs_metrics.timed_stage('follow.publish',
                                     metric='follow_publish_ms',
                                     labels={},
                                     npoints=len(tagged)):
            paths = merge_publish(self.metrics, self.interval,
                                  self.indexroot, self.ds.ds_timefield,
                                  tagged, self.ckpt, new_seq, sources,
                                  recover=recover,
                                  append=bool(
                                      self.conf.get('append')))
        self.seq = new_seq
        self.batches += 1
        self.records += batch.nlines
        self.nbytes += batch.nbytes
        self.ckpt_wall = time.time()
        counter_bump('follow batches published')
        counter_bump('follow records ingested', batch.nlines)
        obs_metrics.inc('follow_batches_total')
        obs_metrics.inc('follow_records_total', batch.nlines)
        obs_metrics.inc('follow_bytes_total', batch.nbytes)
        obs_metrics.inc('follow_shards_published_total', len(paths))
        obs_metrics.observe(
            'follow_append_to_queryable_ms',
            (time.monotonic() - batch.first_t) * 1000.0)
        newest_ms = self._batch_newest_ms(batch)
        if newest_ms is not None:
            self.lag_ms = max(0.0, time.time() * 1000.0 - newest_ms)
            obs_metrics.set_gauge('follow_ingest_lag_ms', self.lag_ms)

    def _batch_newest_ms(self, batch):
        """The raw timefield of the batch's LAST complete record (ms
        since epoch), or None.  Log streams are near time-ordered, so
        the final record approximates the newest — and unlike the
        aggregated points' __dn_ts (quantized to the BUCKET start, up
        to a full day early), it is an actual record timestamp the
        ingest-lag gauge can honestly compare to the wall clock."""
        timefield = getattr(self.ds, 'ds_timefield', None)
        if not timefield:
            return None
        data = batch.data
        end = data.rfind(b'\n')
        if end <= 0:
            return None
        start = data.rfind(b'\n', 0, end) + 1
        try:
            rec = json.loads(data[start:end])
        except (ValueError, UnicodeDecodeError):
            return None
        v = jsv.pluck(rec, timefield)
        if isinstance(v, bool):
            return None
        if isinstance(v, (int, float)):
            return float(v) * 1000.0     # epoch seconds, like __dn_ts
        return jsv.date_parse(v)

    # -- telemetry --------------------------------------------------------

    def _refresh_stats(self):
        now = time.time()
        age = round(now - self.ckpt_wall, 3) \
            if self.ckpt_wall is not None else None
        srcs = []
        for t in self.tailers:
            srcs.append({'path': t.path, 'offset': t.line_off,
                         'dev': t.dev, 'ino': t.ino})
            obs_metrics.set_gauge('follow_source_offset',
                                  t.line_off, source=t.path)
        if age is not None:
            obs_metrics.set_gauge('follow_checkpoint_age_s', age)
        _publish_stats({
            'seq': self.seq,
            'batches_published': self.batches,
            'records': self.records,
            'bytes': self.nbytes,
            'pending_bytes': self.batcher.pending_bytes(),
            'checkpoint_age_s': age,
            'ingest_lag_ms': round(self.lag_ms, 3),
            'publish_pauses': self.pauses,
            'sources': srcs,
        })

    # -- the loop ---------------------------------------------------------

    def _poll_all(self):
        """One pass over every source; returns (bytes READ, sources
        that errored).  Bytes read, not bytes completed — the idle
        test must see mid-line progress too."""
        pre = sum(t.read_off for t in self.tailers)
        errs = 0
        for t in self.tailers:
            try:
                buf = t.poll()
            except DNError as e:
                self.warn(str(getattr(e, 'message', e)))
                errs += 1
                continue
            if buf:
                self.batcher.add(buf)
        return sum(t.read_off for t in self.tailers) - pre, errs

    def _note_pause(self, stopping, why):
        """One disk-pressure pause tick: counted, surfaced, bounded
        backoff (the checkpoint is HELD — nothing published, nothing
        lost; the retry is exact)."""
        self.pauses += 1
        counter_bump('follow publishes paused')
        obs_metrics.inc('follow_publish_pauses_total')
        obs_events.emit_burst('resource.paused', key='follow',
                             component='follow', why=why)
        if self.pauses == 1 or stopping:
            self.warn('publish paused: %s (checkpoint held; '
                      'resuming when the resource frees)' % why)
        delay = min(self.PAUSE_BACKOFF_MAX_S,
                    (self.conf['poll_ms'] / 1000.0) *
                    max(1, self.pauses))
        if self._stop.is_set():
            # draining: _stop is already set, so waiting on it would
            # return instantly and burn every DRAIN_PAUSE_RETRIES in
            # milliseconds — the pause must really pace the drain
            time.sleep(delay)
        else:
            self._stop.wait(delay)

    def run(self):
        with obs_trace.span('follow.resume'):
            self.resume()
        self._refresh_stats()
        poll_s = self.conf['poll_ms'] / 1000.0
        pause_cap = self.PAUSE_QUEUE_BATCHES * self.conf['max_bytes']
        pending = None
        fails = 0
        drain_pauses = 0
        attempt_recover = False
        poll_fails = 0
        once_rc = 0
        draining = False
        while True:
            stopping = self._stop.is_set() or draining
            paused = self.governor.mode() != 'ok'
            got = errs = 0
            if not stopping and not (paused and
                                     self.batcher.pending_bytes() >=
                                     pause_cap):
                # under pressure the sources keep tailing only until
                # the pending queue holds PAUSE_QUEUE_BATCHES batches
                # of bytes — bounded, like everything else here
                got, errs = self._poll_all()
            if self.once and not stopping:
                # --once promises "ingest to the sources' current
                # EOF": a pass that read nothing because a source
                # ERRORED is not caught up — retry (the poll wait at
                # the bottom paces it) up to a bounded streak, then
                # drain what we have and exit non-zero
                if errs and not got:
                    poll_fails += 1
                    if poll_fails >= self.ONCE_POLL_RETRIES:
                        self.warn('giving up on --once catch-up '
                                  'after %d failed poll passes'
                                  % poll_fails)
                        once_rc = 1
                        stopping = True
                elif got:
                    poll_fails = 0
                if not got and not errs:
                    # caught up: one full pass read nothing new.
                    # Enter the drain even with a batch pending — the
                    # drain publishes it (or gives up at the retry
                    # cap); gating on pending would retry a failing
                    # publish forever
                    stopping = True
            if stopping and not draining:
                # `draining` is sticky so a --once publish-failure
                # streak still reaches the retry cap below.  EOF-at-
                # stop flushes only sources that cannot resume (stdin
                # has no durable identity): a regular file's held
                # partial line may still be MID-WRITE — it stays
                # held, the checkpoint stays on a line boundary, and
                # a restarted follower parses the completed line
                # exactly once (docs/ingest.md)
                draining = True
                for t in self.tailers:
                    if t.is_stdin:
                        tail = t.flush_tail()
                        if tail:
                            self.batcher.add(tail)
            if pending is None and \
                    (self.batcher.ready() or
                     (stopping and self.batcher.pending_bytes() > 0)):
                pending = self.batcher.cut(self._offsets())
            if pending is not None and paused and not stopping:
                # pressure pause: hold the batch (and its checkpoint)
                # without even attempting the publish — hammering a
                # known-full disk buys nothing, and every attempt is
                # an abort/retry cycle
                self._note_pause(stopping,
                                 'disk %s' % self.governor.mode())
            elif pending is not None:
                try:
                    # recovery only on a retry: a failed previous
                    # attempt is the one in-process way journal
                    # intent can be left on this single-writer tree
                    self.publish_batch(pending,
                                       recover=attempt_recover)
                    pending = None
                    fails = 0
                    drain_pauses = 0
                    attempt_recover = False
                    if self.pauses:
                        self.pauses = 0
                        self.warn('publish resumed')
                except (DNError, OSError) as e:
                    attempt_recover = True
                    if mod_resources.is_pressure_error(e):
                        # ENOSPC/EMFILE is PAUSABLE, not a failure:
                        # the checkpoint is held, nothing landed (or
                        # recoverable intent only — the retry
                        # completes it), and the streak that would
                        # end a drain with rc=1 is not burned on a
                        # transient full disk
                        self.governor.note_pressure_error(
                            e if isinstance(e, OSError) else None)
                        if stopping:
                            drain_pauses += 1
                            if drain_pauses >= \
                                    self.DRAIN_PAUSE_RETRIES:
                                self.warn(
                                    'giving up on the drain: disk '
                                    'pressure outlasted %d pause(s)'
                                    % drain_pauses)
                                self._refresh_stats()
                                return 1
                        self._note_pause(
                            stopping, str(getattr(e, 'message',
                                                  None) or e))
                    elif isinstance(e, OSError):
                        raise
                    else:
                        fails += 1
                        self.warn('publish failed (attempt %d): %s'
                                  % (fails, getattr(e, 'message', e)))
                        if stopping and \
                                fails >= self.DRAIN_PUBLISH_RETRIES:
                            self._refresh_stats()
                            return 1
                        time.sleep(min(2.0, poll_s * fails))
            self._refresh_stats()
            if stopping and pending is None and \
                    self.batcher.pending_bytes() == 0:
                return once_rc
            if not got and pending is None and not stopping:
                self._stop.wait(poll_s)


def follow_main(ds, metrics, interval, sources, conf, once=False):
    """CLI entry: run the loop until drained (or caught up with
    --once).  Returns the process exit code."""
    loop = FollowLoop(ds, metrics, interval, sources, conf, once=once)
    if not once:
        def on_signal(signo, frame):
            loop.request_stop()
        try:
            signal.signal(signal.SIGTERM, on_signal)
            signal.signal(signal.SIGINT, on_signal)
        except ValueError:
            pass                 # not the main thread (tests)
        sys.stderr.write(
            'dn follow: following %d source(s) -> %s (pid %d)\n'
            % (len(sources), ds.ds_indexpath, os.getpid()))
    rc = loop.run()
    if not once:
        sys.stderr.write('dn follow: drained; exiting\n')
    return rc
