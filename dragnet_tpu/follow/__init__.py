"""`dn follow` — continuous ingest: tail live streams into
incrementally-published indexes.

The batch pipeline this repo grew (byteparse -> columnar scan ->
journaled index publish) assumed a frozen corpus; the prototypical
workload — production HTTP request logs — is a live stream.  This
package closes the gap with a long-lived ingest daemon:

* ``tailer``     — tail growing files (and stdin): bounded reads,
  rotation/truncation detection via stat identity, and the
  held-partial-line discipline (ingest.LineAssembler) so a chunk
  ending mid-line is never parsed as a truncated record.
* ``batcher``    — assemble complete-line buffers into mini-batches
  cut by target latency (DN_FOLLOW_LATENCY_MS) and/or byte budget
  (DN_FOLLOW_MAX_BYTES), StreamBox-HBM's target-latency batching.
* ``publisher``  — run each mini-batch through the existing
  byteparse -> columnar -> index path (a spool DatasourceFile +
  index_scan), merge the new points into the affected shards
  (read-modify-publish through the metric_rows seam), and publish the
  whole touched-shard set two-phase through the PR 6 commit journal.
* ``checkpoint`` — the durable source-offset record
  (`<indexroot>/.dn_follow/checkpoint.json`).  Its update rides the
  SAME commit journal as the shards (publish_prepared extra_paths),
  which is what makes ingest exactly-once across kill -9: a reader
  only ever sees a pre-batch or post-batch (shards AND checkpoint)
  tree, so the resume offset can never disagree with the published
  data.
* ``loop``       — the daemon: poll -> batch -> publish, drain-safe
  stop, --once catch-up mode, follow.* fault seams, and the
  follow telemetry (/stats `follow` section + follow_* metrics in
  the PR 7 registry).

See docs/ingest.md for the model, the checkpoint format, rotation
semantics, and the exactly-once guarantee's boundaries.
"""

from .loop import stats_doc  # noqa: F401  (the /stats `follow` seam)
