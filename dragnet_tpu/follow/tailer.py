"""Source tailers: bounded reads from growing files and stdin with
rotation/truncation detection and held-partial-line assembly.

Each source tracks two positions:

* ``read_off``  — how many bytes have been read off the current file;
* ``line_off``  — ``read_off`` minus the bytes the LineAssembler is
  holding mid-line.  This is the only position the checkpoint may
  record: it always lands on a line boundary, so a resume re-reads
  nothing and skips nothing.

Rotation is detected the way index_query_mt's handle cache keys
shards: by stat identity (st_dev, st_ino).  When the path's identity
no longer matches the open descriptor, the old file is drained to
EOF (its trailing unterminated line, if any, is flushed as a final
record — the file is over), then the new file opens at offset 0.
In-place truncation (copytruncate rotation: same inode, size below
our read position) reopens at 0 and DROPS the held partial — the
bytes it came from no longer exist in the file.
"""

import os
import select
import sys

from ..errors import DNError
from .. import faults as mod_faults
from ..ingest import LineAssembler

STDIN = '-'


class SourceTailer(object):
    """One growing source.  poll() returns a buffer of newly completed
    lines (b'' when nothing new), advancing read_off/line_off."""

    def __init__(self, path, chunk_size=1 << 20):
        self.path = path
        self.chunk_size = chunk_size
        self.asm = LineAssembler()
        self.read_off = 0
        self.is_stdin = path == STDIN
        self.eof = False          # stdin only: the pipe closed
        self._f = None
        self.dev = 0
        self.ino = 0
        if self.is_stdin:
            self._f = getattr(sys.stdin, 'buffer', sys.stdin)

    @property
    def line_off(self):
        return self.read_off - self.asm.pending()

    # -- lifecycle --------------------------------------------------------

    def open_at(self, offset=0):
        """Open (or reopen) the file source at `offset` — resume
        entry; the caller verified the identity matches its
        checkpoint.  DNError when the file cannot be opened."""
        if self.is_stdin:
            return
        self._close()
        try:
            self._f = open(self.path, 'rb')
            st = os.fstat(self._f.fileno())
        except OSError as e:
            self._close()
            raise DNError('follow source "%s": %s' % (self.path, e))
        self.dev, self.ino = st.st_dev, st.st_ino
        if offset:
            self._f.seek(offset)
        self.read_off = offset
        self.asm = LineAssembler()

    def identity(self):
        """The path's CURRENT stat identity (dev, ino), or None when
        the file does not exist (pre-create / mid-rotation)."""
        try:
            st = os.stat(self.path)
        except OSError:
            return None
        return (st.st_dev, st.st_ino)

    def _close(self):
        if self._f is not None and not self.is_stdin:
            try:
                self._f.close()
            except OSError:
                pass
        self._f = None

    def close(self):
        self._close()

    # -- polling ----------------------------------------------------------

    def _read(self):
        mod_faults.fire('follow.read')
        try:
            return self._f.read(self.chunk_size)
        except OSError as e:
            raise DNError('follow source "%s": read: %s'
                          % (self.path, e))

    def _poll_stdin(self):
        """Bounded stdin read: select() first, so an idle pipe never
        wedges the loop (a blocking BufferedReader.read(n) would sit
        until n bytes or EOF, breaking the latency target AND the
        SIGTERM drain).  os.read returns whatever is available."""
        if self.eof:
            return b''
        try:
            fd = self._f.fileno()
        except (OSError, ValueError, AttributeError):
            fd = None
        if fd is None:
            chunk = self._read()     # test doubles without a real fd
        else:
            try:
                ready, _, _ = select.select([fd], [], [], 0)
            except (OSError, ValueError):
                ready = [fd]
            if not ready:
                return b''
            mod_faults.fire('follow.read')
            try:
                chunk = os.read(fd, self.chunk_size)
            except OSError as e:
                raise DNError('follow source "%s": read: %s'
                              % (self.path, e))
        if not chunk:
            self.eof = True
            return b''
        if isinstance(chunk, str):
            chunk = chunk.encode()
        self.read_off += len(chunk)
        return self.asm.feed(chunk)

    def poll(self):
        """Read whatever new bytes the source has; returns a buffer of
        complete lines (b'' when none completed).  Handles
        create-late, rotation, and truncation."""
        if self.is_stdin:
            return self._poll_stdin()
        if self._f is None:
            if self.identity() is None:
                return b''           # not created yet
            self.open_at(0)

        out = []
        # truncation is a STATE check (size fell below our position),
        # not something inferred from a failed read — test it before
        # reading.  A truncate-then-regrow that passes read_off
        # between two polls is stat-invisible (the copytruncate
        # hazard every stat-based tailer shares — the next read hands
        # back new content spliced at the old offset); rename
        # rotation has no such hole (docs/ingest.md).
        try:
            size = os.fstat(self._f.fileno()).st_size
        except OSError:
            size = self.read_off
        if size < self.read_off:
            # truncated in place: the held partial's bytes are gone
            # from the file — drop them and start over
            self.open_at(0)
        chunk = self._read()
        if chunk:
            self.read_off += len(chunk)
            buf = self.asm.feed(chunk)
            if buf:
                out.append(buf)
        else:
            # at EOF: check for rotation
            ident = self.identity()
            if ident is not None and ident != (self.dev, self.ino):
                # rotated: drain the old descriptor (already at EOF —
                # the read above returned b''), flush its tail as the
                # file's final record, and switch to the new file
                tail = self.asm.flush()
                if tail:
                    # the tail bytes were already counted in read_off;
                    # flushing just released them to line_off
                    out.append(tail + b'\n')
                try:
                    self.open_at(0)
                    buf = self.poll()
                    if buf:
                        out.append(buf)
                except DNError:
                    # the flushed tail must not be lost to a transient
                    # open/read failure on the NEW file: return what
                    # we have; the closed descriptor makes the next
                    # poll retry the open (at offset 0) cleanly
                    pass
        return b''.join(out)

    def flush_tail(self):
        """Emit the held partial line as a final record (newline-
        terminated for the batch buffer) and advance line_off past
        it.  Only for sources that are OVER: stdin at stop (no
        resume), and a rotated-away file (handled inside poll).  A
        live file's partial stays held — it may be mid-write, and a
        checkpoint past it could never be resumed exactly."""
        tail = self.asm.flush()
        if not tail:
            return b''
        return tail + b'\n'
