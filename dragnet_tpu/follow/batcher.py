"""Mini-batch assembly: complete-line buffers accumulate until the
target latency or byte budget cuts a batch.

StreamBox-HBM (PAPERS.md) makes the case for cutting mini-batches by
a *target latency* rather than a fixed record count: under light load
a small batch publishes quickly (bounded staleness), under heavy load
the byte budget bounds memory and amortizes the per-publish cost.
Both knobs are live here: a pending batch is cut when its OLDEST
bytes reach DN_FOLLOW_LATENCY_MS of age, or earlier when
DN_FOLLOW_MAX_BYTES of pending data accumulate.

A batch always takes *everything* pending — there is no partial cut —
so the per-source line offsets snapshotted at cut time describe
exactly the bytes published so far, which is what makes the offsets
checkpointable."""

import time


class Batch(object):
    """One cut mini-batch: the concatenated complete-line bytes, the
    per-source offset snapshot to checkpoint after publish, and the
    arrival time of its oldest bytes (append-to-queryable latency is
    measured against this)."""

    __slots__ = ('data', 'offsets', 'nbytes', 'nlines', 'first_t')

    def __init__(self, data, offsets, first_t):
        self.data = data
        self.offsets = offsets
        self.nbytes = len(data)
        self.nlines = data.count(b'\n')
        self.first_t = first_t


class MiniBatcher(object):
    def __init__(self, latency_ms, max_bytes):
        self.latency_s = latency_ms / 1000.0
        self.max_bytes = max_bytes
        self._bufs = []
        self._nbytes = 0
        self._first_t = None

    def add(self, buf):
        """Absorb one complete-line buffer from a tailer poll."""
        if not buf:
            return
        if self._first_t is None:
            self._first_t = time.monotonic()
        self._bufs.append(buf)
        self._nbytes += len(buf)

    def pending_bytes(self):
        return self._nbytes

    def age_s(self):
        if self._first_t is None:
            return 0.0
        return time.monotonic() - self._first_t

    def ready(self):
        """Cut now?  Byte budget reached, or the oldest pending bytes
        hit the target latency."""
        if self._nbytes <= 0:
            return False
        if self._nbytes >= self.max_bytes:
            return True
        return self.age_s() >= self.latency_s

    def cut(self, offsets):
        """Take everything pending as one Batch; `offsets` is the
        caller's per-source {path: (dev, ino, line_off)} snapshot,
        taken AFTER the last poll that fed this batch."""
        batch = Batch(b''.join(self._bufs), offsets,
                      self._first_t or time.monotonic())
        self._bufs = []
        self._nbytes = 0
        self._first_t = None
        return batch
