"""Mini-batch publish: scan the batch through the existing columnar
build path, merge its points into the affected shards, publish the
touched set (plus the checkpoint) through the two-phase journal.

Byte-equality with a from-scratch `dn build` over the same prefix is
structural, not tested-into-existence:

* The batch scans through the SAME path a build uses (a spool
  DatasourceFile + index_scan — byteparse lanes, datasource filter,
  metric filters, vectorized aggregation all included), so its tagged
  points are exactly the build's aggregates over the new records.
* Each touched shard is rewritten read-modify-publish: the existing
  rows (metric_rows, in stored == emission order) seed a fresh
  Aggregator for the metric's build query, then the batch's points
  merge in.  Aggregator key replay is order-preserving for string
  keys and re-sorts numeric keys at emission (aggr.key_items's
  documented equivalence), and because hour/day build queries prepend
  `__dn_ts` (step == the shard span) as the FIRST breakdown, every
  deeper level's insertion order is scoped to this shard's own
  records — no cross-shard order coupling.  The rewrite therefore
  emits exactly the rows, in exactly the order, a from-scratch build
  over old+new records would have written.
* Weight sums are exact for integral weights (the `json` format's
  weight-1 records, and any integer-valued stream).  Non-integral
  json-skinner weights can differ in the last ulp from a from-scratch
  build (float addition order), the same caveat index_query_stack's
  exactness gate documents.

The whole touched set — every rewritten shard AND the post-batch
checkpoint — publishes through one BuildJournal commit record
(publish_prepared extra_paths), so kill -9 at any instant leaves the
recovery sweep a pre-batch or post-batch tree, never a mix and never
a checkpoint that disagrees with the data.
"""

import os
from collections import OrderedDict

from ..errors import DNError
from .. import query as mod_query
from ..aggr import Aggregator, coerce_bucket_value
from ..vpipe import counter_bump
from .. import faults as mod_faults
from .. import index_journal as mod_journal
from ..index_build_mt import (_breakdown_positions, _notify_index_written,
                              _prepare_task, bucket_label, interval_span,
                              publish_prepared, run_flush_tasks)
from ..index_query import open_index
from ..index_sink import metric_catalog_rows, point_metric


def metric_contexts(metrics, interval, timefield):
    """(span, per-metric ctx) for the merge: the metric's build query
    (metric_query — identical to what build/index_scan aggregate
    under), its breakdown names, and its bucketizers."""
    span = None if interval == 'all' else interval_span(interval)
    ctxs = []
    for m in metrics:
        q = mod_query.metric_query(m, None, None, interval, timefield)
        ctxs.append({
            'q': q,
            'names': [b['b_name'] for b in m.m_breakdowns],
            'bz': q.qc_bucketizers,
            'ts_bz': q.qc_bucketizers.get('__dn_ts'),
        })
    return span, ctxs


def _bucket_key(ctx, fields, missing_ok=False):
    """A tagged point's key tuple in the metric's aggregator key space
    (ordinals for bucketized fields, stored strings otherwise) — the
    exact inverse of points() decoding (bucketize(bucket_min(i)) == i
    for both bucketizers)."""
    keys = []
    if ctx['ts_bz'] is not None:
        v = coerce_bucket_value(fields.get('__dn_ts'))
        if v is None:
            raise DNError('index point has non-numeric "__dn_ts": %r'
                          % (fields.get('__dn_ts'),))
        keys.append(ctx['ts_bz'].bucketize(v))
    for name in ctx['names']:
        if name not in fields:
            raise DNError('point is missing breakdown "%s"' % name)
        v = fields[name]
        bz = ctx['bz'].get(name)
        if bz is not None:
            cv = coerce_bucket_value(v)
            if cv is None:
                raise DNError('value for field "%s" is not a number'
                              % name)
            keys.append(bz.bucketize(cv))
        else:
            keys.append(v)
    return tuple(keys)


def _row_key(ctx, ts_ord, row_keys):
    """A stored shard row's key tuple in the same key space (seeding):
    `ts_ord` is the shard's own __dn_ts ordinal (every row of an
    hour/day shard shares it — the shard IS the bucket)."""
    keys = []
    if ts_ord is not None:
        keys.append(ts_ord)
    for name, v in zip(ctx['names'], row_keys):
        bz = ctx['bz'].get(name)
        if bz is not None:
            cv = coerce_bucket_value(v)
            if cv is None:
                raise DNError('index row has non-numeric value for '
                              'bucketized field "%s": %r' % (name, v))
            keys.append(bz.bucketize(cv))
        else:
            keys.append(v)
    return tuple(keys)


def _check_catalog(qr, metrics, path):
    """A shard about to be merged into must describe the SAME metric
    set the follow is building — a silent mismatch would scramble
    tables; fail clean instead."""
    mets = qr.qi_metrics
    ok = len(mets) == len(metrics)
    if ok:
        for met, m in zip(mets, metrics):
            if met['qm_label'] != m.m_name or \
                    [p.get('name') for p in met['qm_params']] != \
                    [b['b_name'] for b in m.m_breakdowns]:
                ok = False
                break
    if not ok:
        raise DNError('index "%s": shard metric catalog does not '
                      'match the follow configuration' % path)


def group_points(tagged, metrics, ctxs, span):
    """Route a batch's tagged points: bucket_start -> {metric index ->
    [(key_tuple, value)]}, preserving points() emission order (the
    order the merge replays them in)."""
    groups = OrderedDict()
    if span is None:
        groups[None] = OrderedDict()
    for fields, value in tagged:
        mi = point_metric(fields, len(metrics))
        if span is None:
            bucket_s = None
        else:
            dnts = coerce_bucket_value(fields.get('__dn_ts'))
            if dnts is None:
                raise DNError('index point has non-numeric '
                              '"__dn_ts": %r'
                              % (fields.get('__dn_ts'),))
            bucket_s = int(dnts // span) * span
        key = _bucket_key(ctxs[mi], fields)
        groups.setdefault(bucket_s, OrderedDict()) \
              .setdefault(mi, []).append((key, value))
    return groups


def _merged_parts(path, metrics, ctxs, span, bucket_s, new_by_mi):
    """One touched shard's merged write blocks [(mi, keycols,
    weights)]: existing rows seed, batch points merge, point_rows
    emits — see the module docstring for why this is byte-exact."""
    old = None
    if os.path.exists(path):
        qr = open_index(path)
        try:
            _check_catalog(qr, metrics, path)
            old = [qr.metric_rows(mi, ctxs[mi]['names'])
                   for mi in range(len(metrics))]
        finally:
            qr.close()
    parts = []
    for mi, ctx in enumerate(ctxs):
        items = new_by_mi.get(mi, [])
        aggr = Aggregator(ctx['q'])
        if old is not None and old[mi]:
            ts_ord = ctx['ts_bz'].bucketize(bucket_s) \
                if ctx['ts_bz'] is not None else None
            for row in old[mi]:
                aggr.write_key(_row_key(ctx, ts_ord, row[:-1]),
                               row[-1])
        if items:
            aggr.merge_key_items(items)
        cols, weights = aggr.point_rows()
        if not weights and span is not None:
            # a from-scratch hour/day build writes no block for a
            # metric with no rows in this bucket; mirror it
            continue
        sel = _breakdown_positions(list(aggr.decomps), metrics[mi])
        parts.append((mi, [cols[p] for p in sel], weights))
    return parts


def merge_publish(metrics, interval, indexroot, timefield, tagged,
                  checkpointer, seq, sources, nworkers=None,
                  recover=True, append=False):
    """Merge one batch's tagged points into the index tree and publish
    the touched shards + the post-batch checkpoint atomically.
    Returns the list of published shard paths.

    `recover=False` skips the tree sweep + own-journal recovery —
    three full directory listings per call otherwise.  Only safe when
    the caller KNOWS the tree is clean: FollowLoop sweeps once in
    resume() and passes recover=True only on the retry after a failed
    publish (the sole in-process way intent can be left behind on a
    single-follower tree).

    `append=True` (dn follow --append): a bucket whose base shard
    already exists lands the batch as a mini-generation
    (`<shard>.sqlite-gNNNNNN`, rollup.next_generation_path) instead of
    read-modify-rewriting the whole shard — O(batch) bytes per
    publish, no seed read.  Queries fold base+generations into one
    logical shard and the compactor (rollup.compact_tree) rewrites
    the group back to a single file.  A generation-number race with a
    concurrent compactor is benign: the compactor only consumes the
    generations it listed, a generation published after its listing
    survives next to the compacted base, and numbering gaps are fine
    (generation order is numeric over whatever exists).  Only hour/
    day trees append; the 'all' shard always merges in place."""
    span, ctxs = metric_contexts(metrics, interval, timefield)
    groups = group_points(tagged, metrics, ctxs, span)
    catalog = metric_catalog_rows(metrics)

    if recover:
        mod_journal.sweep_index_tree(indexroot)
        # a previous attempt that failed AFTER its commit record left
        # complete intent: finish its renames (quarantining them would
        # let this retry re-merge over a half-renamed tree and double-
        # count), then detect the completed batch via the checkpoint
        # seq and skip it — the retry-is-exact contract
        completed = mod_journal.recover_own_committed(indexroot)
        mod_journal.cleanup_own_stale(indexroot)
        if completed:
            _notify_index_written(indexroot, completed)
    # the seq backstop stays unconditional — one tiny-JSON read —
    # so a replayed batch can never double-apply
    doc = checkpointer.load()
    if doc is not None and int(doc.get('seq') or 0) >= seq:
        counter_bump('follow batch replays skipped')
        return []
    journal = mod_journal.BuildJournal(indexroot)

    if span is None:
        ordered_buckets = [None]
        root = indexroot
    else:
        ordered_buckets = sorted(groups)
        root = os.path.join(indexroot, 'by_' + interval)

    ngens = 0
    buckets = []
    for bucket_s in ordered_buckets:
        if bucket_s is None:
            path = os.path.join(root, 'all')
            config = None
        else:
            path = os.path.join(
                root, bucket_label(bucket_s, interval) + '.sqlite')
            config = {'dn_start': bucket_s}
        if append and bucket_s is not None and os.path.exists(path):
            from .. import rollup as mod_rollup
            # the generation path never exists, so _merged_parts
            # seeds nothing: the shard holds exactly this batch's
            # points for the bucket
            path = mod_rollup.next_generation_path(path)
            ngens += 1
        parts = _merged_parts(path, metrics, ctxs, span, bucket_s,
                              groups.get(bucket_s) or {})
        buckets.append((path, config, parts))
    if ngens:
        counter_bump('follow generations appended', ngens)

    paths = [p for p, config, parts in buckets]
    sinks = [None] * len(buckets)
    tasks = [_prepare_task(metrics, path, config, parts, catalog,
                           journal.tmp_suffix, sinks, i)
             for i, (path, config, parts) in enumerate(buckets)]
    try:
        run_flush_tasks(tasks, nworkers)
    except BaseException:
        for sink in sinks:
            if sink is not None:
                sink.abort()
        raise
    try:
        # the drill seam: an error here aborts the whole batch clean
        # (nothing landed, retry later); a kill here is the classic
        # crash between prepare and commit — the sweep rolls BACK and
        # the resumed follower re-ingests from the old checkpoint
        mod_faults.fire('follow.publish')
        ckpt_final = checkpointer.prepare(journal, seq, sources)
    except BaseException:
        for sink in sinks:
            if sink is not None:
                sink.abort()
        raise
    publish_prepared(journal, [s for s in sinks], paths,
                     extra_paths=[ckpt_final])
    _notify_index_written(indexroot, paths)
    return paths
