"""dn: the dragnet command-line interface.

Byte-compatible re-implementation of the reference CLI (bin/dn): the same
14 subcommands, dashdash-style option parsing with per-command option
whitelists, breakdown expansion (`-b a,b` == `-b a -b b`), and the output
layer (pretty tables, histograms, points, raw, gnuplot, counters).

Exit codes: 2 for usage errors (with the usage text on stderr), 1 for
fatal runtime errors ("dn: <message>").
"""

import sys

from .errors import DNError
from . import jsvalues as jsv
from . import attrs as mod_attrs
from . import config as mod_config
from . import query as mod_query
from . import output as mod_output
from .aggr import Aggregator
from . import __init__ as _facade  # noqa
from . import datasource_for_name, metrics_for_index, index_config

ARG0 = 'dn'

USAGE_TEXT = """usage: dn SUBCOMMAND [OPTIONS] ARGS

dn datasource-add    [--backend=file|cluster] --path=DATA_PATH
                     [--index-path=INDEX_PATH] [--filter=FILTER]
                     [--time-field=FIELD] [--time-format=TIME_FORMAT]
                     [--data-format=json|json-skinner] DATASOURCE
dn datasource-update [--backend=file|cluster] [--path=DATA_PATH]
                     [--index-path=INDEX_PATH] [--filter=FILTER]
                     [--time-field=FIELD] [--time-format=TIME_FORMAT]
                     [--data-format=json|json-skinner] DATASOURCE
dn datasource-list   [-v]
dn datasource-remove DATASOURCE
dn datasource-show   [-v] DATASOURCE

dn metric-add        [--breakdowns=BREAKDOWN[,...]] [--filter=FILTER]
\t\t     DATASOURCE METRIC
dn metric-list       [-v] DATASOURCE
dn metric-remove     DATASOURCE METRIC

dn build             [--before=START_TIME] [--after=END_TIME]
                     [--interval=hour|day|all] [--index-config=CONFIG_FILE]
                     [--dry-run] [--assetroot=ASSET_ROOT]
                     DATASOURCE

dn query             [--before=START_TIME] [--after=END_TIME] [--filter=FILTER]
                     [--breakdowns=BREAKDOWN[,...]] [--interval=hour|day|all]
                     [--raw] [--points] [--counters] [--gnuplot]
                     [--dry-run] [--assetroot=ASSET_ROOT]
                     DATASOURCE

dn scan              [--before=START_TIME] [--after=END_TIME] [--filter=FILTER]
                     [--breakdowns=BREAKDOWN[,...]]
                     [--raw] [--points] [--counters] [--warnings] [--dry-run]
                     [--assetroot=ASSET_ROOT] DATASOURCE

dn index-config      DATASOURCE
dn index-read        [--index-config=INDEX_CONFIG_FILE]
                     [--interval=hour|day|all]
                     DATASOURCE
dn index-scan        [--index-config=INDEX_CONFIG_FILE]
                     [--interval=hour|day|all]
                     [--before=START_TIME] [--after=END_TIME] [--filter=FILTER]
                     [--breakdowns=BREAKDOWN[,...]] [--counters] DATASOURCE
"""

# Global option table (reference: bin/dn:146-215).  Each entry:
# (names, type, default)
DN_OPTIONS = [
    (['after', 'A'], 'date', None),
    (['assetroot'], 'string', '/dragnet/assets'),
    (['backend'], 'string', None),
    (['before', 'B'], 'date', None),
    (['breakdowns', 'b'], 'arrayOfString', []),
    # index-build writer pool override (not in USAGE_TEXT: the usage
    # output is byte-pinned to the reference goldens; documented in
    # docs/performance.md).  Equivalent to DN_BUILD_THREADS for one run.
    (['build-threads'], 'string', None),
    # `dn serve` cluster mode: --cluster=TOPOLOGY.json names the
    # scatter-gather cluster map (defaults to DN_SERVE_TOPOLOGY when
    # set) and --member=NAME this server's identity in it.  Not in
    # USAGE_TEXT (byte-pinned); documented in docs/serving.md.
    (['cluster'], 'string', None),
    (['counters'], 'bool', None),
    (['data-format'], 'string', 'json'),
    (['datasource'], 'string', None),
    (['dry-run', 'n'], 'bool', False),
    (['filter', 'f'], 'string', None),
    (['gnuplot'], 'bool', None),
    (['interval', 'i'], 'string', 'day'),
    (['index-config'], 'string', None),
    # index-query worker pool override (not in USAGE_TEXT: the usage
    # output is byte-pinned to the reference goldens; documented in
    # docs/performance.md).  Equivalent to DN_IQ_THREADS for one run.
    (['iq-threads'], 'string', None),
    # stacked cross-shard index-query execution override (same
    # rationale for staying out of USAGE_TEXT).  Equivalent to
    # DN_IQ_STACK for one run: auto|0|1.
    (['iq-stack'], 'string', None),
    (['index-path'], 'string', None),
    (['member'], 'string', None),
    # `dn events --follow`: keep polling the remote journal and print
    # new entries as they land (docs/observability.md).  Distinct
    # from the `dn follow` SUBcommand.  Not in USAGE_TEXT
    # (byte-pinned).
    (['follow'], 'bool', None),
    # `dn follow` catch-up mode: ingest to the sources' current EOF,
    # publish, checkpoint, and exit instead of tailing forever.  Not
    # in USAGE_TEXT (byte-pinned); documented in docs/ingest.md.
    (['once'], 'bool', None),
    # ingest parse-lane override (not in USAGE_TEXT: the usage output
    # is byte-pinned to the reference goldens; documented in
    # docs/performance.md).  Equivalent to DN_PARSE for one run:
    # auto|host|vector|device.
    (['parse'], 'string', None),
    (['path'], 'string', None),
    # `dn serve` endpoint options (pidfile/port/socket/validate) and
    # the data commands' --remote endpoint (unix socket path or
    # HOST:PORT; unreachable servers warn and fall back to local
    # execution).  None appear in USAGE_TEXT — the usage output is
    # byte-pinned to the reference goldens; see docs/serving.md.
    (['pidfile'], 'string', None),
    (['points'], 'bool', None),
    (['port'], 'string', None),
    # `dn stats`: render the Prometheus text exposition instead of
    # the JSON stats document (docs/observability.md)
    (['prom'], 'bool', None),
    (['raw'], 'bool', None),
    (['remote'], 'string', None),
    (['socket'], 'string', None),
    (['time-field'], 'string', None),
    (['time-format'], 'string', None),
    # `dn topo` dynamic-topology options: --topology names the
    # coordinator file (defaults to DN_SERVE_TOPOLOGY), --wait bounds
    # a readiness wait in seconds, --force commits an unready
    # transition, --apply publishes a rebalance proposal.  Not in
    # USAGE_TEXT (byte-pinned); documented in docs/serving.md.
    (['topology'], 'string', None),
    (['wait'], 'string', None),
    (['force'], 'bool', None),
    (['apply'], 'bool', None),
    # `dn scrub` / `dn quarantine` integrity options: --tree limits
    # the walk to one index root, --repair pulls good copies from
    # cluster co-replicas, --check reports without quarantining,
    # --forget-missing drops catalog entries for shards gone from
    # disk, --older-than age-gates `dn quarantine clean`,
    # --max-bytes evicts oldest-first down to a byte budget.  Not in
    # USAGE_TEXT (byte-pinned); documented in docs/robustness.md.
    (['tree'], 'string', None),
    (['max-bytes'], 'string', None),
    (['repair'], 'bool', None),
    (['check'], 'bool', None),
    (['forget-missing'], 'bool', None),
    (['older-than'], 'string', None),
    # `dn compact`: only rewrite base shards holding at least this
    # many follow --append mini-generations (default 1 — fold
    # everything).  Not in USAGE_TEXT (byte-pinned); documented in
    # docs/robustness.md.
    (['min-gens'], 'string', None),
    # `dn subscribe` / `dn top --subscribe` standing-query options:
    # --subscribe switches `dn top` from fleet_stats polling to the
    # server push path, --frames bounds a `dn subscribe` stream to N
    # pushed frames (0 = run until interrupted; used by tests and
    # scripts that want one refresh).  Not in USAGE_TEXT (byte-pinned);
    # documented in docs/serving.md.
    (['subscribe'], 'bool', None),
    (['frames'], 'string', None),
    # per-run request tracing (equivalent to DN_TRACE=stderr for one
    # command; composes with --remote — the client ships its trace id
    # and grafts the server's span subtree).  Not in USAGE_TEXT: the
    # usage output is byte-pinned to the reference goldens; see
    # docs/observability.md.
    (['trace'], 'bool', None),
    (['validate'], 'bool', None),
    (['verbose', 'v'], 'bool', False),
    (['warnings'], 'bool', None),
]


class UsageError(Exception):
    def __init__(self, message=None):
        super(UsageError, self).__init__(message)
        self.message = message


class FatalError(Exception):
    def __init__(self, message):
        super(FatalError, self).__init__(message)
        self.message = message


def fatal(err):
    msg = err.message if hasattr(err, 'message') else str(err)
    raise FatalError(msg)


class Options(object):
    def __init__(self):
        self._args = []


def _option_config(useroptions):
    rv = []
    for name in useroptions:
        for entry in DN_OPTIONS:
            if name in entry[0]:
                rv.append(entry)
                break
        else:
            raise DNError('unknown option: "%s"' % name)
    return rv


def parse_args(argv, useroptions):
    """dashdash-style parse: long/short options, interspersed operands."""
    entries = _option_config(useroptions)
    byname = {}
    for entry in entries:
        for n in entry[0]:
            byname[n] = entry

    opts = Options()
    for entry in entries:
        key = entry[0][0].replace('-', '_')
        if entry[2] is not None or entry[1] == 'arrayOfString':
            setattr(opts, key, [] if entry[1] == 'arrayOfString'
                    else entry[2])
        else:
            setattr(opts, key, None)

    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg == '--':
            opts._args.extend(argv[i + 1:])
            break
        if arg.startswith('--'):
            body = arg[2:]
            if '=' in body:
                name, val = body.split('=', 1)
            else:
                name, val = body, None
            entry = byname.get(name)
            if entry is None:
                raise UsageError('unknown option: "--%s"' % name)
            if entry[1] == 'bool':
                if val is not None:
                    raise UsageError(
                        'argument not allowed for boolean arg: %s' % name)
                _set_opt(opts, entry, True)
            else:
                if val is None:
                    i += 1
                    if i >= len(argv):
                        raise UsageError(
                            'do not have enough args for "--%s" option'
                            % name)
                    val = argv[i]
                _set_opt(opts, entry, _parse_opt_value(entry, name, val))
        elif arg.startswith('-') and len(arg) > 1:
            j = 1
            while j < len(arg):
                name = arg[j]
                entry = byname.get(name)
                if entry is None:
                    raise UsageError('unknown option: "-%s"' % name)
                if entry[1] == 'bool':
                    _set_opt(opts, entry, True)
                    j += 1
                else:
                    rest = arg[j + 1:]
                    if rest == '':
                        i += 1
                        if i >= len(argv):
                            raise UsageError(
                                'do not have enough args for "-%s" option'
                                % name)
                        rest = argv[i]
                    _set_opt(opts, entry,
                             _parse_opt_value(entry, name, rest))
                    break
        else:
            opts._args.append(arg)
        i += 1
    return opts


def _set_opt(opts, entry, value):
    key = entry[0][0].replace('-', '_')
    if entry[1] == 'arrayOfString':
        getattr(opts, key).append(value)
    else:
        setattr(opts, key, value)


def _parse_opt_value(entry, name, val):
    if entry[1] == 'date':
        if val.isdigit():
            return int(val) * 1000
        ms = jsv.date_parse(val)
        if ms is None:
            raise UsageError('arg for "--%s" is not a valid date '
                             'format: "%s"' % (name, val))
        return ms
    return val


def expand_breakdowns(opts):
    """-b a,b[x=1] expansion + step validation
    (reference: bin/dn:283-309)."""
    if not hasattr(opts, 'breakdowns') or \
            not isinstance(opts.breakdowns, list):
        return
    tmp = opts.breakdowns
    opts.breakdowns = []
    for v in tmp:
        lst = mod_attrs.attrs_parse(v)
        if isinstance(lst, DNError):
            raise UsageError('bad value for "breakdowns" ("%s"): %s'
                             % (v, lst.message))
        for s in lst:
            if not s.get('field'):
                s['field'] = s['name']
            if 'step' in s:
                step = mod_query._parse_int(s['step'])
                if step is None:
                    raise UsageError('field "%s": "step" must be a number'
                                     % s['name'])
                s['step'] = step
            opts.breakdowns.append(s)


def dn_parse_args(argv, useroptions):
    opts = parse_args(argv, useroptions)
    expand_breakdowns(opts)
    if getattr(opts, 'filter', None):
        try:
            opts.filter = jsv.json_parse(opts.filter)
        except ValueError as e:
            raise UsageError('invalid filter: %s' % e)
    return opts


def check_arg_count(opts, expected):
    if len(opts._args) < expected:
        raise UsageError('missing arguments')
    if len(opts._args) > expected:
        raise UsageError('extra arguments')


# ---------------------------------------------------------------------------
# Config commands
# ---------------------------------------------------------------------------

def _save(ctx, newconfig):
    if isinstance(newconfig, DNError):
        fatal(newconfig)
    ctx['backend'].save(newconfig.serialize())
    ctx['config'] = newconfig


def cmd_datasource_add(ctx, argv):
    opts = dn_parse_args(argv, ['backend', 'data-format', 'filter', 'path',
                                'time-field', 'time-format', 'index-path'])
    if not opts.path:
        raise UsageError('"path" option is required')
    check_arg_count(opts, 1)
    dsname = opts._args[0]
    dsconfig = {
        'name': dsname,
        'backend': opts.backend or 'file',
        'backend_config': {
            'path': opts.path,
            'indexPath': opts.index_path,
            'timeFormat': opts.time_format,
            'timeField': opts.time_field,
        },
        'filter': opts.filter if opts.filter is not None else None,
        'dataFormat': opts.data_format,
    }
    _save(ctx, ctx['config'].datasource_add(dsconfig))


def cmd_datasource_update(ctx, argv):
    opts = dn_parse_args(argv, ['backend', 'data-format', 'filter', 'path',
                                'time-field', 'time-format', 'index-path'])
    check_arg_count(opts, 1)
    dsname = opts._args[0]
    dsupdate = {
        'backend': opts.backend,
        'backend_config': {
            'path': opts.path,
            'indexPath': opts.index_path,
            'timeFormat': opts.time_format,
            'timeField': opts.time_field,
        },
        'filter': opts.filter if opts.filter is not None else None,
        'dataFormat': opts.data_format,
    }
    _save(ctx, ctx['config'].datasource_update(dsname, dsupdate))


def cmd_datasource_remove(ctx, argv):
    opts = dn_parse_args(argv, [])
    check_arg_count(opts, 1)
    _save(ctx, ctx['config'].datasource_remove(opts._args[0]))


def _datasource_print(out, dsname, ds, verbose):
    if ds['ds_backend'] == 'manta':
        location = 'manta://us-east.manta.joyent.com%s' \
            % ds['ds_backend_config'].get('path')
    else:
        location = 'file:/%s' % ds['ds_backend_config'].get('path')
    out.write('%-20s %-59s\n' % (dsname, location))
    if not verbose:
        return
    if ds['ds_filter'] is not None:
        out.write('%4s%-11s %s\n' % ('', 'filter:',
                                     jsv.json_stringify(ds['ds_filter'])))
    out.write('%4s%-11s %s\n' % ('', 'dataFormat:',
                                 jsv.json_stringify(ds['ds_format'])))
    for k, v in ds['ds_backend_config'].items():
        if k == 'path':
            continue
        sv = jsv.json_stringify(v)
        if sv is None:
            sv = 'undefined'
        out.write('%4s%-11s %s\n' % ('', k + ':', sv))


def cmd_datasource_list(ctx, argv):
    opts = dn_parse_args(argv, ['verbose'])
    check_arg_count(opts, 0)
    out = sys.stdout
    out.write('%-20s %-59s\n' % ('DATASOURCE', 'LOCATION'))
    for dsname, ds in ctx['config'].datasource_list():
        _datasource_print(out, dsname, ds, opts.verbose)


def cmd_datasource_show(ctx, argv):
    opts = dn_parse_args(argv, ['verbose'])
    check_arg_count(opts, 1)
    dsname = opts._args[0]
    ds = ctx['config'].datasource_get(dsname)
    if ds is None:
        fatal(DNError('unknown datasource: "%s"' % dsname))
    out = sys.stdout
    out.write('%-20s %-59s\n' % ('DATASOURCE', 'LOCATION'))
    _datasource_print(out, dsname, ds, opts.verbose)


def cmd_metric_add(ctx, argv):
    opts = dn_parse_args(argv, ['breakdowns', 'filter'])
    check_arg_count(opts, 2)
    mconfig = {
        'name': opts._args[1],
        'datasource': opts._args[0],
        'filter': opts.filter or None,
        'breakdowns': opts.breakdowns,
    }
    _save(ctx, ctx['config'].metric_add(mconfig))


def cmd_metric_remove(ctx, argv):
    opts = dn_parse_args(argv, [])
    check_arg_count(opts, 2)
    _save(ctx, ctx['config'].metric_remove(opts._args[0], opts._args[1]))


def cmd_metric_list(ctx, argv):
    opts = dn_parse_args(argv, ['verbose'])
    check_arg_count(opts, 1)
    dsname = opts._args[0]
    out = sys.stdout
    out.write('%-20s %-20s\n' % ('DATASOURCE', 'METRIC'))
    config = ctx['config']
    if config.datasource_get(dsname) is None:
        fatal(DNError('unknown datasource: "%s"' % dsname))
    for metname, m in config.datasource_list_metrics(dsname):
        out.write('%-20s %-20s\n' % (m.m_datasource, metname))
        if not opts.verbose:
            continue
        if m.m_filter is not None:
            out.write('%4s%-11s %s\n' % ('', 'filter:',
                                         jsv.json_stringify(m.m_filter)))
        if len(m.m_breakdowns) == 0:
            continue
        out.write('%4s%-11s %s\n' % ('', 'breakdowns:', ', '.join(
            b['b_name'] for b in m.m_breakdowns)))


# ---------------------------------------------------------------------------
# Data commands
# ---------------------------------------------------------------------------

def dn_query_doc(opts):
    """The query document parsed options produce — query_load's input
    here, and the document `--remote` ships so the server's
    query_load yields the identical QueryConfig."""
    queryconfig = {'breakdowns': opts.breakdowns}
    if opts.after:
        queryconfig['timeAfter'] = opts.after
    if opts.before:
        queryconfig['timeBefore'] = opts.before
    if opts.filter is not None:
        queryconfig['filter'] = opts.filter
    return queryconfig


def dn_query_config(opts):
    qc = mod_query.query_load(dn_query_doc(opts))
    if isinstance(qc, DNError):
        fatal(qc)

    if getattr(opts, 'gnuplot', None) and len(qc.qc_breakdowns) != 1:
        fatal(DNError(
            '--gnuplot can only be used with exactly one breakdown'))
    return qc


def dn_output(query, opts, result, dsname):
    """(reference: bin/dn:924-967)"""
    pipeline = result.pipeline

    # multi-process SPMD runs: every process computes the full result
    # (allgather), but only process 0 prints it — the analog of the
    # reference's client fetching the single job output.  Dry-run plans
    # still print everywhere: each process's plan shows ITS partition.
    if result.dry_run_files is None:
        from .parallel import distributed as mod_dist
        if not mod_dist.is_output_process():
            return

    if result.dry_run_files is not None:
        plan = getattr(result, 'dry_run_plan', None)
        if plan is not None:
            # cluster backend: the execution plan, then the inputs —
            # the reference printed its Manta job JSON the same way
            # (lib/datasource-manta.js:446-454)
            import json as mod_json
            partition = plan.get('partition', [])
            head = {k: v for k, v in plan.items() if k != 'partition'}
            sys.stderr.write(mod_json.dumps(head, indent=4) + '\n')
            sys.stderr.write('\nInputs:\n')
            for path in partition:
                sys.stderr.write('%s\n' % path)
            return
        sys.stderr.write('would scan files:\n')
        for path in result.dry_run_files:
            sys.stderr.write('    %s\n' % path)
        # parse-lane plan line: shown when the operator asked about it
        # (an explicit DN_PARSE / --parse, or the full-counters view) —
        # the default dry-run output stays byte-pinned to the
        # reference goldens
        import os
        pp = getattr(result, 'parse_plan', None)
        if pp is not None and (os.environ.get('DN_COUNTERS_ALL') == '1'
                               or pp.get('parse_mode') != 'auto'):
            sys.stderr.write('parse lane: %s (%s)\n'
                             % (pp['parse_lane'], pp['reason']))
        return

    points = result.points or []
    if getattr(opts, 'points', None):
        mod_output.print_points(points, sys.stdout)
    else:
        flattener = pipeline.stage('Flattener')
        flat = Aggregator(query)
        for fields, value in points:
            flattener.bump('ninputs')
            flat.write(fields, value)
        rows = flat.rows()
        flattener.bump('noutputs')

        if getattr(opts, 'raw', None):
            mod_output.output_raw(rows, sys.stdout)
        elif getattr(opts, 'gnuplot', None):
            mod_output.output_gnuplot(query, rows, dsname, sys.stdout)
        else:
            mod_output.output_pretty(query, rows, sys.stdout)

    if getattr(opts, 'counters', None):
        pipeline.dump_counters(sys.stderr)


def _env_scope(envname, value):
    """Set `envname` for the duration of one command (None leaves it
    untouched): the datasource layer reads the env, and it must be
    restored because the parity harness drives these entry points
    in-process."""
    import contextlib
    import os

    @contextlib.contextmanager
    def scope():
        prior = os.environ.get(envname)
        if value is not None:
            os.environ[envname] = value
        try:
            yield
        finally:
            if value is not None:
                if prior is None:
                    os.environ.pop(envname, None)
                else:
                    os.environ[envname] = prior
    return scope()


def _pool_flag_env(optname, value, envname):
    """Plumb a per-run worker-pool flag (--iq-threads,
    --build-threads) through its env var for the duration of the
    command.  Unlike the env var, a bad explicit flag value is a
    usage error, not a silent fallback to sequential."""
    if value is not None and value != 'auto':
        try:
            if int(value) < 0:
                raise ValueError(value)
        except ValueError:
            raise UsageError('bad value for "%s": "%s"'
                             % (optname, value))
    return _env_scope(envname, value)


def _mode_flag_env(optname, value, envname, allowed):
    """_pool_flag_env for enumerated-mode flags (--iq-stack)."""
    if value is not None and value not in allowed:
        raise UsageError('bad value for "%s": "%s"' % (optname, value))
    return _env_scope(envname, value)


def _obs_command(op, opts):
    """Observability scope for one data command: installs a request
    trace context when asked (--trace, DN_TRACE, DN_SLOW_MS) —
    emitting one JSON span-tree line at command end — and nothing at
    all otherwise (output stays byte-identical by construction:
    tracing writes to the DN_TRACE sink / process stderr only when
    armed).  --trace is DN_TRACE=stderr for one run, without
    clobbering an explicit DN_TRACE target."""
    import contextlib
    import os
    from .obs import trace as obs_trace

    @contextlib.contextmanager
    def scope():
        explicit = bool(getattr(opts, 'trace', None))
        value = 'stderr' if explicit and \
            not os.environ.get('DN_TRACE') else None
        with _env_scope('DN_TRACE', value):
            if explicit or obs_trace.tracing_requested():
                with obs_trace.request(op):
                    yield
            else:
                yield
    return scope()


def _warn_printer(stage, kind, error):
    sys.stderr.write('warn: %s\n' % (getattr(error, 'message', None) or
                                     str(error)))
    sys.stderr.write('    at %s\n' % stage.name)


# ---------------------------------------------------------------------------
# Remote execution (`--remote SOCK` -> a resident `dn serve`)
# ---------------------------------------------------------------------------

def _remote_output_opts(opts):
    return {
        'raw': bool(getattr(opts, 'raw', None)),
        'points': bool(getattr(opts, 'points', None)),
        'counters': bool(getattr(opts, 'counters', None)),
        'gnuplot': bool(getattr(opts, 'gnuplot', None)),
        'dry_run': bool(getattr(opts, 'dry_run', None)),
    }


# per-run execution-mode flags that scope a process-local env var for
# one command: they cannot travel to a shared server (whose process
# env governs every request), and silently dropping them would be a
# behavior change the user explicitly asked against
_LOCAL_ONLY_FLAGS = [('warnings', '--warnings'), ('parse', '--parse'),
                     ('iq_threads', '--iq-threads'),
                     ('iq_stack', '--iq-stack'),
                     ('build_threads', '--build-threads')]


def _try_remote(ctx, opts, req):
    """Ship `req` to opts.remote.  Returns the remote exit code, or
    None after the unreachable-fallback warning (the caller then runs
    the command locally).  Local-only flags must not silently go
    remote: --warnings needs the local per-record path, and the
    execution-mode flags above only scope this process's env."""
    for attr, flag in _LOCAL_ONLY_FLAGS:
        if getattr(opts, attr, None):
            raise UsageError(
                '"%s" cannot be combined with "--remote"' % flag)
    req['config'] = ctx['backend'].cbl_path
    if req.get('op') == 'build':
        # builds are not idempotent: the key lets the transport
        # layer's retry loop re-send safely — the server replays the
        # recorded response instead of double-writing (serve/client.py)
        import uuid
        req['idempotency'] = uuid.uuid4().hex
    from .serve import client as mod_serve_client
    try:
        return mod_serve_client.run_or_fallback(opts.remote, req)
    except DNError as e:
        # transport retries exhausted (RemoteRetryExhausted) or a
        # post-commit failure (RemoteTransportError): the server may
        # have acted and bytes may already be on stdout, so neither
        # another retry nor a local fallback is safe — report
        fatal(e)


def cmd_scan(ctx, argv):
    opts = dn_parse_args(argv, ['before', 'after', 'filter', 'breakdowns',
                                'raw', 'points', 'counters', 'warnings',
                                'gnuplot', 'assetroot', 'dry-run',
                                'parse', 'remote', 'trace'])
    check_arg_count(opts, 1)
    dsname = opts._args[0]
    ds = datasource_for_name(ctx['config'], dsname)
    if isinstance(ds, DNError):
        fatal(ds)
    query = dn_query_config(opts)
    with _obs_command('scan', opts):
        if opts.remote:
            rc = _try_remote(ctx, opts, {
                'op': 'scan', 'ds': dsname,
                'queryconfig': dn_query_doc(opts),
                'opts': _remote_output_opts(opts),
            })
            if rc is not None:
                return rc
        warn_func = _warn_printer if getattr(opts, 'warnings', None) \
            else None
        with _mode_flag_env('parse', opts.parse, 'DN_PARSE',
                            ('auto', 'host', 'vector', 'device')):
            try:
                result = ds.scan(query, dry_run=opts.dry_run,
                                 warn_func=warn_func)
            except DNError as e:
                fatal(e)
        dn_output(query, opts, result, dsname)


def cmd_query(ctx, argv):
    opts = dn_parse_args(argv, ['before', 'after', 'filter', 'breakdowns',
                                'raw', 'points', 'counters', 'interval',
                                'gnuplot', 'assetroot', 'dry-run',
                                'iq-threads', 'iq-stack', 'remote',
                                'trace'])
    check_arg_count(opts, 1)
    dsname = opts._args[0]
    ds = datasource_for_name(ctx['config'], dsname)
    if isinstance(ds, DNError):
        fatal(ds)
    query = dn_query_config(opts)
    with _obs_command('query', opts):
        if opts.remote:
            rc = _try_remote(ctx, opts, {
                'op': 'query', 'ds': dsname,
                'interval': opts.interval,
                'queryconfig': dn_query_doc(opts),
                'opts': _remote_output_opts(opts),
            })
            if rc is not None:
                return rc

        with _pool_flag_env('iq-threads', opts.iq_threads,
                            'DN_IQ_THREADS'), \
                _mode_flag_env('iq-stack', opts.iq_stack,
                               'DN_IQ_STACK', ('auto', '0', '1')):
            try:
                result = ds.query(query, opts.interval,
                                  dry_run=opts.dry_run)
            except DNError as e:
                fatal(e)
        dn_output(query, opts, result, dsname)


def _read_index_config(filename):
    try:
        with open(filename) as f:
            contents = f.read()
    except OSError as e:
        fatal(DNError('read "%s"' % filename, cause=DNError(str(e))))
    try:
        return jsv.json_parse(contents)
    except ValueError as e:
        fatal(DNError('parse "%s"' % filename, cause=DNError(str(e))))


def cmd_build(ctx, argv):
    opts = dn_parse_args(argv, ['after', 'before', 'counters', 'dry-run',
                                'index-config', 'interval', 'warnings',
                                'assetroot', 'build-threads', 'parse',
                                'remote', 'trace'])
    check_arg_count(opts, 1)
    dsname = opts._args[0]
    indexcfg = _read_index_config(opts.index_config) \
        if opts.index_config else None

    if opts.before is not None and opts.after is not None and \
            opts.before < opts.after:
        fatal(DNError('"before" time cannot be before "after" time'))
    if opts.interval not in ('hour', 'day', 'all'):
        fatal(DNError('interval not supported: "%s"' % opts.interval))

    ds = datasource_for_name(ctx['config'], dsname)
    if isinstance(ds, DNError):
        fatal(ds)
    metrics = metrics_for_index(ctx['config'], dsname,
                                index_config=indexcfg)
    if len(metrics) == 0:
        fatal(DNError('no metrics defined for dataset "%s"' % dsname))

    with _obs_command('build', opts):
        if opts.remote:
            rc = _try_remote(ctx, opts, {
                'op': 'build', 'ds': dsname,
                'interval': opts.interval,
                'before': opts.before, 'after': opts.after,
                'index_config': indexcfg,
                'opts': _remote_output_opts(opts),
            })
            if rc is not None:
                return rc

        warn_func = _warn_printer if getattr(opts, 'warnings', None) \
            else None
        # the local write gate (resources.py): a disk-critical index
        # tree rejects the build up front with the clean retryable
        # disk_full error instead of failing mid-publish
        if not opts.dry_run:
            from . import resources as mod_resources
            res_conf = mod_config.resources_config()
            if isinstance(res_conf, DNError):
                fatal(res_conf)
            try:
                mod_resources.check_tree_writable(
                    getattr(ds, 'ds_indexpath', None), res_conf,
                    what='build')
            except DNError as e:
                fatal(e)
        with _pool_flag_env('build-threads', opts.build_threads,
                            'DN_BUILD_THREADS'), \
                _mode_flag_env('parse', opts.parse, 'DN_PARSE',
                               ('auto', 'host', 'vector', 'device')):
            try:
                result = ds.build(metrics, opts.interval,
                                  time_after=opts.after,
                                  time_before=opts.before,
                                  dry_run=opts.dry_run,
                                  warn_func=warn_func)
            except DNError as e:
                fatal(e)

        if opts.dry_run:
            dn_output(None, opts, result, dsname)
            return
        from .parallel import distributed as mod_dist
        if mod_dist.is_output_process():
            sys.stderr.write('indexes for "%s" built\n' % dsname)
            if getattr(opts, 'counters', None):
                result.pipeline.dump_counters(sys.stderr)


def cmd_index_config(ctx, argv):
    opts = dn_parse_args(argv, [])
    check_arg_count(opts, 1)
    import datetime
    now = datetime.datetime.now(datetime.timezone.utc)
    mtime = jsv.to_iso_string(int(now.timestamp() * 1000))
    cfg = index_config(ctx['config'], opts._args[0], mtime)
    if isinstance(cfg, DNError):
        fatal(cfg)
    sys.stdout.write(jsv.json_stringify(cfg) + '\n')


def cmd_index_scan(ctx, argv):
    opts = dn_parse_args(argv, ['before', 'after', 'filter', 'breakdowns',
                                'counters', 'index-config', 'interval'])
    opts.points = True
    check_arg_count(opts, 1)
    dsname = opts._args[0]
    indexcfg = _read_index_config(opts.index_config) \
        if opts.index_config else None
    ds = datasource_for_name(ctx['config'], dsname)
    if isinstance(ds, DNError):
        fatal(ds)
    metrics = metrics_for_index(ctx['config'], dsname,
                                index_config=indexcfg)
    if len(metrics) == 0:
        fatal(DNError('no metrics defined for dataset "%s"' % dsname))
    dsfilter = None
    if indexcfg:
        dsfilter = indexcfg['datasource'].get('filter')
    try:
        result = ds.index_scan(metrics, opts.interval, filter=dsfilter,
                               time_after=opts.after,
                               time_before=opts.before)
    except DNError as e:
        fatal(e)
    dn_output(None, opts, result, dsname)


def cmd_index_read(ctx, argv):
    opts = dn_parse_args(argv, ['index-config', 'interval'])
    check_arg_count(opts, 1)
    dsname = opts._args[0]
    indexcfg = _read_index_config(opts.index_config) \
        if opts.index_config else None
    ds = datasource_for_name(ctx['config'], dsname)
    if isinstance(ds, DNError):
        fatal(ds)
    metrics = metrics_for_index(ctx['config'], dsname,
                                index_config=indexcfg)
    if len(metrics) == 0:
        fatal(DNError('no metrics defined for dataset "%s"' % dsname))
    # the write gate (resources.py): index-read lands shards — on a
    # disk-critical tree it rejects up front, retryably, instead of
    # consuming the stream and failing mid-publish
    from . import resources as mod_resources
    res_conf = mod_config.resources_config()
    if isinstance(res_conf, DNError):
        fatal(res_conf)
    try:
        mod_resources.check_tree_writable(
            getattr(ds, 'ds_indexpath', None), res_conf,
            what='index-read')
        ds.index_read(metrics, opts.interval, sys.stdin.buffer)
    except DNError as e:
        fatal(e)


def cmd_stats(ctx, argv):
    """`dn stats [--remote SOCK|HOST:PORT] [--prom] [--cluster]`:
    render a resident server's /stats document (or its Prometheus
    metrics exposition with --prom); without --remote, this process's
    own metrics registry — mostly interesting after an in-process
    run.  `--cluster` (a bare flag here, unlike `dn serve
    --cluster=FILE`) asks the server for the MERGED fleet document
    instead — any member aggregates every topology member's stats
    over the pooled path, dead members reported unreachable
    (serve/fleet.py); with --prom the fleet headline numbers render
    as a synthesized dn_fleet_* exposition.  Not in USAGE_TEXT
    (byte-pinned); documented in docs/observability.md."""
    # --cluster is a bare flag for THIS command but a string option
    # (topology path) for `dn serve`; the shared option table keys
    # type by name, so strip it before the parse
    argv = list(argv)
    fleet = False
    while '--cluster' in argv:
        argv.remove('--cluster')
        fleet = True
    opts = dn_parse_args(argv, ['remote', 'prom'])
    check_arg_count(opts, 0)
    if fleet:
        if not opts.remote:
            fatal(DNError('"--cluster" requires "--remote" naming '
                          'any cluster member'))
        from .serve import client as mod_serve_client
        from .serve import fleet as mod_fleet
        import json as mod_json
        try:
            rc, header, out, err = mod_serve_client.request_bytes(
                opts.remote, {'op': 'fleet_stats'}, timeout_s=60.0)
        except (OSError, ValueError, DNError) as e:
            fatal(DNError('serve endpoint "%s" unreachable'
                          % opts.remote, cause=DNError(str(e))))
        sys.stderr.write(err.decode('utf-8', 'replace'))
        if rc != 0:
            return rc
        if getattr(opts, 'prom', None):
            doc = mod_json.loads(out.decode('utf-8'))
            sys.stdout.write(mod_fleet.fleet_prometheus_text(doc))
        else:
            sys.stdout.write(out.decode('utf-8', 'replace'))
        return 0
    if opts.remote:
        from .serve import client as mod_serve_client
        op = 'metrics' if getattr(opts, 'prom', None) else 'stats'
        try:
            rc, header, out, err = mod_serve_client.request_bytes(
                opts.remote, {'op': op}, timeout_s=30.0)
        except (OSError, ValueError, DNError) as e:
            fatal(DNError('serve endpoint "%s" unreachable'
                          % opts.remote, cause=DNError(str(e))))
        sys.stderr.write(err.decode('utf-8', 'replace'))
        sys.stdout.write(out.decode('utf-8', 'replace'))
        return rc
    from . import vpipe as mod_vpipe
    from .obs import export as obs_export
    counters = mod_vpipe.global_counters()
    if getattr(opts, 'prom', None):
        sys.stdout.write(obs_export.prometheus_text(counters=counters))
        return 0
    import json as mod_json
    doc = obs_export.stats_section(counters=counters)
    from .follow import stats_doc as follow_stats
    fs = follow_stats()
    if fs is not None:
        # continuous-ingest telemetry: source offsets, batches
        # published, checkpoint age, ingest lag (docs/ingest.md)
        doc['follow'] = fs
    sys.stdout.write(mod_json.dumps(
        doc, sort_keys=True, indent=2) + '\n')
    return 0


def cmd_events(ctx, argv):
    """`dn events [--follow] [--remote SOCK|HOST:PORT]`: print the
    structured event journal (obs/events.py) as one JSON line per
    entry — failovers, breaker flips, epoch transitions, handoff
    outcomes, repairs, quarantines, shed bursts, scrub summaries,
    each with its trace id when one was active.  --remote reads a
    resident server's journal through the `events` op; --follow
    keeps polling and prints new entries as they land (the journal
    must be armed with DN_EVENTS / DN_EVENTS_FILE on the server).
    Without --remote, this process's own journal.  Not in USAGE_TEXT
    (byte-pinned); documented in docs/observability.md."""
    import json as mod_json
    import time as mod_time
    opts = dn_parse_args(argv, ['remote', 'follow'])
    check_arg_count(opts, 0)
    obs_conf = mod_config.obs_config()
    if isinstance(obs_conf, DNError):
        fatal(obs_conf)

    def emit_lines(entries):
        for e in entries:
            sys.stdout.write(mod_json.dumps(
                e, sort_keys=True, separators=(',', ':')) + '\n')
        if entries:
            sys.stdout.flush()

    if not opts.remote:
        from .obs import events as obs_events
        j = obs_events.journal()
        if j is None:
            sys.stderr.write('dn: event journal disabled (set '
                             'DN_EVENTS or DN_EVENTS_FILE)\n')
            return 1
        emit_lines(j.tail())
        return 0

    from .serve import client as mod_serve_client
    since = 0
    poll_s = max(0.1, obs_conf['top_interval_ms'] / 1000.0)
    while True:
        try:
            rc, header, out, err = mod_serve_client.request_bytes(
                opts.remote, {'op': 'events', 'since': since},
                timeout_s=30.0)
        except (OSError, ValueError, DNError) as e:
            fatal(DNError('serve endpoint "%s" unreachable'
                          % opts.remote, cause=DNError(str(e))))
        if rc != 0:
            sys.stderr.write(err.decode('utf-8', 'replace'))
            return rc
        doc = mod_json.loads(out.decode('utf-8'))
        if not doc.get('enabled'):
            sys.stderr.write('dn: event journal disabled on the '
                             'server (set DN_EVENTS or '
                             'DN_EVENTS_FILE)\n')
            return 1
        entries = doc.get('events') or []
        emit_lines(entries)
        since = max([doc.get('seq') or 0] +
                    [e.get('seq') or 0 for e in entries])
        if not getattr(opts, 'follow', None):
            return 0
        try:
            mod_time.sleep(poll_s)
        except KeyboardInterrupt:
            return 0


def cmd_top(ctx, argv):
    """`dn top --remote SOCK|HOST:PORT [--once]`: the live fleet
    console (serve/top.py) — polls `fleet_stats` at
    DN_TOP_INTERVAL_MS and redraws the fleet header, per-member
    table, and event tail in place.  Degrades to single-process mode
    against a non-cluster server.  --once prints one frame with no
    ANSI codes and exits.  Not in USAGE_TEXT (byte-pinned);
    documented in docs/observability.md."""
    opts = dn_parse_args(argv, ['remote', 'once', 'subscribe'])
    check_arg_count(opts, 0)
    if not opts.remote:
        raise UsageError('"--remote" is required for "top"')
    obs_conf = mod_config.obs_config()
    if isinstance(obs_conf, DNError):
        fatal(obs_conf)
    from .serve import top as mod_top
    try:
        return mod_top.top_main(opts.remote,
                                obs_conf['top_interval_ms'],
                                once=bool(getattr(opts, 'once',
                                                  None)),
                                subscribe=bool(getattr(opts,
                                                       'subscribe',
                                                       None)))
    except KeyboardInterrupt:
        return 0


def cmd_subscribe(ctx, argv):
    """`dn subscribe --remote SOCK|HOST:PORT [QUERY OPTIONS]
    [--frames=N] DATASOURCE`: register a standing query on the server
    (serve/subscribe.py) and stream pushed result frames as JSONL —
    one JSON object per frame with kind/seq/epoch/payload/token.  The
    payload at epoch E is byte-identical to `dn query --remote` at
    epoch E; the token in each frame resumes the stream after a
    disconnect without a reseed when the result is unchanged.
    --frames=N exits 0 after N pushed frames (the seed counts).  Not
    in USAGE_TEXT (byte-pinned); documented in docs/serving.md."""
    opts = dn_parse_args(argv, ['before', 'after', 'filter',
                                'breakdowns', 'raw', 'points',
                                'interval', 'remote', 'frames'])
    check_arg_count(opts, 1)
    dsname = opts._args[0]
    if not opts.remote:
        raise UsageError('"--remote" is required for "subscribe"')
    nframes = 0
    if getattr(opts, 'frames', None) is not None:
        try:
            nframes = int(opts.frames)
        except ValueError:
            nframes = -1
        if nframes < 0:
            fatal(DNError('"--frames" expects a non-negative '
                          'integer, got "%s"' % opts.frames))
    # validates the query flags locally (same contract as cmd_query)
    # before shipping the doc
    dn_query_config(opts)
    req = {
        'op': 'subscribe', 'ds': dsname,
        'interval': opts.interval,
        'queryconfig': dn_query_doc(opts),
        'opts': {'raw': bool(getattr(opts, 'raw', None)),
                 'points': bool(getattr(opts, 'points', None))},
        'config': ctx['backend'].cbl_path,
    }
    import json as mod_json
    import time as mod_time
    from .serve import client as mod_serve_client
    from .serve.client import (SubscribeUnsupported,
                               RemoteTransportError)

    def emit(frame):
        line = mod_json.dumps({
            'kind': frame['kind'],
            'seq': frame['seq'],
            'epoch': frame['epoch'],
            'payload': frame['payload'].decode('utf-8',
                                               'replace'),
            'token': frame['token'],
        }, sort_keys=True)
        sys.stdout.write(line + '\n')
        sys.stdout.flush()

    resume = None
    emitted = 0
    failures = 0
    while True:
        stream = mod_serve_client.subscribe_stream(
            opts.remote, dict(req), resume=resume)
        try:
            for frame in stream:
                failures = 0
                resume = (frame['token'], frame['payload'])
                # a resume-matched 'current' frame repeats bytes the
                # consumer already has — refresh the token, skip the
                # line (and the --frames budget)
                if frame['kind'] != 'current':
                    emit(frame)
                    emitted += 1
                if nframes and emitted >= nframes:
                    return 0
            return 0  # server sent a clean 'end' frame
        except SubscribeUnsupported as e:
            sys.stderr.write('dn: %s\n' % e.message)
            return 1
        except RemoteTransportError:
            failures += 1
            if failures > 5 or resume is None:
                raise FatalError('subscription stream lost and '
                                 'reconnect failed')
            mod_time.sleep(min(2.0, 0.1 * (2 ** failures)))
        except DNError as e:
            fatal(e)
        except KeyboardInterrupt:
            return 0
        finally:
            stream.close()


def cmd_follow(ctx, argv):
    """`dn follow [--interval=I] [--index-config=F] [--once]
    [--validate] DATASOURCE [FILE ...]`: the continuous-ingest daemon
    (follow/loop.py) — tail growing files (FILE of `-` reads stdin;
    default: the datasource's own data path when it is a regular
    file), cut mini-batches by DN_FOLLOW_LATENCY_MS /
    DN_FOLLOW_MAX_BYTES, and incrementally publish shard updates with
    an exactly-once checkpoint.  Not in USAGE_TEXT — the usage output
    is byte-pinned to the reference goldens; documented in
    docs/ingest.md."""
    import os
    opts = dn_parse_args(argv, ['interval', 'index-config', 'once',
                                'validate'])
    if len(opts._args) < 1:
        raise UsageError('missing arguments')
    dsname = opts._args[0]
    sources = opts._args[1:]
    indexcfg = _read_index_config(opts.index_config) \
        if opts.index_config else None
    if opts.interval not in ('hour', 'day', 'all'):
        fatal(DNError('interval not supported: "%s"' % opts.interval))

    # the follow knobs share the fail-fast validation contract with
    # the serve/remote/router/fault knobs: a malformed value is caught
    # here (and by --validate), not at the first batch that needs it
    conf = mod_config.follow_config()
    if isinstance(conf, DNError):
        fatal(conf)
    faults_conf = mod_config.faults_config()
    if isinstance(faults_conf, DNError):
        fatal(faults_conf)
    obs_conf = mod_config.obs_config()
    if isinstance(obs_conf, DNError):
        fatal(obs_conf)
    res_conf = mod_config.resources_config()
    if isinstance(res_conf, DNError):
        fatal(res_conf)

    ds = datasource_for_name(ctx['config'], dsname)
    if isinstance(ds, DNError):
        fatal(ds)
    if getattr(ds, 'ds_indexpath', None) is None:
        fatal(DNError('datasource is missing "indexpath"'))
    if opts.interval != 'all' and \
            getattr(ds, 'ds_timefield', None) is None:
        fatal(DNError('datasource is missing "timefield"'))
    metrics = metrics_for_index(ctx['config'], dsname,
                                index_config=indexcfg)
    if len(metrics) == 0:
        fatal(DNError('no metrics defined for dataset "%s"' % dsname))

    if not sources:
        datapath = getattr(ds, 'ds_datapath', None)
        if datapath is None or not os.path.isfile(datapath):
            fatal(DNError('no sources given and the datasource path '
                          'is not a regular file; name the file(s) '
                          'to follow (or "-" for stdin)'))
        sources = [datapath]
    norm = []
    for src in sources:
        norm.append(src if src == '-' else os.path.abspath(src))
    if norm.count('-') > 1:
        raise UsageError('stdin ("-") may be named at most once')

    if getattr(opts, 'validate', None):
        # dry mode (matching `dn serve --validate`): the DN_FOLLOW_* /
        # DN_FAULTS / obs knobs and the source arguments were just
        # validated through the paths the daemon uses; report the
        # resolved configuration and exit without touching anything
        sys.stdout.write(
            'follow config ok: latency_ms=%d max_bytes=%d '
            'poll_ms=%d\n'
            % (conf['latency_ms'], conf['max_bytes'],
               conf['poll_ms']))
        sys.stdout.write(
            'obs config ok: trace=%s slow_ms=%s buckets=%d\n'
            % (obs_conf['trace'] or 'off',
               obs_conf['slow_ms'] if obs_conf['slow_ms'] is not None
               else 'off', len(obs_conf['buckets'])))
        sys.stdout.write(
            'resources config ok: disk_low_pct=%g '
            'disk_critical_pct=%g poll_ms=%d\n'
            % (res_conf['disk_low_pct'],
               res_conf['disk_critical_pct'], res_conf['poll_ms']))
        sys.stdout.write(
            'follow plan: datasource=%s interval=%s index=%s '
            'sources=%s\n'
            % (dsname, opts.interval, ds.ds_indexpath,
               ','.join(norm)))
        sites = faults_conf['sites']
        if sites:
            sys.stdout.write(
                'faults armed: %s\n' % ' '.join(
                    '%s:%s:%g:%d' % (s, k, r, seed)
                    for s, (k, r, seed) in sorted(sites.items())))
        return 0

    from .follow import loop as mod_floop
    try:
        return mod_floop.follow_main(ds, metrics, opts.interval, norm,
                                     conf, once=bool(opts.once))
    except DNError as e:
        fatal(e)


def _parse_age(raw):
    """'30s' / '15m' / '12h' / '7d' (or bare seconds) -> seconds."""
    mult = {'s': 1, 'm': 60, 'h': 3600, 'd': 86400}
    val, unit = raw, 1
    if raw and raw[-1] in mult:
        val, unit = raw[:-1], mult[raw[-1]]
    try:
        seconds = float(val) * unit
        if seconds < 0:
            raise ValueError(raw)
    except ValueError:
        raise UsageError('bad value for "older-than": "%s"' % raw)
    return seconds


def _integrity_trees(opts):
    """[(dsname-or-None, indexroot)] a scrub/quarantine walk covers:
    the --tree override, else every configured file datasource's
    index tree."""
    from . import integrity as mod_integrity
    if opts.tree:
        return [(None, opts.tree)]
    try:
        trees = mod_integrity.configured_index_trees()
    except DNError as e:
        fatal(e)
    if not trees:
        fatal(DNError('no index trees configured (and no --tree '
                      'given)'))
    return trees


def cmd_scrub(ctx, argv):
    """`dn scrub [--tree T] [--check] [--forget-missing]
    [--repair --cluster TOPO.json --member NAME]
    [--remote SOCK|HOST:PORT]`: walk index trees comparing shard
    bytes against the integrity catalog (integrity.py).  Mismatches
    quarantine (--check reports only); --repair pulls good copies
    from committed cluster co-replicas; --remote asks a resident
    server to run the pass itself (tree-locked, plus anti-entropy in
    cluster mode).  Exits 0 only when the trees are clean (or fully
    repaired).  Not in USAGE_TEXT — the usage output is byte-pinned
    to the reference goldens; documented in docs/robustness.md."""
    import json as mod_json
    opts = dn_parse_args(argv, ['tree', 'check', 'forget-missing',
                                'repair', 'remote', 'cluster',
                                'member'])
    check_arg_count(opts, 0)
    if opts.remote:
        from .serve import client as mod_serve_client
        req = {'op': 'scrub',
               'repair': bool(getattr(opts, 'repair', None)),
               'check': bool(getattr(opts, 'check', None))}
        try:
            rc, header, out, err = mod_serve_client.request_bytes(
                opts.remote, req, timeout_s=600.0)
        except (OSError, ValueError, DNError) as e:
            fatal(DNError('serve endpoint "%s" unreachable'
                          % opts.remote, cause=DNError(str(e))))
        sys.stderr.write(err.decode('utf-8', 'replace'))
        sys.stdout.write(out.decode('utf-8', 'replace'))
        if rc != 0:
            return rc
        try:
            doc = mod_json.loads(out.decode('utf-8'))
        except ValueError:
            return 1
        dirty = sum((t.get('corrupt', 0) + t.get('missing', 0))
                    for t in (doc.get('trees') or {}).values())
        return 0 if dirty == 0 else 1
    conf = mod_config.integrity_config()
    if isinstance(conf, DNError):
        fatal(conf)
    if (opts.cluster is None) != (opts.member is None):
        raise UsageError('"--cluster" and "--member" must be used '
                         'together')
    topo = None
    if opts.cluster is not None:
        from .serve import topology as mod_topology
        try:
            topo = mod_topology.load_topology(opts.cluster,
                                              member=opts.member)
        except DNError as e:
            fatal(e)
    if getattr(opts, 'repair', None) and topo is None:
        raise UsageError('"--repair" needs donors: use --remote '
                         'against a cluster member, or --cluster/'
                         '--member with a topology file')
    from . import integrity as mod_integrity
    trees = _integrity_trees(opts)
    if opts.tree and trees[0][0] is None:
        # a bare --tree path carries no datasource name; repair needs
        # one (the donor's shard_fetch resolves its tree by ds) —
        # recover it from the configured datasources, or refuse
        # rather than fail every donor fetch with a confusing error
        import os
        want = os.path.abspath(opts.tree)
        try:
            for dsname, root in \
                    mod_integrity.configured_index_trees():
                if os.path.abspath(root) == want:
                    trees = [(dsname, opts.tree)]
                    break
        except DNError:
            pass
        if trees[0][0] is None and getattr(opts, 'repair', None):
            fatal(DNError('"--repair" with "--tree": "%s" matches '
                          'no configured datasource, so donors '
                          'cannot serve it' % opts.tree))
    rate = conf['scrub_rate_mb_s'] << 20
    summary = {}
    dirty = 0
    for dsname, root in trees:
        res = mod_integrity.scrub_tree(
            root, quarantine=not getattr(opts, 'check', None),
            forget_missing=bool(getattr(opts, 'forget_missing',
                                        None)),
            rate_bytes_s=rate)
        res['repaired'] = 0
        if getattr(opts, 'repair', None) and topo is not None:
            res['repaired'] = _scrub_repair(
                topo, opts.member, dsname, root,
                res['corrupt_shards'] + res['missing_shards'])
        summary[root] = res
        dirty += res['corrupt'] + res['missing'] - res['repaired']
    sys.stdout.write(mod_json.dumps(summary, indent=2,
                                    sort_keys=True) + '\n')
    return 0 if dirty == 0 else 1


def _scrub_repair(topo, member, dsname, indexroot, rels):
    """Pull damaged/missing shards from committed co-replicas (the
    offline `dn scrub --repair` leg; a resident member repairs
    itself through serve/scrub.py instead).  Returns how many
    landed."""
    import os
    from . import integrity as mod_integrity
    from .serve import rebalance as mod_rebalance
    from .serve import scrub as mod_scrub
    topo_conf = mod_config.topo_config()
    if isinstance(topo_conf, DNError):
        fatal(topo_conf)
    catalog = mod_integrity.load_catalog(indexroot)
    repaired = 0
    for rel in rels:
        expected = catalog.get(rel)
        if expected is None:
            continue
        dest = os.path.join(os.path.abspath(indexroot), rel)
        pid = topo.partition_of(dest, mod_scrub.rel_timeformat(rel))
        donors = [m for m in topo.replicas(pid) if m != member]
        for donor in donors:
            try:
                mod_rebalance.land_shard(
                    topo.endpoint(donor), dsname, None, topo.epoch,
                    rel, expected[0], expected[1], dest,
                    topo_conf['handoff_timeout_s'],
                    indexroot=indexroot)
                repaired += 1
                break
            except (OSError, ValueError, DNError):
                continue
    return repaired


def cmd_quarantine(ctx, argv):
    """`dn quarantine list|clean [--older-than AGE] [--max-bytes N]
    [--tree T]`: inspect and prune `.dn_quarantine/` — the forensics
    directory every crash rollback and corrupt-detect moves
    artifacts into, and nothing ever pruned before this command
    existed.  AGE: '30s'/'15m'/'12h'/'7d' or bare seconds (clean
    defaults to everything).  `--max-bytes N` evicts OLDEST-FIRST
    only until each tree's quarantine fits the byte budget (newest
    forensics survive); the serve scrub timer applies the same
    eviction automatically under DN_QUARANTINE_MAX_MB.  Not in
    USAGE_TEXT (byte-pinned); documented in docs/robustness.md."""
    from . import integrity as mod_integrity
    opts = dn_parse_args(argv, ['tree', 'older-than', 'max-bytes'])
    if len(opts._args) < 1:
        raise UsageError('missing quarantine subcommand')
    sub = opts._args[0]
    if sub == 'list':
        check_arg_count(opts, 1)
        total_files = 0
        total_bytes = 0
        for dsname, root in _integrity_trees(opts):
            for name, size, age_s, path in \
                    mod_integrity.quarantine_entries(root):
                sys.stdout.write('%12d %10ds %s\n'
                                 % (size, int(age_s), path))
                total_files += 1
                total_bytes += size
        sys.stderr.write('dn quarantine: %d file(s), %d byte(s)\n'
                         % (total_files, total_bytes))
        return 0
    if sub == 'clean':
        check_arg_count(opts, 1)
        age_s = _parse_age(opts.older_than) \
            if opts.older_than is not None else 0
        max_bytes = None
        if opts.max_bytes is not None:
            try:
                max_bytes = int(opts.max_bytes)
                if max_bytes < 0:
                    raise ValueError(opts.max_bytes)
            except ValueError:
                raise UsageError('bad value for "max-bytes": "%s"'
                                 % opts.max_bytes)
        removed = 0
        freed = 0
        for dsname, root in _integrity_trees(opts):
            n, b = mod_integrity.quarantine_clean(
                root, older_than_s=age_s, max_bytes=max_bytes)
            removed += n
            freed += b
        sys.stderr.write('dn quarantine: removed %d file(s), '
                         'freed %d byte(s)\n' % (removed, freed))
        return 0
    raise UsageError('unknown quarantine subcommand: "%s"' % sub)


def cmd_topo(ctx, argv):
    """`dn topo show|status|apply|commit|abort|rebalance
    [--topology T.json] ...`: dynamic cluster topology management
    (serve/coordinator.py, serve/rebalance.py).  `apply NEW.json`
    publishes a pending epoch (members stream their newly-assigned
    shards from the committed owners), `commit` cuts over atomically
    once every member is handoff-ready (`--wait S` polls readiness,
    `--force` overrides), `abort` withdraws the pending epoch, and
    `rebalance` proposes partition moves toward load from the
    members' live /stats (`--apply` publishes the proposal).  Not in
    USAGE_TEXT — the usage output is byte-pinned to the reference
    goldens; documented in docs/serving.md."""
    import json
    import os
    opts = dn_parse_args(argv, ['topology', 'wait', 'force',
                                'apply'])
    if len(opts._args) < 1:
        raise UsageError('missing topo subcommand')
    sub = opts._args[0]
    path = opts.topology or os.environ.get('DN_SERVE_TOPOLOGY') \
        or None
    if path is None:
        raise UsageError('"--topology" (or DN_SERVE_TOPOLOGY) is '
                         'required')
    wait_s = None
    if opts.wait is not None:
        try:
            wait_s = float(opts.wait)
            if wait_s < 0:
                raise ValueError(opts.wait)
        except ValueError:
            raise UsageError('bad value for "wait": "%s"'
                             % opts.wait)
    from .serve import coordinator as mod_coordinator
    from .serve import topology as mod_topology
    try:
        if sub == 'show':
            check_arg_count(opts, 1)
            committed, pending = \
                mod_topology.load_topology_state(path)
            doc = {'committed': committed.summary()}
            if pending is not None:
                doc['pending'] = pending.summary()
            sys.stdout.write(json.dumps(doc, indent=2,
                                        sort_keys=True) + '\n')
            return 0
        if sub == 'status':
            check_arg_count(opts, 1)
            doc = mod_coordinator.transition_status(path)
            sys.stdout.write(json.dumps(doc, indent=2,
                                        sort_keys=True) + '\n')
            return 0 if doc.get('ready') else 1
        if sub == 'apply':
            check_arg_count(opts, 2)
            new_path = opts._args[1]
            try:
                with open(new_path, 'r') as f:
                    new_doc = json.load(f)
            except (OSError, ValueError) as e:
                fatal(DNError('cannot read topology "%s": %s'
                              % (new_path, e)))
            committed, pending = mod_coordinator.begin_transition(
                path, new_doc)
            sys.stderr.write(
                'dn topo: pending epoch %d published (committed '
                'epoch %d; members hand off, then `dn topo '
                'commit`)\n' % (pending.epoch, committed.epoch))
            return 0
        if sub == 'commit':
            check_arg_count(opts, 1)
            if wait_s:
                status = mod_coordinator.wait_ready(
                    path, timeout_s=wait_s)
            else:
                status = mod_coordinator.transition_status(path)
            if not status.get('ready') and \
                    not getattr(opts, 'force', None):
                lag = [m for m, d in
                       (status.get('members') or {}).items()
                       if not d.get('ready')]
                fatal(DNError(
                    'transition to epoch %s not ready: member(s) %s '
                    'still handing off (wait with --wait S, or '
                    '--force to cut over anyway)'
                    % (status.get('pending_epoch'),
                       ','.join(sorted(lag)) or '?')))
            committed = mod_coordinator.commit_transition(path)
            sys.stderr.write('dn topo: epoch %d committed\n'
                             % committed.epoch)
            return 0
        if sub == 'abort':
            check_arg_count(opts, 1)
            committed = mod_coordinator.abort_transition(path)
            sys.stderr.write('dn topo: transition aborted '
                             '(committed epoch %d stands)\n'
                             % committed.epoch)
            return 0
        if sub == 'rebalance':
            check_arg_count(opts, 1)
            committed, pending = \
                mod_topology.load_topology_state(path)
            if pending is not None:
                fatal(DNError('transition to epoch %d already '
                              'pending; commit or abort it first'
                              % pending.epoch))
            from .serve import rebalance as mod_rebalance
            loads = mod_rebalance.collect_loads(committed)
            doc, decisions = mod_rebalance.propose_moves(committed,
                                                         loads)
            out = {'loads': loads, 'decisions': decisions,
                   'proposed_epoch': doc['epoch'] if doc else None}
            sys.stdout.write(json.dumps(out, indent=2,
                                        sort_keys=True) + '\n')
            if doc is None:
                sys.stderr.write('dn topo: cluster balanced; '
                                 'nothing to move\n')
                return 0
            if getattr(opts, 'apply', None):
                mod_coordinator.begin_transition(
                    path, doc, note={'rebalance': decisions})
                sys.stderr.write(
                    'dn topo: pending epoch %d published '
                    '(%d move(s))\n' % (doc['epoch'],
                                        len(decisions)))
            return 0
        raise UsageError('unknown topo subcommand: "%s"' % sub)
    except DNError as e:
        fatal(e)


def cmd_serve(ctx, argv):
    """`dn serve --socket PATH | --port N [--pidfile P]
    [--cluster TOPOLOGY.json --member NAME] [--validate]`: the
    resident query server (serve/server.py), optionally as a member
    of a scatter-gather cluster (serve/topology.py, serve/router.py).
    Not in USAGE_TEXT — the usage output is byte-pinned to the
    reference goldens; documented in docs/serving.md."""
    import os
    opts = dn_parse_args(argv, ['socket', 'port', 'pidfile',
                                'cluster', 'member', 'validate'])
    check_arg_count(opts, 0)

    conf = mod_config.serve_config()
    if isinstance(conf, DNError):
        fatal(conf)
    # the retry, router, and fault-injection knobs share the
    # fail-fast contract: a malformed value is caught here (and by
    # --validate), not at the first request that needs it
    remote_conf = mod_config.remote_config()
    if isinstance(remote_conf, DNError):
        fatal(remote_conf)
    router_conf = mod_config.router_config()
    if isinstance(router_conf, DNError):
        fatal(router_conf)
    topo_conf = mod_config.topo_config()
    if isinstance(topo_conf, DNError):
        fatal(topo_conf)
    faults_conf = mod_config.faults_config()
    if isinstance(faults_conf, DNError):
        fatal(faults_conf)
    obs_conf = mod_config.obs_config()
    if isinstance(obs_conf, DNError):
        fatal(obs_conf)
    integ_conf = mod_config.integrity_config()
    if isinstance(integ_conf, DNError):
        fatal(integ_conf)
    res_conf = mod_config.resources_config()
    if isinstance(res_conf, DNError):
        fatal(res_conf)
    dev_conf = mod_config.device_config()
    if isinstance(dev_conf, DNError):
        fatal(dev_conf)
    iq_conf = mod_config.index_device_config()
    if isinstance(iq_conf, DNError):
        fatal(iq_conf)
    sub_conf = mod_config.subscribe_config()
    if isinstance(sub_conf, DNError):
        fatal(sub_conf)

    cluster = opts.cluster or os.environ.get('DN_SERVE_TOPOLOGY') \
        or None
    if (cluster is None) != (opts.member is None):
        raise UsageError('"--cluster" and "--member" must be used '
                         'together')
    topo = None
    topo_pending = None
    if cluster is not None:
        from .serve import topology as mod_topology
        try:
            # a pending transition file loads as (committed, pending):
            # the server serves the committed map and — when this
            # member appears in the pending epoch — starts its shard
            # handoff immediately (a fresh joiner's startup path)
            topo, topo_pending = mod_topology.load_topology_state(
                cluster, member=opts.member)
        except DNError as e:
            fatal(e)

    port = None
    if opts.port is not None:
        try:
            port = int(opts.port)
            if not 0 <= port <= 65535:
                raise ValueError(opts.port)
        except ValueError:
            raise UsageError('bad value for "port": "%s"' % opts.port)
    if (opts.socket is None) == (port is None):
        raise UsageError(
            'exactly one of "--socket" and "--port" is required')

    if getattr(opts, 'validate', None):
        # dry mode: the DN_SERVE_* / DN_REMOTE_* / DN_FAULTS knobs and
        # the endpoint arguments were just validated through the same
        # paths the daemon and client use; report the resolved
        # configuration and exit without binding
        sys.stdout.write(
            'serve config ok: max_inflight=%d queue_depth=%d '
            'deadline_ms=%d coalesce=%d drain_s=%d\n'
            % (conf['max_inflight'], conf['queue_depth'],
               conf['deadline_ms'], 1 if conf['coalesce'] else 0,
               conf['drain_s']))
        sys.stdout.write(
            'serve front-end ok: read_deadline_ms=%d '
            'write_deadline_ms=%d idle_ms=%d\n'
            % (conf['read_deadline_ms'], conf['write_deadline_ms'],
               conf['idle_ms']))
        sys.stdout.write(
            'serve tenancy ok: quota=%d default_weight=%d '
            'weights=%s\n'
            % (conf['tenant_quota'], conf['tenant_default_weight'],
               ','.join('%s:%d' % (n, w) for n, w in
                        sorted(conf['tenant_weights'].items()))
               or 'none'))
        sys.stdout.write(
            'remote config ok: retries=%d backoff_ms=%d '
            'connect_timeout_s=%d deadline_ms=%d\n'
            % (remote_conf['retries'], remote_conf['backoff_ms'],
               remote_conf['connect_timeout_s'],
               remote_conf['deadline_ms']))
        sys.stdout.write(
            'obs config ok: trace=%s slow_ms=%s buckets=%d\n'
            % (obs_conf['trace'] or 'off',
               obs_conf['slow_ms'] if obs_conf['slow_ms'] is not None
               else 'off', len(obs_conf['buckets'])))
        sys.stdout.write(
            'fleet obs ok: history_s=%d events=%d events_file=%s '
            'top_interval_ms=%d fleet_timeout_s=%d\n'
            % (obs_conf['history_s'], obs_conf['events'],
               obs_conf['events_file'] or 'off',
               obs_conf['top_interval_ms'],
               conf['fleet_timeout_s']))
        sys.stdout.write(
            'subscribe config ok: max=%d coalesce_ms=%d '
            'queue_depth=%d delta_pct=%d\n'
            % (sub_conf['max'], sub_conf['coalesce_ms'],
               sub_conf['queue_depth'], sub_conf['delta_pct']))
        sys.stdout.write(
            'router config ok: probe_ms=%d failures=%d '
            'cooldown_ms=%d hedge_ms=%d fetch_timeout_s=%d '
            'partial=%s\n'
            % (router_conf['probe_ms'], router_conf['failures'],
               router_conf['cooldown_ms'], router_conf['hedge_ms'],
               router_conf['fetch_timeout_s'],
               router_conf['partial']))
        sys.stdout.write(
            'topo config ok: poll_ms=%d handoff_timeout_s=%d '
            'handoff_retries=%d max_moves=%d\n'
            % (topo_conf['poll_ms'], topo_conf['handoff_timeout_s'],
               topo_conf['handoff_retries'], topo_conf['max_moves']))
        sys.stdout.write(
            'integrity config ok: verify=%s scrub_interval_s=%d '
            'scrub_rate_mb_s=%d quarantine_max_mb=%d\n'
            % (integ_conf['verify'], integ_conf['scrub_interval_s'],
               integ_conf['scrub_rate_mb_s'],
               integ_conf['quarantine_max_mb']))
        sys.stdout.write(
            'resources config ok: disk_low_pct=%g '
            'disk_critical_pct=%g poll_ms=%d mem_budget_mb=%d '
            'fd_headroom=%d events_file_max_mb=%d\n'
            % (res_conf['disk_low_pct'],
               res_conf['disk_critical_pct'], res_conf['poll_ms'],
               res_conf['mem_budget_mb'], res_conf['fd_headroom'],
               obs_conf['events_file_max_mb']))
        # the device lane's serving picture: backend identity (probed
        # under a short deadline ONLY when the engine could actually
        # reach the device — a wedged plugin costs 5s here, and a
        # host-only rig pays no backend initialization at all), the
        # HBM residency budget, and the persisted audition cache
        from . import device_scan as mod_ds
        from . import engine as mod_engine
        from .ops import accelerator_likely
        mode = (mod_engine.engine_mode() or 'auto').strip().lower()
        possible = mode == 'jax' or (mode == 'auto'
                                     and accelerator_likely())
        if possible:
            status, backend = mod_ds.run_with_deadline(
                mod_ds._backend_id, 5.0, 'validate-backend-id')
            backend = backend if status == 'ok' and backend \
                else 'unprobed'
        else:
            backend = 'host-only'
        apath, entries, wins = mod_ds.audition_cache_entries()
        sys.stdout.write(
            'device lane ok: engine=%s backend=%s residency_mb=%d '
            'prewarm=%d probe_timeout_s=%d audition_cache=%s '
            'entries=%d wins=%d\n'
            % (mode, backend, dev_conf['residency_mb'],
               1 if dev_conf['prewarm'] else 0,
               dev_conf['probe_timeout_s'], apath or 'off',
               entries, wins))
        sys.stdout.write(
            'index device lane ok: mode=%s batch_rows=%d '
            'residency_share=%.2f\n'
            % (iq_conf['mode'], iq_conf['batch_rows'],
               iq_conf['residency_share']))
        from . import scan_mt as mod_scan_mt
        sys.stdout.write(
            'scan pipeline ok: pipeline_depth=%d batch_floor=%s '
            'partitions=%s scan_threads=%d\n'
            % (dev_conf['pipeline_depth'],
               dev_conf['batch_floor'] or 'auto',
               '%d (auto)' % mod_scan_mt.scan_partitions()
               if dev_conf['scan_partitions'] == 'auto'
               else dev_conf['scan_partitions'],
               mod_scan_mt.scan_threads()))
        if topo is not None:
            sys.stdout.write(
                'cluster topology ok: member=%s epoch=%d assign=%s '
                'members=%d partitions=%d (owns: %s)\n'
                % (opts.member, topo.epoch, topo.assign,
                   len(topo.members), len(topo.partitions),
                   ','.join(str(p)
                            for p in topo.partitions_of(opts.member))
                   or 'none'))
            if topo_pending is not None:
                sys.stdout.write(
                    'cluster transition pending: epoch %d (owns: '
                    '%s)\n'
                    % (topo_pending.epoch,
                       ','.join(str(p) for p in
                                topo_pending.partitions_of(
                                    opts.member))
                       or 'none'))
        sites = faults_conf['sites']
        if sites:
            sys.stdout.write(
                'faults armed: %s\n' % ' '.join(
                    '%s:%s:%g:%d' % (s, k, r, seed)
                    for s, (k, r, seed) in sorted(sites.items())))
        return 0

    from .serve import server as mod_server
    try:
        return mod_server.serve_main(socket_path=opts.socket,
                                     port=port, pidfile=opts.pidfile,
                                     cluster=topo,
                                     member=opts.member,
                                     router_conf=router_conf,
                                     pending=topo_pending,
                                     topo_conf=topo_conf)
    except DNError as e:
        fatal(e)


def cmd_rollup(ctx, argv):
    """`dn rollup [--tree T] [--interval hour|day]`: build/refresh
    the multi-resolution rollup shards (day-from-hour, month-from-
    day/hour; rollup.py) for the interval's fine tree — merging
    EXISTING index shards, no raw rescan — and publish them through
    the two-phase journal + integrity catalog.  The query planner
    then answers wide-window queries from the coarsest covering
    shard set, byte-identically.  Not in USAGE_TEXT — the usage
    output is byte-pinned to the reference goldens; documented in
    docs/serving.md."""
    from . import rollup as mod_rollup
    opts = dn_parse_args(argv, ['tree', 'interval'])
    check_arg_count(opts, 0)
    if opts.interval not in ('hour', 'day'):
        fatal(DNError('interval not supported: "%s"' % opts.interval))
    total = {'built': 0, 'fresh': 0, 'removed': 0}
    for dsname, root in _integrity_trees(opts):
        try:
            doc = mod_rollup.build_rollups(root, opts.interval)
        except (DNError, OSError) as e:
            fatal(e if isinstance(e, DNError) else DNError(str(e)))
        for k in total:
            total[k] += doc[k]
        if doc['paused']:
            sys.stderr.write('dn rollup: paused under resource '
                             'pressure (tree "%s")\n' % root)
    sys.stderr.write('dn rollup: %d shard(s) built, %d fresh, '
                     '%d removed\n' % (total['built'], total['fresh'],
                                       total['removed']))
    return 0


def cmd_compact(ctx, argv):
    """`dn compact [--tree T] [--interval hour|day] [--min-gens N]`:
    rewrite base shards + their `dn follow --append` mini-generations
    into single shards (rollup.compact_tree).  The consumed
    generations are deleted through the publish commit record —
    crash-safe at every instant.  Not in USAGE_TEXT (byte-pinned);
    documented in docs/robustness.md."""
    from . import rollup as mod_rollup
    opts = dn_parse_args(argv, ['tree', 'interval', 'min-gens'])
    check_arg_count(opts, 0)
    if opts.interval not in ('hour', 'day'):
        fatal(DNError('interval not supported: "%s"' % opts.interval))
    min_gens = 1
    if opts.min_gens is not None:
        try:
            min_gens = int(opts.min_gens)
            if min_gens < 1:
                raise ValueError(opts.min_gens)
        except ValueError:
            raise UsageError('bad value for "min-gens": "%s"'
                             % opts.min_gens)
    total = {'groups': 0, 'compacted': 0, 'generations_removed': 0}
    for dsname, root in _integrity_trees(opts):
        try:
            doc = mod_rollup.compact_tree(root, opts.interval,
                                          min_gens=min_gens)
        except (DNError, OSError) as e:
            fatal(e if isinstance(e, DNError) else DNError(str(e)))
        for k in total:
            total[k] += doc[k]
        if doc['paused']:
            sys.stderr.write('dn compact: paused under resource '
                             'pressure (tree "%s")\n' % root)
    sys.stderr.write('dn compact: %d group(s) compacted, %d '
                     'generation(s) removed\n'
                     % (total['compacted'],
                        total['generations_removed']))
    return 0


COMMANDS = {
    'datasource-add': cmd_datasource_add,
    'datasource-list': cmd_datasource_list,
    'datasource-remove': cmd_datasource_remove,
    'datasource-update': cmd_datasource_update,
    'datasource-show': cmd_datasource_show,
    'metric-add': cmd_metric_add,
    'metric-list': cmd_metric_list,
    'metric-remove': cmd_metric_remove,
    'build': cmd_build,
    'events': cmd_events,
    'follow': cmd_follow,
    'index-config': cmd_index_config,
    'index-read': cmd_index_read,
    'index-scan': cmd_index_scan,
    'compact': cmd_compact,
    'query': cmd_query,
    'quarantine': cmd_quarantine,
    'rollup': cmd_rollup,
    'scan': cmd_scan,
    'scrub': cmd_scrub,
    'serve': cmd_serve,
    'stats': cmd_stats,
    'subscribe': cmd_subscribe,
    'top': cmd_top,
    'topo': cmd_topo,
}


def main(argv=None, startup=None):
    """startup=(process_t0, require_seconds) from bin/dn.py lets -t
    split module-load cost from total, like the reference's
    require-vs-total timing (bin/dn:80-83,1290-1296)."""
    if argv is None:
        argv = sys.argv[1:]

    track_time = False
    if argv and argv[0] == '-t':
        track_time = True
        argv = argv[1:]

    import time
    t0 = time.time()
    require_s = None
    if startup is not None:
        t0, require_s = startup[0], startup[1]

    rv = None
    try:
        if len(argv) < 1:
            raise UsageError('no command specified')
        cmdname = argv[0]
        if cmdname not in COMMANDS:
            raise UsageError('no such command: "%s"' % cmdname)

        backend = mod_config.ConfigBackendLocal()
        err, config = backend.load()
        if err is not None and not getattr(err, 'is_enoent', False):
            fatal(err)
        ctx = {'backend': backend, 'config': config}
        rv = COMMANDS[cmdname](ctx, argv[1:])
    except UsageError as e:
        if e.message:
            sys.stderr.write('%s: %s\n' % (ARG0, e.message))
        sys.stderr.write(USAGE_TEXT)
        return 2
    except FatalError as e:
        sys.stderr.write('%s: %s\n' % (ARG0, e.message))
        return 1
    except BrokenPipeError:
        return 0

    if track_time:
        sys.stderr.write('timing stats:\n')
        if require_s is not None:
            sys.stderr.write('    require:  %.3fs\n' % require_s)
        sys.stderr.write('    total:    %.3fs\n' % (time.time() - t0))
    # remote-executing commands propagate the server's exit code
    return rv if isinstance(rv, int) else 0
