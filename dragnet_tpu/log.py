"""Structured logging gated by LOG_LEVEL — the bunyan role in the
reference (bin/dn:68-71 creates the root logger with level from
LOG_LEVEL, default warn; components get child loggers, e.g.
lib/datasource-file.js:102,224,494).

Log records are bunyan-shaped JSON lines on stderr:

    {"name":"dn","component":"datasource-file","level":30,
     "msg":"scan start","time":"...","pid":...,"hostname":"...",...}

plus arbitrary structured fields per call.  The level check is a
single integer compare, so disabled levels cost nothing on hot paths;
`enabled_for()` guards any record assembly that is itself expensive.
"""

import json
import os
import socket
import sys
import time

TRACE = 10
DEBUG = 20
INFO = 30
WARN = 40
ERROR = 50
FATAL = 60

_NAMES = {'trace': TRACE, 'debug': DEBUG, 'info': INFO,
          'warn': WARN, 'error': ERROR, 'fatal': FATAL}


def _iso_now():
    t = time.time()     # one clock read: seconds and millis agree
    return time.strftime('%Y-%m-%dT%H:%M:%S', time.gmtime(t)) + \
        ('.%03dZ' % (int(t * 1000) % 1000))


def _env_level():
    """LOG_LEVEL by name or bunyan numeric value; default warn."""
    raw = (os.environ.get('LOG_LEVEL') or 'warn').strip().lower()
    if raw in _NAMES:
        return _NAMES[raw]
    try:
        return int(raw)
    except ValueError:
        return WARN


class Logger(object):
    __slots__ = ('name', 'component', 'level', 'stream', '_fields')

    def __init__(self, name='dn', component=None, level=None,
                 stream=None, fields=None):
        self.name = name
        self.component = component
        self.level = _env_level() if level is None else level
        self.stream = stream
        self._fields = fields or {}

    def child(self, component, **fields):
        """Per-component child logger (the bunyan child idiom)."""
        merged = dict(self._fields)
        merged.update(fields)
        return Logger(self.name, component=component, level=self.level,
                      stream=self.stream, fields=merged)

    def enabled_for(self, level):
        return level >= self.level

    def _log(self, level, msg, fields):
        if level < self.level:
            return
        rec = {
            'name': self.name,
            'hostname': socket.gethostname(),
            'pid': os.getpid(),
            'level': level,
            'msg': msg,
            'time': _iso_now(),
            'v': 0,
        }
        if self.component is not None:
            rec['component'] = self.component
        rec.update(self._fields)
        if fields:
            rec.update(fields)
        stream = self.stream or sys.stderr
        try:
            stream.write(json.dumps(rec, default=str) + '\n')
        except Exception:
            pass   # logging must never take the process down

    def trace(self, msg, **fields):
        self._log(TRACE, msg, fields)

    def debug(self, msg, **fields):
        self._log(DEBUG, msg, fields)

    def info(self, msg, **fields):
        self._log(INFO, msg, fields)

    def warn(self, msg, **fields):
        self._log(WARN, msg, fields)

    def error(self, msg, **fields):
        self._log(ERROR, msg, fields)

    def fatal(self, msg, **fields):
        self._log(FATAL, msg, fields)


_root = None


def root():
    global _root
    if _root is None:
        _root = Logger('dn')
    return _root


def get(component, **fields):
    """Child logger for a component (cached root; level from
    LOG_LEVEL at first use).  Extra fields ride on every record —
    `dn serve` uses this for per-request loggers (req=N)."""
    return root().child(component, **fields)
