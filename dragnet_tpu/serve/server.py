"""The `dn serve` daemon: a long-lived multi-threaded server that
executes scan/build/query requests with warm process state.

Every `dn query` today pays full cold start — interpreter boot, jit
compilation, shard-handle/find-memo/audition-cache warm-up — per
invocation.  The warm-path machinery only earns its keep when one
process lives across requests; this server is that process.  It holds:

* the shard-handle LRU + whole-tree find memo (index_query_mt),
* the persisted audition-verdict cache and compiled device
  executables (device_scan / ops),
* the stacked cross-shard execution path (index_query_stack), which
  request coalescing (admission.py) turns into one aggregation for N
  compatible concurrent queries.

Protocol: newline-JSON over a unix socket (TCP optional), framed by
serve/protocol.py.  v1 (legacy, still served byte-identically): one
request per connection.  Request: one JSON line, e.g.

    {"op": "query", "ds": "muskie", "config": "/path/.dragnetrc",
     "queryconfig": {"breakdowns": [...], "filter": ...},
     "interval": "day", "opts": {"raw": false, "counters": true}}

Response: one JSON header line {"ok": bool, "rc": int, "nout": N,
"nerr": M, "stats": {...}} followed by exactly N stdout bytes and M
stderr bytes.  v2 (negotiated by a `"proto": 2` field plus a request
`"id"`): the same frames on a PERSISTENT multiplexed connection —
requests pipeline, responses return out of order tagged with the
request id, and the connection front end is a selector loop
(serve/ioloop.py) so idle connections cost no threads and half-dead
peers are reaped on read/write deadlines.  The payload bytes are
BYTE-IDENTICAL to what the local CLI command would have written —
requests execute through the same datasource entry points and the
same output layer, with each worker thread's stdout/stderr routed to
per-request buffers (the thread-stdio router below), and coalesced
requests demuxed through private ScanResult clones.

Overload posture (admission.py): per-tenant weighted-fair admission
(tenants from the request's `tenant` field, defaulting to peer
identity), deadline propagation (`deadline_ms` rides client -> router
-> member partials), and early load shedding — a request whose
remaining deadline cannot cover the observed service time is rejected
with a clean retryable error carrying `retry_after_ms` BEFORE it
occupies an execution slot.  Under N× capacity the server degrades —
honest 429/503-style rejections — instead of collapsing.

Ops: scan, query, build, stats, ping (+ a `_sleep` debug op when
DN_SERVE_TEST_OPS=1, used by the lifecycle tests to hold slots).
"""

import codecs
import contextlib
import io
import json
import os
import signal
import socket
import sys
import threading
import time

from .. import cli as mod_cli
from .. import config as mod_config
from .. import faults as mod_faults
from .. import integrity as mod_integrity
from .. import resources as mod_resources
from .. import vpipe as mod_vpipe
from .. import index_query_mt as mod_iqmt
from .. import log as mod_log
from ..errors import DNError
from ..obs import events as obs_events
from ..obs import export as obs_export
from ..obs import history as obs_history
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..watchdog import LeakCheck
from . import admission as mod_admission
from . import ioloop as mod_ioloop
from . import lifecycle as mod_lifecycle
from . import protocol as mod_protocol
from . import qcache as mod_qcache
from . import residency as mod_residency
from . import subscribe as mod_subscribe

MAX_REQUEST_BYTES = mod_protocol.MAX_FRAME_BYTES

# a server that exits while `running` never drained: in-flight
# requests (and their clients) may have been dropped on the floor
_SERVER_LEAKS = LeakCheck(
    'dn serve server(s) never drained; in-flight requests may have '
    'been dropped', lambda s: s.running)


# -- output-encoding parity with bin/dn.py ----------------------------------

def _dn_fffd(err):
    return ('�' * (err.end - err.start), err.end)


def output_errors():
    """The error handler name request buffers encode with — the same
    lone-surrogate -> U+FFFD behavior bin/dn.py installs on the real
    stdout, so response bytes match the CLI's byte-for-byte."""
    try:
        codecs.lookup_error('dn_fffd')
    except LookupError:
        codecs.register_error('dn_fffd', _dn_fffd)
    return 'dn_fffd'


# -- thread-directed stdio --------------------------------------------------
#
# The CLI output layer writes to sys.stdout / sys.stderr directly, and
# that is exactly what guarantees byte parity — so instead of
# refactoring every write site, the server routes the PROCESS streams
# through a per-thread binding: worker threads bind their request
# buffers, every other thread falls through to the real stream.  The
# binding registry is module-global (not per-router-instance) so a
# router displaced by test harnesses that swap sys.stdout can be
# reinstalled at any time without stranding live bindings.

_STDIO_TLS = threading.local()
_STDIO_LOCK = threading.Lock()


class _ThreadStream(object):
    def __init__(self, which, fallback):
        self._which = which
        self._fallback = fallback

    def _target(self):
        bound = getattr(_STDIO_TLS, self._which, None)
        return self._fallback if bound is None else bound

    def write(self, data):
        return self._target().write(data)

    def writelines(self, lines):
        return self._target().writelines(lines)

    def flush(self):
        return self._target().flush()

    def __getattr__(self, name):
        return getattr(self._target(), name)


def install_stdio_router():
    """Idempotently route sys.stdout/sys.stderr through the
    thread-binding proxies (re-wrapping whatever stream is current if
    something replaced them since the last install)."""
    with _STDIO_LOCK:
        if not isinstance(sys.stdout, _ThreadStream):
            sys.stdout = _ThreadStream('out', sys.stdout)
        if not isinstance(sys.stderr, _ThreadStream):
            sys.stderr = _ThreadStream('err', sys.stderr)


class _Capture(object):
    """Per-request byte buffers presented as text streams (utf-8 with
    the CLI's surrogate policy)."""

    def __init__(self):
        errors = output_errors()
        self.out_b = io.BytesIO()
        self.err_b = io.BytesIO()
        self.out_t = io.TextIOWrapper(self.out_b, encoding='utf-8',
                                      errors=errors, newline='')
        self.err_t = io.TextIOWrapper(self.err_b, encoding='utf-8',
                                      errors=errors, newline='')

    def finish(self):
        """Flush and return (stdout_bytes, stderr_bytes); the buffers
        detach so the text wrappers' GC cannot close them early."""
        self.out_t.flush()
        self.err_t.flush()
        out, err = self.out_b.getvalue(), self.err_b.getvalue()
        self.out_t.detach()
        self.err_t.detach()
        return out, err


@contextlib.contextmanager
def bound_stdio(capture):
    """Bind THIS thread's sys.stdout/sys.stderr to the capture."""
    install_stdio_router()
    prior = (getattr(_STDIO_TLS, 'out', None),
             getattr(_STDIO_TLS, 'err', None))
    _STDIO_TLS.out = capture.out_t
    _STDIO_TLS.err = capture.err_t
    try:
        yield
    finally:
        _STDIO_TLS.out, _STDIO_TLS.err = prior


@contextlib.contextmanager
def thread_stdio():
    """Capture this thread's CLI output as bytes (tests use this to
    compute expected local bytes through the same router the server
    routes through): yields the _Capture; read via .finish()."""
    cap = _Capture()
    with bound_stdio(cap):
        yield cap


# -- request options shim ---------------------------------------------------

class _ReqOpts(object):
    """The parsed-options surface cli.dn_query_config / cli.dn_output
    expect, rebuilt from a request's shipped documents."""


def _opts_shim(req):
    o = _ReqOpts()
    qc = req.get('queryconfig') or {}
    o.breakdowns = qc.get('breakdowns') or []
    o.after = qc.get('timeAfter')
    o.before = qc.get('timeBefore')
    o.filter = qc.get('filter')
    opts = req.get('opts') or {}
    for name in ('raw', 'points', 'counters', 'gnuplot'):
        setattr(o, name, opts.get(name))
    o.dry_run = bool(opts.get('dry_run'))
    o.interval = req.get('interval')
    return o


def _config_ident(path):
    try:
        st = os.stat(path)
        return [path, st.st_mtime_ns, st.st_size]
    except OSError:
        return [path, None, None]


_DEVICE_SIGNALS = ('ndevicebatches', 'nstackedbatches',
                   'index device sums')


def device_engaged(counters):
    return any(counters.get(k) for k in _DEVICE_SIGNALS)


# -- the server -------------------------------------------------------------

class DnServer(object):
    def __init__(self, socket_path=None, port=None, host='127.0.0.1',
                 conf=None, pidfile=None, cluster=None, member=None,
                 router_conf=None, pending=None, topo_conf=None):
        if conf is None:
            conf = mod_config.serve_config()
        if isinstance(conf, DNError):
            raise conf
        assert (socket_path is None) != (port is None), \
            'exactly one of socket_path/port'
        # embedders (tests, soaks) pass partial conf dicts; the newer
        # front-end/tenancy knobs fall back to their defaults
        full = mod_config.serve_config(env={})
        full.update(conf)
        self.conf = conf = full
        # cluster mode (`--cluster=TOPOLOGY.json --member=NAME`): this
        # server owns its partitions of the index tree and acts as
        # scatter-gather router for incoming queries (serve/router.py)
        self.cluster = cluster
        self.member = member
        self.router = None
        # dynamic topology (serve/coordinator.py): the committed map
        # can be swapped while serving, a pending epoch streams its
        # handoff (serve/rebalance.py), and DN_TOPO_POLL_MS > 0 polls
        # the topology file for both
        if topo_conf is None:
            topo_conf = mod_config.topo_config()
        if isinstance(topo_conf, DNError):
            raise topo_conf
        self.topo_conf = topo_conf
        self.pending = None
        self._initial_pending = pending
        self.puller = None
        self.topo_watcher = None
        self.topo_leaving = False
        self._topo_lock = threading.Lock()
        self._topo_counters = {'transitions': 0,
                               'mismatch_rejections': 0,
                               'resyncs': 0,
                               'handoff_rejections': 0,
                               'handoff_retries': 0}
        if cluster is not None:
            from . import router as mod_router
            self.router = mod_router.Router(
                cluster, member, conf=router_conf,
                local_exec=self._local_partial,
                self_draining=lambda: self.draining,
                self_degraded=lambda: self.governor.is_read_only())
        self.socket_path = socket_path
        self.port = port
        self.host = host
        self.pidfile = pidfile
        self.bound_port = None
        # shard integrity (integrity.py, serve/scrub.py): verified
        # reads quarantine + reject retryably; the repair manager
        # pulls good copies from co-replicas in the background; the
        # scrub thread (DN_SCRUB_INTERVAL_S) sweeps proactively
        integ_conf = mod_config.integrity_config()
        if isinstance(integ_conf, DNError):
            raise integ_conf
        self.integrity_conf = integ_conf
        # resource governance (resources.py): disk watermarks drive
        # explicit low/critical modes (background consumers pause,
        # then the member flips read-only while queries keep serving
        # byte-identically); the memory budget sheds over-footprint
        # admissions with retry hints
        res_conf = mod_config.resources_config()
        if isinstance(res_conf, DNError):
            raise res_conf
        self._resource_paths_memo = (None, 0.0)
        self.governor = mod_resources.ResourceGovernor(
            res_conf, paths=self._resource_paths, member=member)
        from . import scrub as mod_scrub
        self.repair = mod_scrub.RepairManager(self)
        self.scrubber = None
        self.maintainer = None
        self.admission = mod_admission.Admission(
            conf['max_inflight'], conf['queue_depth'],
            tenant_quota=conf['tenant_quota'],
            tenant_weights=conf['tenant_weights'],
            tenant_default_weight=conf['tenant_default_weight'])
        self.coalescer = mod_admission.Coalescer(conf['coalesce'])
        # query-result cache (serve/qcache.py): repeat identical
        # queries answer from memory — no lease, no admission slot —
        # invalidated by the writer-invalidation epoch + tree stat
        # validators, residency charged against the governor's shared
        # memory budget.  DN_SERVE_CACHE_MB=0 (default) disables.
        self.qcache = mod_qcache.ResultCache(
            conf['cache_mb'] << 20, governor=self.governor)
        # device-lane serving (serve/residency.py): pinned HBM
        # accumulators answer repeat stacked aggregations with zero
        # transfer either direction, invalidated by the same writer
        # epoch as the result cache.  The HBM budget is deliberately
        # NOT charged to the host governor — different resource.
        # DN_DEVICE_RESIDENCY_MB=0 (default) disables.
        dev_conf = mod_config.device_config()
        if isinstance(dev_conf, DNError):
            raise dev_conf
        self.device_conf = dev_conf
        # index-query device-lane knobs (device_index.py) validated
        # with the same fail-fast contract; the residency share caps
        # how much HBM pinned shard tensors may occupy
        iq_conf = mod_config.index_device_config()
        if isinstance(iq_conf, DNError):
            raise iq_conf
        self.index_device_conf = iq_conf
        mod_residency.configure(dev_conf['residency_mb'] << 20)
        self._prewarm_doc = None
        # fleet observability (obs/history.py, obs/events.py,
        # serve/fleet.py): the metric-history snapshotter and the
        # event journal are armed at bind from DN_METRICS_HISTORY_S /
        # DN_EVENTS — both off by default, costing nothing disabled
        self.history = None
        self.log = mod_log.get('serve')
        # standing queries (serve/subscribe.py): registered v2
        # subscribers get delta/full result frames PUSHED on publish
        # — one incremental merge per publish batch serves all of
        # them.  DN_SUB_MAX=0 disables (requests answer cleanly).
        self.subman = mod_subscribe.SubscriptionManager(self)
        self.running = False
        self.draining = False
        self._listener = None
        self.loop = None
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._workers = set()
        self._workers_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._counters = {'requests': 0, 'errors': 0,
                          'busy_rejected': 0, 'deadline_expired': 0,
                          'draining_rejected': 0,
                          'shed_overloaded': 0,
                          'build_idem_replays': 0}
        # build idempotency: key -> {'done': Event, 'result': tuple}.
        # A retried `dn build --remote` (same client-generated key)
        # replays the recorded response instead of double-writing.
        self._idem_lock = threading.Lock()
        self._idem = {}
        self._by_op = {}
        # monotonic for durations (uptime_s must not jump when NTP
        # steps the wall clock); wall time kept only as a timestamp
        self._t0 = time.monotonic()
        self._started_wall = time.time()
        self._hook = None
        self._thread = None
        # per-index-tree reader/writer locks (admission.TreeLock):
        # index queries read-lock, builds write-lock — concurrent
        # builds over one tree would race on the writer's per-PID tmp
        # names (one process = one pid), and a query walking a tree
        # mid-rewrite would see tmp litter and partial shard sets
        self._tree_locks = {}
        self._tree_locks_lock = threading.Lock()

    # -- lifecycle --------------------------------------------------------

    def bind(self):
        if self.socket_path is not None:
            listener = socket.socket(socket.AF_UNIX,
                                     socket.SOCK_STREAM)
            listener.bind(self.socket_path)
        else:
            listener = socket.socket(socket.AF_INET,
                                     socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET,
                                socket.SO_REUSEADDR, 1)
            listener.bind((self.host, self.port))
            self.bound_port = listener.getsockname()[1]
        listener.listen(512)
        self._listener = listener
        # the selector front end (serve/ioloop.py): accepts, frames,
        # reaps; workers are spawned per dispatched request
        self.loop = mod_ioloop.IOLoop(
            listener,
            {'read_deadline_ms': self.conf['read_deadline_ms'],
             'write_deadline_ms': self.conf['write_deadline_ms'],
             'idle_ms': self.conf['idle_ms']},
            on_request=self._on_frame,
            on_overflow=self._on_overflow,
            on_accept=self._on_accept,
            on_close=self.subman.on_conn_close,
            log=self.log)
        self.subman.start()
        self.running = True
        _SERVER_LEAKS.track(self)
        self._hook = mod_lifecycle.install_writer_invalidation()
        if self.router is not None:
            self.router.start()
        if self.cluster is not None:
            obs_metrics.set_gauge('topo_epoch', self.cluster.epoch)
            if self._initial_pending is not None:
                # started mid-transition (e.g. a fresh joiner): begin
                # the handoff immediately
                self.apply_topology(self.cluster,
                                    self._initial_pending)
                self._initial_pending = None
            if self.cluster.path and self.topo_conf['poll_ms'] > 0:
                from . import coordinator as mod_coordinator
                self.topo_watcher = mod_coordinator.TopologyWatcher(
                    self, self.cluster.path,
                    self.topo_conf['poll_ms'],
                    log=self.log).start()
        if self.integrity_conf['scrub_interval_s'] > 0:
            from . import scrub as mod_scrub
            self.scrubber = mod_scrub.ScrubThread(
                self, self.integrity_conf['scrub_interval_s'],
                self.integrity_conf['scrub_rate_mb_s'] << 20,
                log=self.log).start()
        if self.integrity_conf['rollup_interval_s'] > 0 or \
                self.integrity_conf['compact_interval_s'] > 0:
            # the rollup/compaction timer (serve/scrub.py): refresh
            # day/month rollup shards and fold follow --append
            # mini-generations in the background, governor-paused
            # under disk pressure
            from . import scrub as mod_scrub
            self.maintainer = mod_scrub.MaintenanceThread(
                self, self.integrity_conf['rollup_interval_s'],
                self.integrity_conf['compact_interval_s'],
                self.integrity_conf['compact_min_gens'],
                log=self.log).start()
        # the event journal is per-PROCESS (emit sites are global,
        # like DN_TRACE): the first server to bind installs it;
        # embedded co-process members share it (the fleet merge
        # dedupes their identical tails)
        if obs_events.journal() is None:
            obs_events.install(member=self.member)
        # the resource governor polls in the background so gauges and
        # mode transitions stay fresh even on an idle server, and
        # recovery from critical is automatic with no request traffic
        self.governor.start()
        # serve-time device pre-warm (serve/residency.py): compile
        # the stacked index-query programs and load the persisted
        # audition cache on a background thread so the first request
        # never pays compile or probe latency.  Gated on the engine
        # being able to reach the device lane at all; bounded by the
        # probe deadline inside prewarm() — a wedged plugin costs a
        # bounded background wait, never a hung bind.
        if self.device_conf['prewarm'] and self._device_lane_possible():
            threading.Thread(target=self._run_prewarm,
                             name='dn-prewarm', daemon=True).start()
        hist_s = obs_history.history_interval_s()
        if hist_s > 0:
            self.history = obs_history.HistorySnapshotter(
                hist_s, provider=self._history_provider,
                log=self.log).start()
        self.log.info('listening',
                      socket=self.socket_path, port=self.bound_port,
                      member=self.member,
                      max_inflight=self.conf['max_inflight'])

    def serve_forever(self):
        """Run the selector front end (blocks until request_stop);
        drains on exit: stop accepting, finish in-flight, flush
        responses, flush caches, unlink the socket."""
        install_stdio_router()
        self.loop.start()
        try:
            self._stop.wait()
        finally:
            self._drain()

    def start(self):
        """Embedded mode (tests, benchmarks): bind if needed and run
        the accept loop on a background thread."""
        if self._listener is None:
            self.bind()
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def request_stop(self):
        # queued-but-unadmitted requests wake NOW with the clean,
        # retryable DrainingError instead of dying with the listener;
        # admitted executions finish inside the drain grace
        self.draining = True
        self.admission.shutdown()
        self._stop.set()

    def stop(self, wait=True):
        self.request_stop()
        if self._thread is not None and wait:
            self._thread.join(self.conf['drain_s'] + 5)
        elif wait:
            self._drained.wait(self.conf['drain_s'] + 5)

    def _drain(self):
        if self._drained.is_set():
            return
        self.loop.stop_accepting()
        deadline = time.monotonic() + self.conf['drain_s']
        with self._workers_lock:
            workers = list(self._workers)
        for t in workers:
            t.join(max(0.0, deadline - time.monotonic()))
        leftover = sum(1 for t in workers if t.is_alive())
        if leftover:
            self.log.warn('drain grace expired', abandoned=leftover)
        # standing queries end cleanly: each subscriber gets an 'end'
        # frame queued before the loop flushes and closes below
        self.subman.stop()
        # flush queued response bytes (the draining rejections the
        # workers just framed included), then close every connection
        self.loop.shutdown(max(1.0, deadline - time.monotonic() + 1))
        if self.topo_watcher is not None:
            self.topo_watcher.stop()
        if self.history is not None:
            self.history.stop()
        if self.scrubber is not None:
            self.scrubber.stop()
        if self.maintainer is not None:
            self.maintainer.stop()
        self.governor.stop()
        self.repair.stop()
        if self.puller is not None:
            self.puller.stop()
        if self.router is not None:
            self.router.stop()
        # flush warm state cleanly: cached shard handles hold open
        # mmaps / sqlite connections; the result cache hands its
        # reserved governor bytes back
        self.qcache.clear()
        mod_iqmt.shard_cache_clear()
        # drop every pinned device array so the backend can reclaim
        # the HBM, and deregister the residency gauges
        mod_residency.deconfigure()
        if self._hook is not None:
            mod_lifecycle.remove_writer_invalidation(self._hook)
            self._hook = None
        mod_lifecycle.release(socket_path=self.socket_path,
                              pidfile=self.pidfile)
        self.running = False
        _SERVER_LEAKS.untrack(self)
        self._drained.set()
        self.log.info('drained', requests=self._counters['requests'])

    # -- device lane (serve/residency.py) ---------------------------------

    def _device_lane_possible(self):
        """Can this process's engine mode ever reach the device lane?
        Cheap env/topology inspection only — never initializes the
        backend (that is the pre-warm thread's job, under deadline)."""
        from .. import engine as mod_engine
        mode = (mod_engine.engine_mode() or 'auto').strip().lower()
        if mode == 'jax':
            return True
        if mode != 'auto':
            return False
        from ..ops import accelerator_likely
        try:
            return bool(accelerator_likely())
        except Exception:
            return False

    def _run_prewarm(self):
        try:
            doc = mod_residency.prewarm(
                deadline_s=self.device_conf['probe_timeout_s'])
        except Exception as e:        # honest doc over a dead thread
            doc = {'state': 'failed', 'error': str(e)}
        self._prewarm_doc = doc
        self.log.info('device prewarm', state=doc.get('state'),
                      backend=doc.get('backend'),
                      programs=doc.get('programs'),
                      auditions=doc.get('auditions'),
                      ms=doc.get('ms'))

    # -- dynamic topology -------------------------------------------------

    def apply_topology(self, committed, pending):
        """The live-membership cutover (TopologyWatcher calls this on
        every observed change; also called at bind for a server
        started mid-transition).  Idempotent: same-epoch re-applies
        are no-ops.  A committed epoch bump swaps the serving map
        atomically (router probers/pool conns for departed members
        retire); a pending epoch starts the shard handoff."""
        if self.cluster is None:
            return
        with self._topo_lock:
            if committed.epoch > self.cluster.epoch:
                self.cluster = committed
                self.topo_leaving = \
                    self.member not in committed.members
                if self.router is not None:
                    self.router.update_topology(committed)
                self._topo_counters['transitions'] += 1
                obs_metrics.inc('topo_epoch_transitions_total')
                obs_metrics.set_gauge('topo_epoch', committed.epoch)
                obs_events.emit('topo.commit', epoch=committed.epoch,
                                leaving=self.topo_leaving or None)
                self.log.info('topology committed',
                              epoch=committed.epoch,
                              leaving=self.topo_leaving)
            if pending is not None and \
                    pending.epoch > self.cluster.epoch:
                # dedupe by CONTENT, not epoch number: an abort
                # followed by a re-apply reuses committed+1, and a
                # member that only saw the final file must not keep
                # the withdrawn map's handoff state (serving the new
                # assignments with the old pull's shards would be a
                # silently short shard set)
                if self.pending is None or \
                        self.pending.epoch != pending.epoch or \
                        self.pending.doc() != pending.doc():
                    self.pending = pending
                    obs_metrics.set_gauge('topo_pending_epoch',
                                          pending.epoch)
                    obs_events.emit('topo.pending',
                                    epoch=pending.epoch)
                    self._start_handoff(self.cluster, pending)
                    self.log.info('topology pending',
                                  epoch=pending.epoch)
            elif self.pending is not None and \
                    (pending is None or
                     self.pending.epoch <= self.cluster.epoch):
                # resolved: committed (the puller's ready flag keeps
                # gating until its pull finishes) or aborted
                resolved = self.pending
                self.pending = None
                obs_metrics.set_gauge('topo_pending_epoch', 0)
                if pending is None and \
                        resolved.epoch > self.cluster.epoch:
                    obs_events.emit('topo.abort',
                                    epoch=resolved.epoch)
                if pending is None and self.puller is not None and \
                        self.puller.target_epoch == resolved.epoch \
                        and resolved.epoch > self.cluster.epoch:
                    # aborted outright: stop a pull for the withdrawn
                    # epoch (streamed shards are harmless litter the
                    # partition filter ignores)
                    self.puller.stop()
                    self.puller = None

    def _start_handoff(self, committed, pending):
        """Spawn the shard puller for a pending epoch (call with
        _topo_lock held).  Members LEAVING in the pending map pull
        nothing — they are demoted (health reports draining) and
        removed only after the commit, when ownership has moved."""
        if self.member is None or self.member not in pending.members:
            if self.puller is not None:
                self.puller.stop()
            self.puller = None
            return
        from . import rebalance as mod_rebalance
        if self.puller is not None:
            self.puller.stop()
        self.puller = mod_rebalance.HandoffPuller(
            committed, pending, self.member,
            topo_conf=self.topo_conf, log=self.log,
            governor=self.governor).start()

    def retry_failed_handoff(self):
        """Restart a FAILED pull for the still-pending epoch (the
        watcher calls this every poll): a donor that was transiently
        unreachable past the retry budget must not wedge the
        transition until a process restart.  One attempt per poll,
        never concurrent (only a finished, failed puller restarts);
        a pull left failed after a forced early commit is out of
        scope — its donors have moved epochs and the operator
        explicitly chose the degraded window."""
        with self._topo_lock:
            puller, pending = self.puller, self.pending
            if pending is None or puller is None or \
                    puller.target_epoch != pending.epoch:
                return False
            if puller.ready or not puller.failed or \
                    not puller.wait(0):
                return False
            self._topo_counters['handoff_retries'] = \
                self._topo_counters.get('handoff_retries', 0) + 1
            self.log.info('retrying failed handoff',
                          epoch=pending.epoch, error=puller.error)
            self._start_handoff(self.cluster, pending)
            return True

    def _topo_leaving_now(self):
        """Demotion signal: True once this member is absent from the
        pending map (leaving as soon as the transition starts, per
        the demote-then-remove contract) or from the committed map
        (already removed)."""
        with self._topo_lock:
            if self.cluster is None:
                return False
            if self.topo_leaving:
                return True
            return self.pending is not None and \
                self.member not in self.pending.members

    def _serving_for_epoch(self, epoch, pids=None):
        """The topology a partial at `epoch` executes under, with the
        epoch-mismatch and handoff gates applied.  Accepts the
        committed epoch always, and the pending epoch during a
        transition window (commits propagate asynchronously — a
        router that saw the commit first must not be reject-stormed
        by members that have not polled yet).  Raises the retryable
        mismatch/handoff-incomplete DNErrors otherwise."""
        with self._topo_lock:
            committed, pending = self.cluster, self.pending
            puller = self.puller
        serving = None
        if epoch == committed.epoch:
            serving = committed
        elif pending is not None and epoch == pending.epoch:
            serving = pending
        if serving is None:
            with self._topo_lock:
                self._topo_counters['mismatch_rejections'] += 1
            obs_metrics.inc('topo_epoch_mismatch_total')
            have = str(committed.epoch)
            if pending is not None:
                have += '/pending %d' % pending.epoch
            e = DNError('topology epoch mismatch (member has %s, '
                        'router sent %s)' % (have, epoch))
            e.retryable = True
            e.epoch_mismatch = True
            e.current_epoch = committed.epoch
            raise e
        if puller is not None and not puller.ready and \
                puller.target_epoch == epoch and pids is not None and \
                (set(pids) & puller.affected_pids):
            # this member's shards for the requested partitions are
            # still streaming in: serving now would return a SHORT
            # shard set with rc=0 — reject retryably instead (the
            # router fails over to a replica that has the bytes)
            with self._topo_lock:
                self._topo_counters['handoff_rejections'] += 1
            e = DNError('handoff incomplete for partition(s) %s '
                        '(epoch %d): shards still streaming'
                        % (','.join(str(p) for p in sorted(
                            set(pids) & puller.affected_pids)),
                           epoch))
            e.retryable = True
            raise e
        return serving

    def topology_doc(self):
        """The /stats `topology` section and the `topology` op body:
        current/pending epochs, handoff progress, transition
        counters, watcher telemetry — what the coordinator polls for
        commit readiness and dashboards scrape."""
        with self._topo_lock:
            committed, pending = self.cluster, self.pending
            puller = self.puller
            counters = dict(self._topo_counters)
        doc = {'member': self.member,
               'configured': committed is not None}
        if committed is None:
            return doc
        doc.update({
            'epoch': committed.epoch,
            'state': 'pending' if pending is not None
            else 'committed',
            'pending_epoch': pending.epoch
            if pending is not None else None,
            'leaving': self._topo_leaving_now(),
            'source': committed.path,
            'poll_ms': self.topo_conf['poll_ms'],
            'partitions_owned':
            committed.partitions_of(self.member),
            'counters': counters,
        })
        doc['handoff'] = puller.status() if puller is not None \
            else None
        if pending is not None:
            ready = puller is not None and \
                puller.target_epoch == pending.epoch and puller.ready
            doc['handoff_ready'] = ready
            note = getattr(pending, 'note', None)
            if note is not None:
                doc['pending_note'] = note
        else:
            doc['handoff_ready'] = puller is None or puller.ready
        if self.topo_watcher is not None:
            doc['watcher'] = self.topo_watcher.stats()
        return doc

    # -- stats ------------------------------------------------------------

    def _bump(self, name, n=1):
        with self._stats_lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def _resource_paths(self):
        """Index roots the resource governor watches (30s-memoized:
        resolving them loads the member config, which must not run
        once per 2s poll)."""
        paths, at = self._resource_paths_memo
        now = time.monotonic()
        if paths is not None and now - at < 30.0:
            return paths
        paths = []
        try:
            from . import scrub as mod_scrub
            for dsname, ds in mod_scrub.member_datasources(self):
                paths.append(ds.ds_indexpath)
        except Exception:
            pass
        self._resource_paths_memo = (paths, now)
        return paths

    def _admit_resources(self, op, ds):
        """Memory-budget admission (resources.py): reserve the
        request's estimated footprint for its lifetime; an
        over-budget request sheds through the PR 10 OverloadedError
        path with an honest retry hint.  Returns the lease (release
        exactly-or-more-than once)."""
        try:
            return self.governor.admit_request(op, ds)
        except mod_resources.MemoryBudgetError as e:
            obs_metrics.inc('serve_shed_total', reason='memory')
            raise mod_admission.OverloadedError(
                e.message,
                retry_after_ms=self.admission.retry_after_ms())

    def _quarantine_usage(self):
        """The quarantine_bytes/quarantine_files gauges for /stats
        `recovery`: `.dn_quarantine/` is moved-into by every
        corrupt-detect and crash rollback and pruned only by `dn
        quarantine clean` — a long-lived fault-heavy deployment needs
        its growth VISIBLE."""
        files = 0
        total = 0
        try:
            from . import scrub as mod_scrub
            for dsname, ds in mod_scrub.member_datasources(self):
                q = mod_integrity.quarantine_stats(ds.ds_indexpath)
                files += q['files']
                total += q['bytes']
        except Exception:
            pass
        obs_metrics.set_gauge('quarantine_bytes', float(total))
        return {'quarantine_files': files, 'quarantine_bytes': total}

    def _bump_op(self, op):
        with self._stats_lock:
            self._counters['requests'] += 1
            self._by_op[op] = self._by_op.get(op, 0) + 1

    def _history_provider(self):
        """Named operational series for the history snapshotter:
        request/shed/error totals (the admission counters predate the
        typed registry), live inflight depth, repair completions, and
        follow ingest lag — the qps / shed-rate / repair-rate /
        ingest-lag trends by their headline names."""
        with self._stats_lock:
            requests = self._counters['requests']
            errors = self._counters['errors']
            shed = (self._counters['shed_overloaded'] +
                    self._counters['busy_rejected'])
        out = {
            'serve.requests': (obs_history.COUNTER_KIND, requests),
            'serve.errors': (obs_history.COUNTER_KIND, errors),
            'serve.shed': (obs_history.COUNTER_KIND, shed),
            'serve.inflight': (obs_history.GAUGE_KIND,
                               self.admission.depth()['active']),
            'repair.completed': (obs_history.COUNTER_KIND,
                                 self.repair.stats()['completed']),
        }
        from ..follow import stats_doc as follow_stats
        fs = follow_stats()
        if fs is not None:
            out['follow.ingest_lag_ms'] = (
                obs_history.GAUGE_KIND, fs.get('ingest_lag_ms'))
        return out

    def _pipeline_doc(self):
        """Device pipelined-dispatch gauges, read back from the typed
        registry the scan path writes (device_scan._note_dispatch):
        the same numbers Prometheus exposes, shaped for /stats."""
        from .. import device_scan as mod_ds
        reg = obs_metrics.global_registry()
        h2d = reg.counter('device_h2d_bytes').value
        ov = reg.counter('device_h2d_overlapped_bytes').value
        return {
            'depth': mod_ds.pipeline_depth(),
            'dispatches': reg.counter('device_pipe_dispatches').value,
            'overlapped': reg.counter('device_pipe_overlapped').value,
            'h2d_bytes': h2d,
            'h2d_overlapped_bytes': ov,
            'overlap_ratio': round(ov / h2d, 4) if h2d else 0.0,
            'batch_floor': int(reg.gauge('device_batch_floor').value),
        }

    def _index_query_doc(self):
        """Batched index-query offload telemetry (device_index):
        engagement counters plus the resolved lane mode, shaped for
        /stats alongside the scan-lane pipeline doc."""
        from .. import device_index as mod_di
        doc = mod_di.stats_doc()
        doc['mode'] = self.index_device_conf['mode']
        doc['batch_rows'] = self.index_device_conf['batch_rows']
        return doc

    def _parallel_fetch_doc(self):
        from .. import device_scan as mod_ds
        return mod_ds.parallel_fetch_doc()

    def _scan_merge_doc(self):
        from .. import scan_mt as mod_scan_mt
        ms = mod_scan_mt.merge_stats()
        return {
            'partitions': mod_scan_mt.scan_partitions(),
            'merge_ms': round(ms['merge_ms'], 3),
            'merges': ms['engaged'],
            'rows_in': ms['rows'],
            'unique_rows': ms['unique'],
        }

    def stats_doc(self):
        counters = mod_vpipe.global_counters()
        with self._stats_lock:
            requests = dict(self._counters, by_op=dict(self._by_op))
        requests.update(self.coalescer.stats())
        doc = {
            'pid': os.getpid(),
            'uptime_s': round(time.monotonic() - self._t0, 3),
            'started_at': round(self._started_wall, 3),
            'socket': self.socket_path,
            'port': self.bound_port,
            'draining': self.draining,
            'requests': requests,
            'inflight': self.admission.depth(),
            # per-tenant fair-admission telemetry: weights, queue
            # depths, admitted/shed/completed counters, the live
            # service-time estimate (admission.py)
            'tenants': self.admission.tenants_doc(),
            # connection front-end telemetry: open/accepted conns,
            # v2 negotiation, pipelined frames, reap counters
            # (serve/ioloop.py)
            'protocol': self.loop.stats()
            if self.loop is not None else {},
            # standing-query subscriptions (serve/subscribe.py):
            # active/group gauges, push/shed/recompute counters,
            # per-group and per-subscriber detail
            'subscriptions': self.subman.stats_doc(),
            'caches': {
                'shard_handles': mod_iqmt.shard_cache_stats(),
                'find_memo': mod_iqmt.find_cache_stats(),
                'results': self.qcache.stats(),
                # measured pool-vs-sequential fan-out costs and the
                # strategy the last multi-shard query actually ran
                'index_fanout': mod_iqmt.fanout_stats(),
            },
            'counters': counters,
            'device': {
                'engaged': device_engaged(counters),
                'signals': {k: counters.get(k, 0)
                            for k in _DEVICE_SIGNALS},
                # HBM residency + serve-start pre-warm
                # (serve/residency.py); prewarm is None until the
                # background thread reports (or when gated off)
                'residency': mod_residency.stats(),
                'prewarm': self._prewarm_doc,
                # pipelined-dispatch telemetry (device_scan): window
                # depth, dispatch/overlap counters, and how much of
                # the H2D upload volume rode under compute
                'pipeline': self._pipeline_doc(),
                # batched index-query offload (device_index):
                # dispatch/shard/row engagement, pinned-shard hits
                # and the H2D bytes residency pins saved
                'index_query': self._index_query_doc(),
                # probed concurrent-fetch capability (device_scan);
                # doc records whether the default came from the env
                # override or the one-shot probe
                'parallel_fetch': self._parallel_fetch_doc(),
            },
            # radix-partitioned MT merge telemetry (scan_mt): the
            # configured partition count and the accumulated
            # merge-phase cost since process start
            'scan_merge': self._scan_merge_doc(),
            # resource governance (resources.py): mode, per-tree
            # disk view, fd headroom, memory-budget accounting,
            # transition counters
            'resources': self.governor.stats_doc(),
            # chaos/recovery observability: per-site injection
            # telemetry (empty unless DN_FAULTS armed) and the
            # crash-recovery counters (index_journal)
            'faults': mod_faults.stats(),
            'recovery': dict(
                {k: counters.get(k, 0)
                 for k in ('index recovery rollbacks',
                           'index recovery rollforwards',
                           'index tmps quarantined')},
                **self._quarantine_usage()),
            # shard-integrity observability: verify mode, verified/
            # corrupt/unverified read counters, repair queue +
            # outcomes, last background-scrub summary (integrity.py,
            # serve/scrub.py)
            'integrity': {
                'verify': mod_integrity.verify_mode(),
                'reads_verified':
                counters.get('integrity reads verified', 0),
                'reads_unverified':
                counters.get('integrity reads unverified', 0),
                'corrupt_shards':
                counters.get('integrity corrupt shards', 0),
                'missing_shards':
                counters.get('integrity missing shards', 0),
                'repair': self.repair.stats(),
                'scrub': self.scrubber.stats()
                if self.scrubber is not None else None,
            },
            # rollup-planner engagement (rollup.py via the hidden
            # query counters): fine shards answered from rollups vs
            # every fine-shard read, as a coverage ratio
            'rollup': {
                'covered_shards':
                counters.get('index shards via rollup', 0),
                'rollup_shards_read':
                counters.get('rollup shards queried', 0),
                'shards_queried':
                counters.get('index shards queried', 0),
                'coverage_ratio': round(
                    counters.get('index shards via rollup', 0) /
                    counters.get('index shards queried', 1), 4)
                if counters.get('index shards queried', 0) else 0.0,
            },
            # rollup/compaction timer summary (serve/scrub.py
            # MaintenanceThread): pass counters, compaction backlog;
            # None when both intervals are 0
            'maintenance': self.maintainer.stats()
            if self.maintainer is not None else None,
            # the typed registry (obs/metrics.py): versioned so
            # dashboards can gate on shape; histograms carry
            # p50/p90/p99 and cumulative buckets
            'metrics': obs_export.stats_section(counters=counters),
            # metric-history rings (obs/history.py): windowed
            # qps/shed/repair/lag trends when DN_METRICS_HISTORY_S
            # arms the snapshotter; shape-stable disabled stub
            # otherwise (versioned, like `metrics`)
            'history': self.history.history.doc()
            if self.history is not None
            else obs_history.disabled_doc(),
            # event-journal summary (obs/events.py): capacity/seq/
            # drop counters only — the entries ride the `events` op,
            # never /stats
            'events': obs_events.journal().doc()
            if obs_events.journal() is not None
            else obs_events.disabled_doc(),
        }
        if self.router is not None:
            # scatter-gather observability: per-member breaker
            # states, failover/hedge/degraded counters (router.py)
            doc['cluster'] = self.router.stats_doc()
        if self.cluster is not None:
            # dynamic-topology observability: current/pending epoch,
            # handoff progress, transition counters
            # (serve/coordinator.py, serve/rebalance.py)
            doc['topology'] = self.topology_doc()
        from ..follow import stats_doc as follow_stats
        fs = follow_stats()
        if fs is not None:
            # continuous-ingest telemetry when a follow loop runs in
            # this process: source offsets, batches published,
            # checkpoint age, ingest lag (docs/ingest.md)
            doc['follow'] = fs
        try:
            from ..device_scan import _audition_cache_file
            doc['caches']['audition_verdicts'] = _audition_cache_file()
        except Exception:
            pass
        return doc

    # -- request handling -------------------------------------------------

    # -- connection front end (loop-thread callbacks) ---------------------

    def _on_accept(self, conn):
        """Accept veto hook (loop thread): an injected accept fault
        drops the connection, exactly the failure the client's
        pre-commit retry loop exists for."""
        try:
            mod_faults.fire('serve.accept')
        except mod_faults.FaultInjected:
            return False
        return True

    def _on_overflow(self, conn):
        """A frame grew past MAX_REQUEST_BYTES without a newline: the
        connection cannot be resynchronized — answer with a clean v1
        error and close (loop thread)."""
        msg = ('dn: bad request: frame exceeds %d bytes\n'
               % MAX_REQUEST_BYTES).encode()
        self.loop.send(conn,
                       mod_protocol.encode_response(1, b'', msg, {}),
                       close_after=True)

    def _on_frame(self, conn, line):
        """One complete request line (loop thread): parse, classify
        v1 vs v2, and hand execution to a worker thread.  Never
        blocks — malformed frames are answered (or the connection
        dropped) right here."""
        rx = time.monotonic()
        try:
            req = json.loads(line.decode('utf-8'))
            if not isinstance(req, dict):
                raise ValueError('not an object')
        except (ValueError, UnicodeDecodeError) as e:
            err = ('dn: bad request: %s\n' % e).encode()
            self.loop.send(
                conn, mod_protocol.encode_response(1, b'', err, {}),
                close_after=True, completes=True)
            return
        try:
            proto, rid = mod_protocol.classify_request(req)
        except mod_protocol.FrameError as e:
            err = ('dn: bad request: %s\n' % e).encode()
            self.loop.send(
                conn, mod_protocol.encode_response(1, b'', err, {}),
                close_after=True, completes=True)
            return
        if proto == mod_protocol.PROTO_V2:
            if conn.proto is None:
                self.loop._bump('v2_conns')
            conn.proto = mod_protocol.PROTO_V2
            if conn.inflight > 1:
                self.loop._bump('frames_pipelined')
            with conn.ids_lock:
                duplicate = rid in conn.inflight_ids
                if not duplicate:
                    conn.inflight_ids.add(rid)
            if duplicate:
                # a client re-using an in-flight id is out of sync;
                # answer retryably and close before responses can be
                # misattributed
                err = ('dn: bad request: duplicate request id %d\n'
                       % rid).encode()
                self.loop.send(
                    conn, mod_protocol.encode_response(
                        1, b'', err, {'retryable': True},
                        proto=proto, rid=rid),
                    close_after=True, completes=True)
                return
        else:
            conn.proto = 1
            # v1 contract: one request per connection — stop reading
            self.loop.pause_reading(conn)
        t = threading.Thread(target=self._handle_request,
                             args=(conn, req, proto, rid, rx),
                             daemon=True)
        with self._workers_lock:
            self._workers.add(t)
        t.start()

    # -- request handling (worker threads) --------------------------------

    def _handle_request(self, conn, req, proto, rid, rx):
        try:
            try:
                mod_faults.fire('serve.read')
                # the stall seam: `delay` holds THIS request (a slow
                # peer/stage), never the loop or other requests
                mod_faults.fire('serve.stall')
            except mod_faults.FaultInjected:
                self.loop.close_conn(conn, completes=True)
                return
            tenant = req.get('tenant') or conn.peer or 'default'
            deadline_ms = req.get('deadline_ms')
            if deadline_ms is None:
                deadline_ms = self.conf['deadline_ms']
            deadline_at = rx + deadline_ms / 1000.0 \
                if deadline_ms and deadline_ms > 0 else None
            if req.get('op') == 'subscribe':
                # needs the CONNECTION (execute() is transport-
                # blind): register, answer, THEN queue the seed
                # frame — the loop's FIFO write queue guarantees the
                # registration ack reaches the peer first
                self._bump_op('subscribe')
                rc, out, err, extra, sub = self.subman.subscribe(
                    conn, req, proto)
                self._send_response(conn, proto, rid, rc, out, err,
                                    extra)
                if sub is not None:
                    self.subman.activate(sub)
                return
            rc, out, err, extra = self.execute(
                req, tenant=tenant, deadline_at=deadline_at)
            self._send_response(conn, proto, rid, rc, out, err,
                                extra)
        except Exception as e:
            # a request must ALWAYS resolve: respond or close, never
            # strand the peer waiting on a frame that will not come
            self.log.error('request handling failed', err=repr(e))
            try:
                msg = ('%s: internal error: %r\n'
                       % (mod_cli.ARG0, e)).encode()
                self._send_response(conn, proto, rid, 1, b'', msg,
                                    {})
            except Exception:
                self.loop.close_conn(conn, completes=True)
        finally:
            if rid is not None:
                with conn.ids_lock:
                    conn.inflight_ids.discard(rid)
            with self._workers_lock:
                self._workers.discard(threading.current_thread())

    def _send_response(self, conn, proto, rid, rc, out, err, extra):
        data = mod_protocol.encode_response(rc, out, err, extra,
                                            proto=proto, rid=rid)
        try:
            mod_faults.fire('serve.write')
        except mod_faults.FaultInjected:
            # injected write fault: drop the connection — the peer
            # sees EOF before any header (pre-commit, retry-safe)
            self.loop.close_conn(conn, completes=True)
            return
        if proto == mod_protocol.PROTO_V2:
            try:
                mod_faults.fire('serve.frame_torn')
            except mod_faults.FaultInjected:
                # a torn frame: half the response then EOF — the
                # client must classify post-commit vs pre-commit by
                # whether ITS header arrived, never hang
                self.loop.send(conn, data[:max(1, len(data) // 2)],
                               close_after=True, completes=True)
                return
        self.loop.send(conn, data, close_after=(proto == 1),
                       completes=True)

    def execute(self, req, tenant=None, deadline_at=None):
        """Execute one request dict; returns (rc, stdout_bytes,
        stderr_bytes, header_stats).  `tenant` keys the fair-admission
        queue; `deadline_at` (monotonic) is the propagated request
        deadline load shedding enforces."""
        op = req.get('op')
        self._bump_op(op)
        if op == 'ping':
            return 0, b'', b'', {}
        if op == 'sub_ack':
            # subscription flow control (serve/subscribe.py): tiny,
            # never queued — a throttled ack path would BE the
            # backpressure bug it exists to prevent
            return self.subman.ack(req)
        if op == 'unsubscribe':
            return self.subman.unsubscribe(req)
        if op == 'health':
            # the replica-probe op (scatter-gather routers, load
            # balancers): tiny, never queued behind admission.  The
            # fault seam lets the chaos soak fail probes
            # deterministically (a FaultInjected here propagates to
            # _handle_conn, which drops the connection — exactly what
            # a dead member looks like to a prober).
            mod_faults.fire('member.health')
            # a member LEAVING the topology (absent from the pending
            # or committed map) reports draining so routers demote it
            # — but stays ok (healthy, still serving) so the breaker
            # never churns on an orderly departure
            leaving = self._topo_leaving_now()
            # a read-only member (disk critical) stays ok — queries
            # keep serving byte-identically, the breaker must not
            # churn — but reports degraded_ro so routers rank it
            # down for write-shaped ops
            degraded_ro = self.governor.is_read_only()
            doc = {
                'ok': not self.draining,
                'draining': self.draining or leaving,
                'degraded_ro': degraded_ro,
                'pid': os.getpid(),
                'uptime_s': round(time.monotonic() - self._t0, 3),
                'inflight': self.admission.depth(),
            }
            if degraded_ro:
                doc['health'] = 'degraded_ro'
            if self.cluster is not None:
                doc['member'] = self.member
                doc['epoch'] = self.cluster.epoch
                if self.pending is not None:
                    doc['pending_epoch'] = self.pending.epoch
            body = json.dumps(doc, sort_keys=True) + '\n'
            return 0, body.encode(), b'', {}
        if op == 'stats':
            body = json.dumps(self.stats_doc(), sort_keys=True,
                              indent=2) + '\n'
            return 0, body.encode(), b'', {}
        if op == 'topology':
            # the dynamic-topology status op (coordinator readiness
            # polls, `dn topo status`): tiny, never queued
            body = json.dumps(self.topology_doc(),
                              sort_keys=True) + '\n'
            return 0, body.encode(), b'', {}
        if op == 'metrics':
            # Prometheus text exposition of the typed registry (the
            # scrape endpoint; `dn stats --remote S --prom` renders
            # it).  Like stats/health: never queued behind admission.
            body = obs_export.prometheus_text(
                counters=mod_vpipe.global_counters())
            return 0, body.encode(), b'', {}
        if op == 'events':
            # the event-journal tail (`dn events [--follow]` and the
            # fleet scatter): entries with seq > `since`, newest
            # `limit`.  Control plane: never queued behind admission.
            j = obs_events.journal()
            since = req.get('since') or 0
            limit = req.get('limit')
            if not isinstance(since, int) or isinstance(since, bool) \
                    or (limit is not None and
                        (not isinstance(limit, int) or
                         isinstance(limit, bool) or limit < 1)):
                self._bump('errors')
                return (1, b'', b'dn: bad "since"/"limit" in events '
                        b'request\n', {})
            doc = {'member': self.member,
                   'enabled': j is not None,
                   'seq': j.seq if j is not None else 0,
                   'events': j.tail(since=since, limit=limit)
                   if j is not None else []}
            body = json.dumps(doc, sort_keys=True) + '\n'
            return 0, body.encode(), b'', {}
        if op == 'fleet_stats':
            # the cluster-aggregated view (serve/fleet.py): scatter
            # stats/events to every topology member over the pooled
            # path, merge one fleet doc.  Bounded by fleet_timeout_s
            # per member — a dead member becomes an error slot,
            # never a hang.  Control plane: no admission slot (the
            # fleet view must render DURING the flood it describes).
            from . import fleet as mod_fleet
            limit = req.get('events')
            if limit is not None and \
                    (not isinstance(limit, int) or
                     isinstance(limit, bool) or limit < 0):
                self._bump('errors')
                return (1, b'', b'dn: bad "events" in fleet_stats '
                        b'request\n', {})
            doc = mod_fleet.fleet_doc(
                self, events_limit=50 if limit is None else limit)
            body = json.dumps(doc, sort_keys=True, indent=2) + '\n'
            return 0, body.encode(), b'', {}
        if op == 'scrub':
            # one on-demand integrity pass (`dn scrub --remote`):
            # verify every configured tree against its catalog under
            # the tree read locks, quarantine + schedule repair for
            # mismatches, run cluster anti-entropy.  Control plane:
            # no admission slot (like shard_manifest — a scrub must
            # not starve behind a query flood).
            from . import scrub as mod_scrub
            try:
                doc = mod_scrub.scrub_member(
                    self, repair=bool(req.get('repair', True)),
                    rate_bytes_s=self.integrity_conf[
                        'scrub_rate_mb_s'] << 20,
                    quarantine=not req.get('check'))
            except DNError as e:
                self._bump('errors')
                return (1, b'',
                        ('dn: %s\n' % e.message).encode(), {})
            body = json.dumps(doc, sort_keys=True, indent=2) + '\n'
            return 0, body.encode(), b'', {}
        if op == 'build' and req.get('idempotency'):
            return self._execute_idempotent(req['idempotency'], req,
                                            tenant, deadline_at)
        if op in ('scan', 'query', 'build', 'query_partial',
                  'shard_manifest', 'shard_fetch') or \
                (op == '_sleep' and
                 os.environ.get('DN_SERVE_TEST_OPS') == '1'):
            return self._execute_data(req, tenant=tenant,
                                      deadline_at=deadline_at)
        self._bump('errors')
        return (1, b'',
                ('dn: unsupported request op: "%s"\n' % op).encode(),
                {})

    def _execute_idempotent(self, key, req, tenant=None,
                            deadline_at=None):
        """Builds are NOT idempotent, so a retried build must not run
        twice: the first request with a given client-generated key is
        the leader and executes; duplicates (the client's retry after
        a transport failure, which may have cut the RESPONSE, not the
        request) wait for and replay the leader's recorded response.
        Retryable rejections (busy/draining) are not recorded — the
        build never ran, so a retry must execute."""
        with self._idem_lock:
            ent = self._idem.get(key)
            leader = ent is None
            if leader:
                ent = {'done': threading.Event(), 'result': None}
                self._idem[key] = ent
        if not leader:
            if not ent['done'].wait(3600.0):
                self._bump('errors')
                return (1, b'',
                        b'dn: idempotent build never completed\n', {})
            self._bump('build_idem_replays')
            rc, out, err, extra = ent['result']
            return rc, out, err, dict(extra, idempotent_replay=True)
        try:
            result = self._execute_data(req, tenant=tenant,
                                        deadline_at=deadline_at)
        except BaseException:
            # the leader died without a recordable response: retire
            # the key so a retry RE-EXECUTES (nothing committed), and
            # wake any followers with a clean retryable rejection —
            # a poisoned key must never strand its duplicates for the
            # full follower wait
            with self._idem_lock:
                self._idem.pop(key, None)
            ent['result'] = (1, b'',
                             b'dn: build execution failed before a '
                             b'response was recorded; retry\n',
                             {'retryable': True})
            ent['done'].set()
            raise
        ent['result'] = result
        with self._idem_lock:
            if result[3].get('retryable'):
                self._idem.pop(key, None)
            else:
                # bound the table: drop oldest COMPLETED records
                done = [k for k, e in self._idem.items()
                        if e['done'].is_set()]
                for k in done[:max(0, len(self._idem) - 128)]:
                    self._idem.pop(k, None)
        ent['done'].set()
        return result

    def _execute_data(self, req, tenant=None, deadline_at=None):
        t0 = time.monotonic()
        deadline_ms = req.get('deadline_ms')
        if deadline_ms is None:
            deadline_ms = self.conf['deadline_ms']
        cap = _Capture()
        flags = {'coalesced': False, 'busy': False, 'deadline': False,
                 'draining': False, 'overloaded': False,
                 'tenant': tenant, 'deadline_at': deadline_at}
        scope_out = {}
        op = req.get('op')

        # observability context: the scoped metrics registry is
        # always on (merged into the global registry at request end);
        # the span tree exists only when the client's trace header or
        # this process's DN_TRACE / DN_SLOW_MS asked for one.  The
        # client-generated trace id joins the server's tree to its
        # client's.
        treq = req.get('trace') or {}
        want_trace = bool(treq.get('want')) or \
            obs_trace.tracing_requested()
        tctx = obs_trace.TraceContext('serve.' + str(op),
                                      trace_id=treq.get('id')) \
            if want_trace else None
        obs_ctx = obs_trace.ObsContext(
            trace=tctx, registry=obs_metrics.Registry())

        def job():
            # may run on the worker thread OR a deadline-armor
            # thread: stdio binding and the counter scope are
            # thread-local, so both bind in here
            with bound_stdio(cap), mod_vpipe.request_scope() as sc:
                sc.obs = obs_ctx
                try:
                    rc = self._run_data(req, flags)
                except mod_admission.OverloadedError as e:
                    # deadline-aware shed: retryable, with the retry
                    # hint derived from observed service time
                    flags['overloaded'] = True
                    flags['retry_after_ms'] = e.retry_after_ms
                    sys.stderr.write('%s: %s\n'
                                     % (mod_cli.ARG0, e.message))
                    rc = 1
                except mod_admission.BusyError as e:
                    flags['busy'] = True
                    flags['retry_after_ms'] = \
                        getattr(e, 'retry_after_ms', None)
                    sys.stderr.write('%s: %s\n'
                                     % (mod_cli.ARG0, e.message))
                    rc = 1
                except mod_admission.DrainingError as e:
                    flags['draining'] = True
                    sys.stderr.write('%s: %s\n'
                                     % (mod_cli.ARG0, e.message))
                    rc = 1
                except mod_admission.DeadlineError as e:
                    flags['deadline'] = True
                    sys.stderr.write('%s: %s\n'
                                     % (mod_cli.ARG0, e.message))
                    rc = 1
                except mod_cli.FatalError as e:
                    sys.stderr.write('%s: %s\n'
                                     % (mod_cli.ARG0, e.message))
                    rc = 1
                except DNError as e:
                    # cluster degraded responses ride the shared
                    # DNError contract but mark the header: a
                    # RouterPartitionError names the dead partitions
                    # and is retryable (another router may have live
                    # replicas); epoch mismatches are retryable too
                    mp = getattr(e, 'missing_partitions', None)
                    if mp is not None:
                        flags['missing'] = list(mp)
                    if getattr(e, 'epoch_mismatch', False):
                        # the rejection names OUR epoch so the peer
                        # can tell a stale map from a dead member
                        flags['epoch_mismatch'] = True
                        flags['current_epoch'] = \
                            getattr(e, 'current_epoch', None)
                    iroot = getattr(e, 'integrity_root', None)
                    if iroot is not None:
                        # a verified read detected corruption (or a
                        # catalogued shard is missing): the header
                        # names it so the router classifies the
                        # rejection, and the damaged member starts
                        # repairing itself in the background — the
                        # self-healing contract
                        flags['corrupt_shard'] = \
                            getattr(e, 'corrupt_shard', None)
                        shards = getattr(e, 'integrity_shards',
                                         None) or []
                        if shards:
                            try:
                                self.repair.schedule(
                                    req.get('ds'), iroot, shards)
                            except Exception:
                                pass
                    if getattr(e, 'disk_full', False):
                        # the read-only rejection (resources.py):
                        # the header names it so clients/routers can
                        # classify — and retry elsewhere or later
                        flags['disk_full'] = True
                    if getattr(e, 'retryable', False):
                        flags['retryable_error'] = True
                        # degraded-because-shedding: the members'
                        # retry hints ride up to the client
                        if getattr(e, 'retry_after_ms', None) \
                                is not None:
                            flags['retry_after_ms'] = \
                                e.retry_after_ms
                    sys.stderr.write('%s: %s\n'
                                     % (mod_cli.ARG0, e.message))
                    rc = 1
                except Exception as e:
                    self.log.error('request failed', err=repr(e),
                                   op=req.get('op'))
                    sys.stderr.write('%s: internal error: %r\n'
                                     % (mod_cli.ARG0, e))
                    rc = 1
                scope_out.update(sc)
            return rc

        def finish_obs(rc, extra):
            """Request-end accounting: merge the scoped registry,
            record the per-op end-to-end latency, and emit/attach the
            span tree.  The subtree travels in the response header
            only when the CLIENT's trace header asked (its tracer
            grafts it) — /stats and response bytes stay byte-identical
            with tracing off."""
            elapsed_ms = (time.monotonic() - t0) * 1000.0
            reg = obs_metrics.global_registry()
            reg.merge(obs_ctx.registry)
            reg.observe('serve_op_latency_ms', elapsed_ms,
                        op=str(op))
            if rc != 0:
                reg.inc('serve_errors_total', op=str(op))
            if tctx is not None:
                # never let telemetry replace a response: a
                # deadline-abandoned job thread may still be mutating
                # this tree while we serialize it
                try:
                    if rc != 0:
                        tctx.root.add_event('error', {'rc': rc})
                    if treq.get('want'):
                        extra['trace'] = tctx.to_doc()
                    obs_trace.emit_trace(tctx)
                except Exception as e:
                    extra.pop('trace', None)
                    self.log.error('trace emit failed', err=repr(e))
            return extra

        if deadline_ms and deadline_ms > 0:
            from ..device_scan import run_with_deadline
            status, rv = run_with_deadline(job, deadline_ms / 1000.0,
                                           'serve-request')
            if status == 'timeout':
                # the job thread is abandoned (there is no way to
                # cancel a wedged op), but its resources must not
                # degrade the server: free its admission slot now
                # (Slot.release is idempotent — the abandoned thread
                # releasing again later is a no-op) and retire its
                # coalescer registration so identical new requests
                # recompute instead of attaching to a dead execution.
                # A TreeLock held by an abandoned BUILD stays held on
                # purpose — the tree is mid-rewrite and must not be
                # served until the write actually finishes.
                slot = flags.get('slot')
                if slot is not None:
                    slot.release()
                self.coalescer.abandon(flags.get('key'),
                                       flags.get('ex'))
                self._bump('deadline_expired')
                self._bump('errors')
                if tctx is not None:
                    tctx.root.add_event('deadline_expired',
                                        {'deadline_ms': deadline_ms})
                msg = ('%s: request deadline (%d ms) exceeded\n'
                       % (mod_cli.ARG0, deadline_ms))
                return 1, b'', msg.encode(), finish_obs(
                    1, {'deadline_expired': True})
            rc = rv if status == 'ok' else 1
        else:
            rc = job()

        out, err = cap.finish()
        if rc != 0:
            self._bump('errors')
        elif op in ('scan', 'query', 'build', 'query_partial'):
            # feed the observed-service-time estimate (retry hints +
            # early shed) and the per-tenant fairness accounting.
            # The sample is EXECUTION time — measured from slot
            # acquisition, not request arrival — queue wait folded in
            # would double-count queueing and over-shed after bursts.
            # (Coalesced followers and routed queries never acquired
            # a slot here: no sample, correctly.)
            if flags.get('exec_t0') is not None:
                self.admission.note_service_ms(
                    (time.monotonic() - flags['exec_t0']) * 1000.0)
            self.admission.note_completed(tenant)
        if flags['overloaded']:
            self._bump('shed_overloaded')
        elif flags['busy']:
            self._bump('busy_rejected')
        if flags['deadline']:
            self._bump('deadline_expired')
        if flags['draining']:
            self._bump('draining_rejected')
        extra = {
            'coalesced': flags['coalesced'],
            'elapsed_ms': round((time.monotonic() - t0) * 1000, 3),
            'counters': scope_out,
        }
        if flags.get('cached'):
            extra['cached'] = True
        if flags['busy'] or flags['overloaded'] or \
                flags['draining'] or flags.get('retryable_error'):
            # the request was never admitted (or failed degraded /
            # pre-execution): nothing committed, a retry is always
            # safe — the client's backoff loop keys off this
            extra['retryable'] = True
        if flags.get('retry_after_ms') is not None:
            # the honest retry hint: roughly when a freed slot could
            # take this work (serve/client.py honors it in place of
            # blind exponential backoff)
            extra['retry_after_ms'] = flags['retry_after_ms']
        if flags.get('missing') is not None:
            # the degraded-result contract: missing partitions are
            # NAMED in the header, in both DN_ROUTER_PARTIAL modes
            # (rc=0 partial merge under 'allow', rc=1 clean retryable
            # error under 'error')
            extra['missing_partitions'] = flags['missing']
            if rc == 0:
                extra['partial'] = True
        if flags.get('epoch_mismatch'):
            # the stale-router resync signal: the rejected peer
            # re-fetches the current map and retries
            extra['epoch_mismatch'] = True
            if flags.get('current_epoch') is not None:
                extra['current_epoch'] = flags['current_epoch']
        if flags.get('disk_full'):
            # the read-only signal: this member is out of disk and
            # rejecting write-shaped ops until space frees (queries
            # still serve) — retry against another member or later
            extra['disk_full'] = True
        if flags.get('corrupt_shard') is not None:
            # the self-healing signal: this member quarantined (or is
            # missing) the named shard and is repairing in the
            # background; the router fails the partial over meanwhile
            extra['corrupt_shard'] = flags['corrupt_shard']
        return rc, out, err, finish_obs(rc, extra)

    def _tree_lock(self, ds, dsname):
        # normalized, so '/data/idx' and '/data/idx/' (or a relative
        # spelling via a different config file) share ONE lock — two
        # locks for one tree would readmit the build/query race
        key = getattr(ds, 'ds_indexpath', None)
        key = os.path.abspath(key) if key else ('ds:' + str(dsname))
        with self._tree_locks_lock:
            return self._tree_locks.setdefault(
                key, mod_admission.TreeLock())

    def _run_data(self, req, flags):
        """The data-command body, mirroring the CLI's post-parse
        execution exactly (the client already did the parsing and
        ships the parsed documents).  Raises FatalError/DNError for
        the caller to frame as 'dn: <message>'."""
        op = req['op']
        if op == '_sleep':
            flags['slot'] = self.admission.acquire(
                tenant=flags.get('tenant'),
                deadline_at=flags.get('deadline_at'))
            flags['exec_t0'] = time.monotonic()
            try:
                time.sleep(float(req.get('ms', 0)) / 1000.0)
            finally:
                flags['slot'].release()
            return 0

        from .. import datasource_for_name, metrics_for_index
        cfg_path = req.get('config') or None
        if self.cluster is not None and \
                op in ('query_partial', 'shard_manifest',
                       'shard_fetch'):
            # per-member index trees: when the topology declares this
            # member's own config, partition-scoped work resolves
            # datasources through IT — the request's config names the
            # router's view of the world, not ours.  Without the
            # declaration, a query partial keeps the request's config
            # (byte-identical to the PR 8 shared-tree contract), but
            # the handoff ops always resolve the DONOR's own view
            # (process default) — a joiner's request config points at
            # its empty tree, and enumerating that as the donor would
            # silently hand off nothing.
            override = self.cluster.member_config(self.member)
            if override is None and self.pending is not None:
                override = self.pending.member_config(self.member)
            if override:
                cfg_path = override
            elif op in ('shard_manifest', 'shard_fetch'):
                cfg_path = None
        backend = mod_config.ConfigBackendLocal(cfg_path)
        err, config = backend.load()
        if err is not None and not getattr(err, 'is_enoent', False):
            mod_cli.fatal(err)
        dsname = req.get('ds')
        ds = datasource_for_name(config, dsname)
        if isinstance(ds, DNError):
            mod_cli.fatal(ds)
        opts = _opts_shim(req)

        if op == 'build':
            return self._run_build(req, ds, config, dsname, opts,
                                   metrics_for_index, flags)
        if op == 'query_partial':
            return self._run_partial(req, ds, dsname, opts, backend,
                                     flags)
        if op == 'shard_manifest':
            return self._run_shard_manifest(req, ds, dsname, flags)
        if op == 'shard_fetch':
            return self._run_shard_fetch(req, ds, dsname, flags)
        if op == 'query' and self.router is not None and \
                not opts.dry_run:
            # cluster mode: this member routes — scatter the query to
            # the partition owners and merge the partial aggregates
            # (dry runs stay local: the plan shows this member's own
            # tree view)
            return self._run_routed_query(req, ds, dsname, opts,
                                          backend, flags)

        query = mod_cli.dn_query_config(opts)
        key = mod_admission.compute_key(
            req, _config_ident(backend.cbl_path))

        # result cache (serve/qcache.py): a valid hit skips the
        # lease, the admission slot, and the tree read entirely.
        # The epoch and validators are captured BEFORE the compute:
        # a write racing the execution stamps the entry already-stale
        # (a wasted put), never a stale hit.
        use_cache = op == 'query' and not opts.dry_run and \
            key is not None and self.qcache.enabled()
        cache_epoch = mod_iqmt.cache_epoch() if use_cache else 0
        if use_cache:
            cached = self.qcache.get(key, cache_epoch)
            if cached is not None:
                # no exec_t0: like a coalesced follower, a hit never
                # held a slot, so it must not feed the service-time
                # estimate the shed/retry hints key off
                flags['cached'] = True
                obs_metrics.inc('serve_result_cache_hits_total')
                mod_cli.dn_output(query, opts,
                                  cached.clone_for_output(), dsname)
                return 0
            obs_metrics.inc('serve_result_cache_misses_total')
        cache_validators = mod_qcache.tree_validators(
            getattr(ds, 'ds_indexpath', None)) if use_cache else None

        def compute():
            lease = self._admit_resources(op, ds)
            try:
                slot = flags['slot'] = self.admission.acquire(
                    tenant=flags.get('tenant'),
                    deadline_at=flags.get('deadline_at'))
            except BaseException:
                # a busy/draining/shed rejection must hand the
                # reserved footprint back — a leaked lease would
                # ratchet the budget shut for the process lifetime
                lease.release()
                raise
            flags['exec_t0'] = time.monotonic()
            try:
                with obs_trace.span('serve.execute', op=op):
                    if op == 'scan':
                        # raw-data scans never read the index tree,
                        # so they run unlocked alongside builds
                        return ds.scan(query, dry_run=opts.dry_run,
                                       warn_func=None)
                    with self._tree_lock(ds, dsname).read():
                        return ds.query(query,
                                        req.get('interval') or 'day',
                                        dry_run=opts.dry_run)
            finally:
                slot.release()
                lease.release()

        try:
            result, shared = self.coalescer.run(key, compute,
                                                lease=flags)
        except (mod_admission.BusyError,
                mod_admission.DrainingError,
                mod_admission.DeadlineError):
            raise
        except DNError as e:
            if getattr(e, 'retryable', False):
                # integrity (and other retryable) rejections keep
                # their attributes: the job() handler frames the
                # message AND marks the header (retryable,
                # corrupt_shard) — fatal() would strip both
                raise
            mod_cli.fatal(e)
        flags['coalesced'] = shared
        if use_cache and not shared:
            # only the compute LEADER populates the cache: its epoch
            # and validators predate its own tree read, so a write
            # racing the execution stamps the entry already-stale.  A
            # coalesced follower captured them AFTER the leader began
            # computing — a write landing in between would let the
            # follower stamp the leader's pre-write result with
            # post-write validators, freezing a stale entry until the
            # next in-process epoch bump (forever, for a tree only
            # cross-process writers touch)
            self.qcache.put(key, cache_epoch, cache_validators,
                            result)
        # coalesced requests demux through private clones: the output
        # layer mutates the pipeline it formats
        mod_cli.dn_output(query, opts, result.clone_for_output(),
                          dsname)
        return 0

    def _run_routed_query(self, req, ds, dsname, opts, backend,
                          flags):
        """Cluster-mode index query: scatter-gather through the
        router, then the unmodified output layer over the merged
        points — byte-identical to a single-process run when every
        partition answered.  NO admission slot is held across the
        scatter wait (the router blocks on REMOTE members; two
        members routing at each other under full admission queues
        would deadlock) — the local partial acquires its own slot
        inside _local_partial."""
        query = mod_cli.dn_query_config(opts)
        key = mod_admission.compute_key(
            req, _config_ident(backend.cbl_path))
        interval = req.get('interval') or 'day'

        def compute():
            with obs_trace.span('serve.execute', op='query.routed'):
                # deadline propagation: the remaining budget rides
                # into every member partial (router.scatter derives
                # per-partial deadline_ms from it)
                return self.router.scatter(
                    ds, dsname, query, interval, req,
                    deadline_at=flags.get('deadline_at'))

        # degraded errors (RouterPartitionError) propagate as DNError
        # with their missing_partitions/retryable attrs intact — the
        # job() handler frames the message and marks the header
        from . import router as mod_router
        try:
            (result, missing), shared = self.coalescer.run(
                key, compute, lease=flags)
        except mod_router.TopologyEpochError:
            # a member rejected the scatter as stale: re-fetch the
            # current map (synchronously, when a watcher runs) and
            # retry ONCE under the refreshed topology — the straggler
            # self-heals instead of erroring to the client
            with self._topo_lock:
                self._topo_counters['resyncs'] += 1
            obs_metrics.inc('topo_resyncs_total')
            if obs_events.enabled():
                obs_events.emit('topo.resync',
                                epoch=self.cluster.epoch
                                if self.cluster is not None else None)
            if self.topo_watcher is not None:
                self.topo_watcher.poll_now()
            (result, missing), shared = self.coalescer.run(
                key, compute, lease=flags)
        flags['coalesced'] = shared
        if missing:
            flags['missing'] = list(missing)
            sys.stderr.write(
                'dn: warning: partial result: partition(s) %s '
                'unavailable\n' % ','.join(str(p) for p in missing))
        mod_cli.dn_output(query, opts, result.clone_for_output(),
                          dsname)
        return 0

    def _run_partial(self, req, ds, dsname, opts, backend, flags):
        """The member side of the scatter: execute the query over the
        requested partitions of THIS member's shard walk and return
        per-shard key items as JSON (the router merges them in global
        find order)."""
        if self.cluster is None:
            mod_cli.fatal(DNError(
                'not a cluster member (start with '
                '--cluster/--member)'))
        pids = req.get('partitions')
        if not isinstance(pids, list) or not pids or \
                not all(isinstance(p, int) and
                        not isinstance(p, bool) for p in pids):
            mod_cli.fatal(DNError(
                'bad "partitions" in query_partial request'))
        # a router running a different topology file must never merge
        # this member's partitions: the epoch gate accepts the
        # committed epoch (and the pending epoch during a handoff
        # window, once this member's shards for the partitions have
        # landed) and rejects anything else with a clean retryable
        # error carrying our current epoch — the stale side resyncs
        serving = self._serving_for_epoch(req.get('epoch'),
                                          pids=pids)
        known = set(serving.partition_ids())
        if not all(p in known for p in pids):
            mod_cli.fatal(DNError(
                'bad "partitions" in query_partial request'))
        query = mod_cli.dn_query_config(opts)
        key = mod_admission.compute_key(
            req, _config_ident(backend.cbl_path))
        interval = req.get('interval') or 'day'

        def compute():
            from . import router as mod_router
            lease = self._admit_resources('query_partial', ds)
            try:
                slot = flags['slot'] = self.admission.acquire(
                    tenant=flags.get('tenant'),
                    deadline_at=flags.get('deadline_at'))
            except BaseException:
                lease.release()
                raise
            flags['exec_t0'] = time.monotonic()
            try:
                with self._tree_lock(ds, dsname).read(), \
                        obs_trace.span('serve.execute',
                                       op='query_partial'):
                    return mod_router.partial_query(
                        ds, query, interval, serving, pids)
            finally:
                slot.release()
                lease.release()

        try:
            shards, shared = self.coalescer.run(key, compute,
                                                lease=flags)
        except (mod_admission.BusyError,
                mod_admission.DrainingError,
                mod_admission.DeadlineError):
            raise
        except DNError as e:
            if getattr(e, 'retryable', False):
                # a corrupt-detect (ShardIntegrityError) must reach
                # the job() handler with its attributes intact: the
                # router reads the corrupt_shard header to classify
                # the failover, and the repair schedule hangs off it
                raise
            mod_cli.fatal(e)
        flags['coalesced'] = shared
        body = json.dumps({'epoch': serving.epoch,
                           'member': self.member, 'shards': shards},
                          sort_keys=True, separators=(',', ':'))
        sys.stdout.write(body + '\n')
        return 0

    def _run_shard_manifest(self, req, ds, dsname, flags):
        """The donor side of partition handoff: enumerate this
        member's shards for the requested COMMITTED partitions as
        (relpath, size, crc32) triples (serve/rebalance.py).  Control
        plane: no admission slot (a handoff must not starve behind a
        query flood), but the tree read lock holds so a concurrent
        build cannot reshape the tree mid-enumeration."""
        from . import rebalance as mod_rebalance
        if self.cluster is None:
            mod_cli.fatal(DNError(
                'not a cluster member (start with '
                '--cluster/--member)'))
        serving = self._serving_for_epoch(req.get('epoch'))
        pids = req.get('partitions')
        known = set(serving.partition_ids())
        if not isinstance(pids, list) or not pids or \
                not all(isinstance(p, int) and
                        not isinstance(p, bool) and p in known
                        for p in pids):
            mod_cli.fatal(DNError(
                'bad "partitions" in shard_manifest request'))
        with self._tree_lock(ds, dsname).read(), \
                obs_trace.span('serve.execute', op='shard_manifest'):
            try:
                shards = mod_rebalance.shard_manifest(ds, serving,
                                                      pids)
            except DNError as e:
                mod_cli.fatal(e)
        body = json.dumps({'epoch': serving.epoch,
                           'member': self.member, 'shards': shards},
                          sort_keys=True, separators=(',', ':'))
        sys.stdout.write(body + '\n')
        return 0

    def _run_shard_fetch(self, req, ds, dsname, flags):
        """The donor side of one shard's stream: the raw shard bytes
        as the response payload (the joiner verifies size + crc
        against the manifest before landing them)."""
        from . import rebalance as mod_rebalance
        if self.cluster is None:
            mod_cli.fatal(DNError(
                'not a cluster member (start with '
                '--cluster/--member)'))
        self._serving_for_epoch(req.get('epoch'))
        offset = req.get('offset') or 0
        length = req.get('length')
        if not isinstance(offset, int) or isinstance(offset, bool) \
                or offset < 0 or \
                (length is not None and
                 (not isinstance(length, int) or
                  isinstance(length, bool) or length < 1)):
            mod_cli.fatal(DNError(
                'bad "offset"/"length" in shard_fetch request'))
        with self._tree_lock(ds, dsname).read(), \
                obs_trace.span('serve.execute', op='shard_fetch'):
            try:
                data = mod_rebalance.read_shard(ds, req.get('rel'),
                                                offset=offset,
                                                length=length)
            except DNError as e:
                mod_cli.fatal(e)
        # raw bytes, not text: write through the capture's underlying
        # binary buffer (this handler writes nothing else)
        sys.stdout.buffer.write(data)
        return 0

    def _local_partial(self, partition_ids, partial_req):
        """The router's in-process partial executor for partitions
        this member itself owns: same admission-slot + tree-read-lock
        discipline as a socket-delivered query_partial, without
        dialing our own socket (a self-dial under a full admission
        queue would deadlock the scatter)."""
        from .. import datasource_for_name
        from . import router as mod_router
        # same epoch + handoff gate as the socket path: the scatter
        # snapshot may be one epoch behind (or ahead of) a cutover
        # that landed between snapshot and execution — serving the
        # wrong map locally would mix epochs in the merge
        serving = self._serving_for_epoch(partial_req.get('epoch'),
                                          pids=partition_ids)
        cfg_path = partial_req.get('config') or None
        override = serving.member_config(self.member)
        if override:
            cfg_path = override
        backend = mod_config.ConfigBackendLocal(cfg_path)
        err, config = backend.load()
        if err is not None and not getattr(err, 'is_enoent', False):
            raise err
        dsname = partial_req.get('ds')
        ds = datasource_for_name(config, dsname)
        if isinstance(ds, DNError):
            raise ds
        opts = _opts_shim(partial_req)
        query = mod_cli.dn_query_config(opts)
        interval = partial_req.get('interval') or 'day'
        deadline_ms = partial_req.get('deadline_ms')
        deadline_at = time.monotonic() + deadline_ms / 1000.0 \
            if deadline_ms and deadline_ms > 0 else None
        lease = self._admit_resources('query_partial', ds)
        try:
            slot = self.admission.acquire(
                tenant=partial_req.get('tenant'),
                deadline_at=deadline_at)
        except BaseException:
            lease.release()
            raise
        try:
            with self._tree_lock(ds, dsname).read():
                return mod_router.partial_query(
                    ds, query, interval, serving, partition_ids)
        except DNError as e:
            # a corrupt/missing detect on OUR OWN partial propagates
            # to the router (which fails over to a replica), not
            # through the request error handler — so the self-repair
            # schedule hooks in right here
            iroot = getattr(e, 'integrity_root', None)
            shards = getattr(e, 'integrity_shards', None) or []
            if iroot is not None and shards:
                try:
                    self.repair.schedule(dsname, iroot, shards)
                except Exception:
                    pass
            raise
        finally:
            slot.release()
            lease.release()

    def _run_build(self, req, ds, config, dsname, opts,
                   metrics_for_index, flags):
        before, after = req.get('before'), req.get('after')
        if before is not None and after is not None and \
                before < after:
            mod_cli.fatal(DNError(
                '"before" time cannot be before "after" time'))
        interval = req.get('interval') or 'day'
        if interval not in ('hour', 'day', 'all'):
            mod_cli.fatal(DNError('interval not supported: "%s"'
                                  % interval))
        metrics = metrics_for_index(config, dsname,
                                    index_config=req.get(
                                        'index_config'))
        if len(metrics) == 0:
            mod_cli.fatal(DNError('no metrics defined for dataset '
                                  '"%s"' % dsname))
        # the read-only gate: a disk-critical member rejects builds
        # up front with the clean retryable disk_full DNError (the
        # job() handler marks the response header) — queries keep
        # serving byte-identically throughout
        if not opts.dry_run:
            self.governor.check_writable('build')
        lease = self._admit_resources('build', ds)
        try:
            slot = flags['slot'] = self.admission.acquire(
                tenant=flags.get('tenant'),
                deadline_at=flags.get('deadline_at'))
        except BaseException:
            lease.release()
            raise
        flags['exec_t0'] = time.monotonic()
        try:
            with self._tree_lock(ds, dsname).write(), \
                    obs_trace.span('serve.execute', op='build'):
                result = ds.build(metrics, interval,
                                  time_after=after,
                                  time_before=before,
                                  dry_run=opts.dry_run,
                                  warn_func=None)
        except DNError as e:
            if getattr(e, 'retryable', False):
                # a mid-build pressure failure keeps its disk_full /
                # retryable attributes for the response header;
                # fatal() would strip both
                raise
            mod_cli.fatal(e)
        finally:
            slot.release()
            lease.release()
        if opts.dry_run:
            mod_cli.dn_output(None, opts, result, dsname)
            return 0
        sys.stderr.write('indexes for "%s" built\n' % dsname)
        if getattr(opts, 'counters', None):
            result.pipeline.dump_counters(sys.stderr)
        return 0


# -- daemon entry (cmd_serve) -----------------------------------------------

def sweep_configured_trees(warn=None):
    """Crash-recovery sweep over every configured file datasource's
    index tree — `dn serve` runs this at startup so a builder that
    died while no server was resident is recovered before the first
    request.  Returns {indexpath: sweep result} for trees that needed
    work."""
    from .. import index_journal as mod_journal
    backend = mod_config.ConfigBackendLocal()
    err, config = backend.load()
    if err is not None:
        return {}
    acted = {}
    for dsname, ds in config.datasource_list():
        idx = (ds.get('ds_backend_config') or {}).get('indexPath')
        if not idx:
            continue
        res = mod_journal.sweep_index_tree(idx)
        if res['rollbacks'] or res['rollforwards'] or \
                res['quarantined']:
            acted[idx] = res
            if warn is not None:
                warn('recovered index tree "%s" (%d roll-forward(s), '
                     '%d rollback(s), %d tmp(s) quarantined)'
                     % (idx, res['rollforwards'], res['rollbacks'],
                        res['quarantined']))
    return acted


def serve_main(socket_path=None, port=None, pidfile=None,
               cluster=None, member=None, router_conf=None,
               pending=None, topo_conf=None):
    """Run the daemon until SIGTERM/SIGINT, then drain.  Returns the
    process exit code.  `cluster` (an already-loaded, validated
    topology.Topology) and `member` (this server's member name) start
    the scatter-gather cluster mode (serve/topology.py,
    serve/router.py); `pending` is the in-flight transition epoch
    when the topology file was mid-handoff at startup (a fresh joiner
    starts pulling immediately).  The CLI loads and validates the
    topology file and DN_ROUTER_*/DN_TOPO_* knobs exactly once and
    hands the results here — re-reading them would open a window
    where the state just validated/printed differs from the state
    actually served."""
    conf = mod_config.serve_config()
    if isinstance(conf, DNError):
        raise conf
    topo = cluster
    pidfile = mod_lifecycle.pidfile_for(socket_path, pidfile)

    def warn(msg):
        sys.stderr.write('dn serve: %s\n' % msg)

    sweep_configured_trees(warn=warn)
    mod_lifecycle.claim(socket_path=socket_path, port=port,
                        pidfile=pidfile, warn=warn)
    server = DnServer(socket_path=socket_path, port=port,
                      pidfile=pidfile, conf=conf, cluster=topo,
                      member=member, router_conf=router_conf,
                      pending=pending, topo_conf=topo_conf)
    try:
        server.bind()
    except OSError as e:
        mod_lifecycle.release(socket_path=None, pidfile=pidfile)
        raise DNError('cannot bind serve endpoint',
                      cause=DNError(str(e)))

    def on_signal(signo, frame):
        server.request_stop()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    where = socket_path if socket_path is not None \
        else '%s:%d' % (server.host, server.bound_port)
    aka = ' as member "%s" (epoch %d)' % (member, topo.epoch) \
        if topo is not None else ''
    sys.stderr.write('dn serve: listening on %s (pid %d)%s\n'
                     % (where, os.getpid(), aka))
    server.serve_forever()
    sys.stderr.write('dn serve: drained; exiting\n')
    return 0
