"""Serve-side integrity: self-healing replica repair, the background
scrub thread, and cluster anti-entropy.

The read path (integrity.py) DETECTS damage — a verified read that
fails quarantines the shard and rejects retryably, and the router
fails the partial over to a replica that has the bytes.  This module
closes the loop so detection becomes self-healing:

* RepairManager: when a member's partial hits a corrupt (or
  catalogued-but-missing) shard, the serve layer schedules it here.
  A background worker re-fetches the good copy from a committed
  co-replica over the pooled `shard_fetch` path — crc-verified
  against THIS member's catalog entry, landed journal-style tmp +
  rename (exactly the PR 11 joiner discipline, shared code:
  rebalance.land_shard) — and the member serves the partition again
  with byte-identical data.  Repair counters ride /stats
  `integrity`.

* ScrubThread (DN_SCRUB_INTERVAL_S > 0): periodically walks every
  configured tree comparing bytes against the catalog at a bounded
  read rate (DN_SCRUB_RATE_MB_S), quarantining mismatches and
  scheduling their repair.

* anti_entropy: in cluster mode the scrub additionally diffs this
  member's shard set against co-replicas' `shard_manifest` answers
  for every partition it owns, pulling what is missing outright
  (shards this member lost entirely, including their catalog
  entries).  A shard that matches OUR catalog but differs from a
  donor's manifest is counted `diverged` and left alone — that is a
  concurrent publish racing the scrub, not rot; the next pass sees
  the settled trees.

The `scrub` serve op (`dn scrub --remote SOCK`) runs one pass on
demand under the server's tree read locks (an in-process build can
never swap shards mid-scrub), returning the summary as JSON.
"""

import collections
import os
import threading

from ..errors import DNError
from .. import integrity as mod_integrity
from ..obs import events as obs_events
from ..obs import metrics as obs_metrics
from . import rebalance as mod_rebalance

# the interval-tree layouts index_find_params produces: a manifest/
# catalog relpath maps back to its assignment rule by its subdir
TIMEFORMATS = {'by_day': '%Y-%m-%d.sqlite',
               'by_hour': '%Y-%m-%d-%H.sqlite'}


def rel_timeformat(rel):
    head = rel.split('/')[0] if '/' in rel else rel
    return TIMEFORMATS.get(head)


class RepairManager(object):
    """The damaged member's background self-repair queue.

    schedule() is called from the request path (a corrupt detect must
    not block the rejection riding back to the router) and from the
    scrub; the worker drains one shard at a time.  Work is deduped by
    (indexroot, rel) — a flood of partials hitting the same corrupt
    shard schedules ONE repair."""

    def __init__(self, server):
        self.server = server
        self._lock = threading.Lock()
        self._pending = set()          # (indexroot, rel) queued/active
        self._queue = collections.deque()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = None
        self.counters = {'scheduled': 0, 'completed': 0,
                         'failed': 0, 'no_donor': 0,
                         'no_catalog': 0, 'bytes_repaired': 0}

    def _bump(self, name, n=1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def stats(self):
        with self._lock:
            return dict(self.counters, queued=len(self._queue))

    def schedule(self, dsname, indexroot, rels):
        """Queue shards of `dsname`'s tree for repair (cluster mode
        only — without replicas there is nothing to pull from)."""
        if self.server.cluster is None or self.server.member is None:
            return
        started = False
        with self._lock:
            for rel in rels:
                key = (os.path.abspath(indexroot), rel)
                if key in self._pending:
                    continue
                self._pending.add(key)
                self._queue.append((dsname, key[0], rel))
                self.counters['scheduled'] += 1
                started = True
                if obs_events.enabled():
                    obs_events.emit('repair.scheduled', shard=rel,
                                    ds=dsname)
        if started:
            self._wake.set()
            self._ensure_thread()

    def _ensure_thread(self):
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._run, name='dn-shard-repair', daemon=True)
            self._thread.start()

    def stop(self):
        self._stop.set()
        self._wake.set()

    def _run(self):
        paused = False
        while not self._stop.is_set():
            # resource governance: repair pulls are BACKGROUND disk
            # consumers — under low/critical pressure queued work
            # stays queued (resuming automatically when the governor
            # recovers) instead of filling the last free bytes the
            # serving path needs.  Only pause when there IS work: an
            # idle worker under a long pressure window must not emit
            # a pause event per second for the whole incident.
            gov = getattr(self.server, 'governor', None)
            with self._lock:
                has_work = bool(self._queue)
            if has_work and gov is not None and gov.mode() != 'ok':
                if not paused:
                    paused = True
                    obs_events.emit('resource.paused',
                                    component='repair')
                    obs_metrics.inc('resource_paused_total',
                                    component='repair')
                # pace on the STOP event (the wake event may already
                # be set by a schedule(); waiting on it here would
                # spin) — stop still interrupts the pause instantly
                self._stop.wait(1.0)
                continue
            paused = False
            with self._lock:
                item = self._queue.popleft() if self._queue else None
            if item is None:
                self._wake.clear()
                if self._wake.wait(5.0):
                    continue
                # idle timeout: retire ONLY if nothing raced in —
                # a schedule() between our pop and this check saw a
                # live thread and did not respawn, so returning with
                # a non-empty queue (its keys already in _pending)
                # would strand that shard unrepaired forever
                with self._lock:
                    if self._queue:
                        continue
                    self._thread = None   # next schedule respawns
                return
            dsname, indexroot, rel = item
            try:
                ok = self._repair_one(dsname, indexroot, rel)
            except Exception as e:
                ok = False
                if self.server.log is not None:
                    self.server.log.error('shard repair failed',
                                          rel=rel, err=repr(e))
            finally:
                with self._lock:
                    self._pending.discard((indexroot, rel))
            if ok:
                self._bump('completed')
            else:
                self._bump('failed')
            obs_events.emit(
                'repair.completed' if ok else 'repair.failed',
                shard=rel, ds=dsname)

    def _repair_one(self, dsname, indexroot, rel):
        """Pull one shard's good copy from a committed co-replica,
        verified against OUR catalog entry (the byte-exact repair
        target the publish recorded)."""
        server = self.server
        topo = server.cluster           # committed snapshot
        if topo is None:
            return False
        # the resource-exhaustion seam (and the read-only gate: a
        # repair LANDS bytes — on a disk-critical member that write
        # is refused like any other until space frees)
        from .. import faults as mod_faults
        mod_faults.fire('repair.land')
        gov = getattr(server, 'governor', None)
        if gov is not None:
            gov.check_writable('shard repair')
        expected = mod_integrity.load_catalog(indexroot).get(rel)
        if expected is None:
            self._bump('no_catalog')
            return False
        size, crc = expected
        dest = os.path.join(indexroot, rel)
        try:
            if mod_integrity.file_crc(dest) == expected:
                return True             # healed by another path
        except OSError:
            pass
        pid = topo.partition_of(dest, rel_timeformat(rel))
        donors = [m for m in topo.replicas(pid)
                  if m != server.member]
        if not donors:
            self._bump('no_donor')
            return False
        timeout_s = server.topo_conf['handoff_timeout_s']
        for donor in donors:
            try:
                mod_rebalance.land_shard(
                    topo.endpoint(donor), dsname, None, topo.epoch,
                    rel, size, crc, dest, timeout_s,
                    indexroot=indexroot)
            except (OSError, ValueError, DNError):
                continue
            from .. import index_query_mt as mod_iqmt
            mod_iqmt.shard_cache_invalidate(dest)
            self._bump('bytes_repaired', size)
            obs_metrics.inc('integrity_repairs_total')
            obs_metrics.inc('integrity_repair_bytes_total', size)
            if server.log is not None:
                server.log.info('shard repaired', rel=rel,
                                donor=donor, bytes=size)
            return True
        return False


# -- the scrub pass ----------------------------------------------------------

def member_datasources(server):
    """[(dsname, ds)] of file datasources with index trees under the
    server's view of the world (its topology member config when
    declared, the process default otherwise)."""
    from .. import datasource_for_name
    from .. import config as mod_config
    cfg_path = None
    if server.cluster is not None and server.member is not None:
        cfg_path = server.cluster.member_config(server.member)
    backend = mod_config.ConfigBackendLocal(cfg_path or None)
    err, config = backend.load()
    if err is not None and not getattr(err, 'is_enoent', False):
        raise err
    out = []
    for dsname, dsdoc in config.datasource_list():
        idx = (dsdoc.get('ds_backend_config') or {}).get('indexPath')
        if not idx:
            continue
        ds = datasource_for_name(config, dsname)
        if isinstance(ds, DNError):
            continue
        out.append((dsname, ds))
    return out


def anti_entropy(server, dsname, ds, repair=True):
    """Diff this member's shard set against co-replicas' manifests
    for every partition it owns; pull what is missing.  Returns
    {'checked', 'pulled', 'diverged', 'unreachable'}."""
    from . import client as mod_client
    res = {'checked': 0, 'pulled': 0, 'diverged': 0,
           'unreachable': 0}
    topo = server.cluster
    if topo is None or server.member is None:
        return res
    import json as mod_json
    catalog = mod_integrity.load_catalog(ds.ds_indexpath)
    timeout_s = server.topo_conf['handoff_timeout_s']
    for pid in topo.partitions_of(server.member):
        donors = [m for m in topo.replicas(pid)
                  if m != server.member]
        got = None
        used_donor = None
        for donor in donors:
            try:
                rc, header, out, err = mod_client.request_bytes(
                    topo.endpoint(donor),
                    {'op': 'shard_manifest', 'ds': dsname,
                     'epoch': topo.epoch, 'partitions': [pid]},
                    timeout_s=timeout_s, retry=True, pooled=True)
                if rc == 0:
                    got = mod_json.loads(
                        out.decode('utf-8'))['shards']
                    used_donor = donor
                    break
            except (OSError, ValueError, KeyError, DNError):
                pass
        if got is None:
            if donors:
                res['unreachable'] += 1
            continue
        for rel, size, crc in got:
            res['checked'] += 1
            dest = mod_rebalance.safe_rel(ds.ds_indexpath, rel)
            try:
                have = mod_integrity.file_crc(dest)
            except OSError:
                have = None
            if have == (size, crc):
                continue
            if have is not None and catalog.get(rel) == have:
                # our bytes match OUR catalog: the trees diverged
                # (a publish racing the scrub) — not rot, not ours
                # to clobber
                res['diverged'] += 1
                continue
            if not repair:
                res['diverged'] += 1
                continue
            try:
                mod_rebalance.land_shard(
                    topo.endpoint(used_donor), dsname, None,
                    topo.epoch, rel, size, crc, dest, timeout_s,
                    indexroot=ds.ds_indexpath)
            except (OSError, ValueError, DNError):
                res['unreachable'] += 1
                continue
            from .. import index_query_mt as mod_iqmt
            mod_iqmt.shard_cache_invalidate(dest)
            res['pulled'] += 1
            obs_metrics.inc('integrity_repairs_total')
            obs_metrics.inc('integrity_repair_bytes_total', size)
    return res


def scrub_member(server, repair=True, rate_bytes_s=0,
                 quarantine=True):
    """One scrub pass over the server's trees (the `scrub` op and the
    background thread): verify bytes against catalogs (tree
    read-locked — an in-process build cannot swap shards mid-walk),
    quarantine + schedule repair for mismatches, then run cluster
    anti-entropy.  quarantine=False (`dn scrub --check --remote`)
    reports without acting.  Returns the summary doc."""
    doc = {'trees': {}, 'anti_entropy': {}}
    for dsname, ds in member_datasources(server):
        lock = server._tree_lock(ds, dsname)

        def on_corrupt(rel, path, dsname=dsname, ds=ds):
            if repair:
                server.repair.schedule(dsname, ds.ds_indexpath,
                                       [rel])

        with lock.read():
            res = mod_integrity.scrub_tree(
                ds.ds_indexpath, quarantine=quarantine,
                rate_bytes_s=rate_bytes_s, on_corrupt=on_corrupt)
        if repair and res['missing_shards']:
            server.repair.schedule(dsname, ds.ds_indexpath,
                                   res['missing_shards'])
        doc['trees'][dsname] = res
        if server.cluster is not None:
            doc['anti_entropy'][dsname] = anti_entropy(
                server, dsname, ds, repair=repair and quarantine)
    return doc


class ScrubThread(object):
    """The background scrubber `dn serve` runs under
    DN_SCRUB_INTERVAL_S > 0: one scrub_member pass per interval,
    rate-limited reads, last-pass summary in /stats `integrity`."""

    def __init__(self, server, interval_s, rate_bytes_s, log=None):
        self.server = server
        self.interval_s = interval_s
        self.rate_bytes_s = rate_bytes_s
        self.log = log
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.runs = 0
        self.last = None
        self.last_error = None
        self.quarantine_evicted_files = 0
        self.quarantine_evicted_bytes = 0
        self._thread = threading.Thread(
            target=self._run, name='dn-scrub', daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def stats(self):
        with self._lock:
            return {'interval_s': self.interval_s,
                    'rate_bytes_s': self.rate_bytes_s,
                    'runs': self.runs, 'last': self.last,
                    'quarantine_evicted_files':
                    self.quarantine_evicted_files,
                    'quarantine_evicted_bytes':
                    self.quarantine_evicted_bytes,
                    'last_error': self.last_error}

    def _enforce_quarantine_budget(self):
        """The DN_QUARANTINE_MAX_MB auto-clean hook: after each scrub
        pass, evict the OLDEST quarantined forensics past the byte
        budget so quarantined corruption can never fill the disk it
        was saved from.  0 (the default) keeps the manual-only
        `dn quarantine clean` contract."""
        max_mb = self.server.integrity_conf.get('quarantine_max_mb',
                                                0)
        if not max_mb:
            return
        budget = max_mb << 20
        for dsname, ds in member_datasources(self.server):
            n, b = mod_integrity.quarantine_clean(
                ds.ds_indexpath, max_bytes=budget)
            if not n:
                continue
            with self._lock:
                self.quarantine_evicted_files += n
                self.quarantine_evicted_bytes += b
            obs_metrics.inc('quarantine_evicted_total', n)
            obs_metrics.inc('quarantine_evicted_bytes_total', b)
            obs_events.emit('quarantine.evicted', ds=dsname,
                            files=n, bytes=b)
            if self.log is not None:
                self.log.info('quarantine budget enforced',
                              ds=dsname, files=n, bytes=b)

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self._enforce_quarantine_budget()
                doc = scrub_member(self.server, repair=True,
                                   rate_bytes_s=self.rate_bytes_s)
                with self._lock:
                    self.runs += 1
                    self.last = doc
                    self.last_error = None
                obs_metrics.inc('integrity_scrub_runs_total')
                if obs_events.enabled():
                    trees = doc.get('trees') or {}
                    obs_events.emit(
                        'scrub.summary',
                        trees=len(trees),
                        corrupt=sum(
                            len(t.get('corrupt_shards') or [])
                            for t in trees.values()),
                        missing=sum(
                            len(t.get('missing_shards') or [])
                            for t in trees.values()))
            except Exception as e:
                with self._lock:
                    self.last_error = repr(e)
                if self.log is not None:
                    self.log.error('scrub pass failed', err=repr(e))


class MaintenanceThread(object):
    """The rollup/compaction timer `dn serve` runs under
    DN_ROLLUP_INTERVAL_S / DN_COMPACT_INTERVAL_S > 0 — the scrub
    thread's sibling on the same member-datasource walk and the same
    governor discipline (background disk consumers pause under
    pressure and resume on their own).

    * Rollup refresh (rollup.build_rollups) runs WITHOUT the tree
      write lock: a build only ADDS shards and atomically republishes
      the manifest — concurrent queries either still plan fine shards
      or pick up the finished rollup, never a torn view.

    * Compaction holds the tree write lock per GROUP (one base shard
      + its generations — the same short exclusive window a build
      takes), so a query can never enumerate a generation the commit
      record is about to delete.  Every completed group bumps the
      writer-invalidation epoch through _notify_index_written, which
      retires result-cache entries and reader memos.
    """

    INTERVALS = ('hour', 'day')

    def __init__(self, server, rollup_s, compact_s, min_gens,
                 log=None):
        self.server = server
        self.rollup_s = rollup_s
        self.compact_s = compact_s
        self.min_gens = min_gens
        self.log = log
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.runs = 0
        self.last = None
        self.last_error = None
        self.backlog = 0
        self._thread = threading.Thread(
            target=self._run, name='dn-maintenance', daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def stats(self):
        with self._lock:
            return {'rollup_interval_s': self.rollup_s,
                    'compact_interval_s': self.compact_s,
                    'compact_min_gens': self.min_gens,
                    'runs': self.runs,
                    'compact_backlog': self.backlog,
                    'last': self.last,
                    'last_error': self.last_error}

    def _rollup_pass(self):
        from .. import rollup as mod_rollup
        doc = {'built': 0, 'fresh': 0, 'removed': 0, 'paused': False}
        for dsname, ds in member_datasources(self.server):
            for interval in self.INTERVALS:
                r = mod_rollup.build_rollups(
                    ds.ds_indexpath, interval,
                    governor=self.server.governor)
                for k in ('built', 'fresh', 'removed'):
                    doc[k] += r[k]
                doc['paused'] = doc['paused'] or r['paused']
        if doc['built']:
            obs_metrics.inc('rollup_shards_built_total',
                            doc['built'])
        return doc

    def _compact_pass(self):
        from .. import rollup as mod_rollup
        doc = {'groups': 0, 'compacted': 0, 'generations_removed': 0,
               'paused': False}
        backlog = 0
        for dsname, ds in member_datasources(self.server):
            root = ds.ds_indexpath
            for interval in self.INTERVALS:
                groups = [
                    (b, g)
                    for b, g in mod_rollup.find_gen_groups(root,
                                                           interval)
                    if len(g) >= self.min_gens]
                doc['groups'] += len(groups)
                for base, gens in groups:
                    if self.server.governor.mode() != 'ok':
                        doc['paused'] = True
                        obs_events.emit_burst(
                            'resource.paused', key='compact',
                            component='compact')
                        break
                    if self._stop.is_set():
                        break
                    # the same short exclusive window a build takes:
                    # queries drain, the group rewrites, queries
                    # resume against the compacted shard
                    with self.server._tree_lock(ds, dsname).write():
                        mod_rollup.compact_group(root, interval,
                                                 base, gens)
                    doc['compacted'] += 1
                    doc['generations_removed'] += len(gens)
                backlog += mod_rollup.compaction_backlog(root,
                                                         interval)
        if doc['compacted']:
            obs_metrics.inc('compact_groups_total', doc['compacted'])
            obs_metrics.inc('compact_generations_removed_total',
                            doc['generations_removed'])
        obs_metrics.set_gauge('compact_backlog', backlog)
        with self._lock:
            self.backlog = backlog
        return doc

    def _run(self):
        import time as mod_time
        tick = min(s for s in (self.rollup_s, self.compact_s)
                   if s > 0)
        next_rollup = mod_time.monotonic() + self.rollup_s \
            if self.rollup_s > 0 else None
        next_compact = mod_time.monotonic() + self.compact_s \
            if self.compact_s > 0 else None
        while not self._stop.wait(tick):
            now = mod_time.monotonic()
            last = {}
            try:
                if next_compact is not None and now >= next_compact:
                    last['compact'] = self._compact_pass()
                    next_compact = mod_time.monotonic() \
                        + self.compact_s
                if next_rollup is not None and now >= next_rollup:
                    last['rollup'] = self._rollup_pass()
                    next_rollup = mod_time.monotonic() \
                        + self.rollup_s
                if last:
                    with self._lock:
                        self.runs += 1
                        self.last = last
                        self.last_error = None
            except Exception as e:
                with self._lock:
                    self.last_error = repr(e)
                if self.log is not None:
                    self.log.error('maintenance pass failed',
                                   err=repr(e))
