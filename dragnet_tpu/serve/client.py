"""The `--remote` thin client: ship a parsed request to a resident
`dn serve`, stream the result bytes back verbatim, and fall back to
local execution — with a warning — when the server is unreachable.

The client does ALL argument parsing locally (usage errors never
travel), ships the parsed QueryConfig document plus output options,
and writes the response's stdout/stderr bytes through this process's
streams untouched — so remote output is byte-identical to local
output by construction, and `dn query --remote ... | sort` composes
exactly like the local pipeline would.

Fallback contract: local execution is only a safe substitute while
the request has observably NOT run — so the fallback window closes
the moment the response header arrives.  A transport failure after
that (server killed mid-response) raises RemoteTransportError
instead: the server may have already acted (a build!) and response
bytes may already be on this process's stdout, so re-running locally
would duplicate both.
"""

import json
import os
import socket
import sys

from ..errors import DNError

CHUNK = 1 << 16


class RemoteTransportError(DNError):
    """The connection died AFTER the server committed a response —
    too late to fall back to local execution."""


def parse_addr(value):
    """'--remote' address forms: a unix socket path, or HOST:PORT /
    :PORT for TCP."""
    if value and os.sep not in value and ':' in value:
        host, _, port = value.rpartition(':')
        if port.isdigit():
            return ('tcp', host or '127.0.0.1', int(port))
    return ('unix', value, None)


def _connect(value, timeout_s):
    kind, a, b = parse_addr(value)
    if kind == 'tcp':
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        addr = (a, b)
    else:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        addr = a
    sock.settimeout(timeout_s)
    sock.connect(addr)
    return sock


def _open_request(remote, req, timeout_s):
    """Connect, send one request line, read the response header.
    Everything in here is the pre-commit phase: failures raise plain
    OSError/ValueError and falling back to local execution is safe.
    Returns (header, response_file, sock)."""
    sock = _connect(remote, timeout_s)
    try:
        sock.sendall(json.dumps(req).encode() + b'\n')
        f = sock.makefile('rb')
        line = f.readline()
        if not line:
            raise OSError('server closed the connection before '
                          'responding')
        return json.loads(line.decode('utf-8')), f, sock
    except BaseException:
        sock.close()
        raise


def _read_exact(f, size):
    """Read exactly `size` payload bytes in chunks, yielding each;
    post-commit, so truncation is a RemoteTransportError."""
    left = size
    while left > 0:
        try:
            chunk = f.read(min(CHUNK, left))
        except OSError as e:
            raise RemoteTransportError(
                'remote response interrupted mid-payload',
                cause=DNError(str(e)))
        if not chunk:
            raise RemoteTransportError('remote response truncated '
                                       'mid-payload')
        yield chunk
        left -= len(chunk)


def _roundtrip(remote, req, timeout_s):
    """One buffered request/response exchange: returns (header,
    stdout_bytes, stderr_bytes)."""
    header, f, sock = _open_request(remote, req, timeout_s)
    try:
        out = b''.join(_read_exact(f, header.get('nout', 0)))
        err = b''.join(_read_exact(f, header.get('nerr', 0)))
        return header, out, err
    finally:
        sock.close()


def _write_bytes(stream, data):
    """Verbatim byte pass-through: the underlying binary buffer when
    the stream has one (flushing pending text first so ordering
    holds), a decode otherwise (StringIO capture harnesses)."""
    if not data:
        return
    buf = getattr(stream, 'buffer', None)
    try:
        stream.flush()
    except Exception:
        pass
    if buf is not None:
        buf.write(data)
        buf.flush()
    else:
        stream.write(data.decode('utf-8', 'replace'))


def request(remote, req, timeout_s=None):
    """Send one request and stream the response through this
    process's stdout/stderr.  Returns the remote exit code.  Raises
    OSError while falling back is still safe (pre-header), and
    RemoteTransportError once it is not."""
    if timeout_s is None:
        timeout_s = float(os.environ.get('DN_SERVE_CLIENT_TIMEOUT_S',
                                         '3600'))
    header, f, sock = _open_request(remote, req, timeout_s)
    try:
        for size, stream in ((header.get('nout', 0), sys.stdout),
                             (header.get('nerr', 0), sys.stderr)):
            for chunk in _read_exact(f, size):
                _write_bytes(stream, chunk)
        return int(header.get('rc', 1))
    finally:
        sock.close()


def request_bytes(remote, req, timeout_s=60.0):
    """request() for harnesses: returns (rc, header, stdout_bytes,
    stderr_bytes) instead of writing through the process streams."""
    header, out, err = _roundtrip(remote, req, timeout_s)
    return int(header.get('rc', 1)), header, out, err


def run_or_fallback(remote, req):
    """request() with the unreachable-server contract: on a
    PRE-COMMIT failure (connect/send/header) print the fallback
    warning and return None so the caller runs the command locally.
    Post-commit transport failures (RemoteTransportError) propagate —
    the server already acted and bytes may already be on stdout."""
    try:
        return request(remote, req)
    except RemoteTransportError:
        raise
    except (OSError, ValueError) as e:
        sys.stderr.write(
            'dn: warning: serve endpoint "%s" unreachable (%s); '
            'falling back to local execution\n'
            % (remote, getattr(e, 'strerror', None) or e))
        return None


def stats(remote, timeout_s=5.0):
    """Fetch and parse the server's /stats document (bench + tests)."""
    header, out, err = _roundtrip(remote, {'op': 'stats'}, timeout_s)
    return json.loads(out.decode('utf-8'))
