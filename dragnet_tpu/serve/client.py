"""The `--remote` thin client: ship a parsed request to a resident
`dn serve`, stream the result bytes back verbatim, and survive
transport flaps with bounded, jittered retries.

The client does ALL argument parsing locally (usage errors never
travel), ships the parsed QueryConfig document plus output options,
and writes the response's stdout/stderr bytes through this process's
streams untouched — so remote output is byte-identical to local
output by construction, and `dn query --remote ... | sort` composes
exactly like the local pipeline would.

Retry policy lives HERE, at the transport seam (Diba's
transport/execution separation: the engines never see a retry):

* Failures BEFORE the response header — connect refused/timed out,
  the request send cut short, the connection dying before the header
  — are pre-commit: the server has not published a response.  These
  retry up to DN_REMOTE_RETRIES times with exponential backoff
  (DN_REMOTE_BACKOFF_MS base, +/-50% jitter) on top of a per-attempt
  connect deadline (DN_REMOTE_CONNECT_TIMEOUT_S).  Queries and scans
  are idempotent; builds carry a client-generated idempotency key so
  a retried build whose first request actually ran replays the
  recorded response instead of double-writing.
* Responses the server marks `retryable` (busy, draining) retry the
  same way — the request was never admitted.
* Failures AFTER the header arrives are post-commit: response bytes
  may already be on this process's stdout, so the only honest outcome
  is RemoteTransportError — never a silent re-run.

When every attempt fails, the classification decides the caller's
move: RemoteUnreachable (no attempt ever reached a server — local
fallback is safe and run_or_fallback takes it, with the attempt count
in the warning) vs RemoteRetryExhausted (the server saw at least one
request but never answered — reported as a clean retryable transport
error with the attempt count, never a bare socket traceback, and
never a local fallback that might double-run a build).
"""

import io
import json
import os
import random
import socket
import sys
import time

from ..errors import DNError
from .. import faults as mod_faults
from ..obs import trace as obs_trace
from ..vpipe import counter_bump
from . import pool as mod_pool

CHUNK = 1 << 16


class RemoteTransportError(DNError):
    """The connection died AFTER the server committed a response —
    too late to retry or fall back to local execution."""


class RemoteUnreachable(DNError):
    """Every attempt failed at connect: no server ever saw the
    request, so local fallback is safe (run_or_fallback takes it)."""


class RemoteRetryExhausted(DNError):
    """Pre-commit failures exhausted the retry budget, but at least
    one attempt reached a server (the request may have been received):
    reported, not silently re-run locally."""


def parse_addr(value):
    """'--remote' address forms: a unix socket path, or HOST:PORT /
    :PORT for TCP."""
    if value and os.sep not in value and ':' in value:
        host, _, port = value.rpartition(':')
        if port.isdigit():
            return ('tcp', host or '127.0.0.1', int(port))
    return ('unix', value, None)


def retry_conf():
    """The validated DN_REMOTE_* knobs (config.remote_config); a
    malformed value raises its DNError here, before any socket is
    touched."""
    from .. import config as mod_config
    conf = mod_config.remote_config()
    if isinstance(conf, DNError):
        raise conf
    return conf


def _backoff_s(conf, attempt):
    """Exponential backoff with +/-50% jitter: attempt k (1-based)
    sleeps ~base * 2^(k-1) before attempt k+1."""
    base = conf['backoff_ms'] / 1000.0
    return base * (1 << (attempt - 1)) * random.uniform(0.5, 1.5)


def _connect(value, timeout_s, connect_timeout_s):
    mod_faults.fire('client.connect')
    kind, a, b = parse_addr(value)
    if kind == 'tcp':
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        addr = (a, b)
    else:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        addr = a
    # the connect deadline is its own (tighter) knob: a dead host must
    # fail fast so the retry/backoff loop — or the fallback — can act;
    # the exchange keeps the caller's longer timeout
    sock.settimeout(connect_timeout_s)
    try:
        sock.connect(addr)
    except BaseException:
        sock.close()
        raise
    sock.settimeout(timeout_s)
    return sock


def _open_request(remote, req, timeout_s, conf, phase):
    """Connect, send one request line, read the response header.
    Everything in here is the pre-commit phase: failures raise plain
    OSError/ValueError and retrying is safe.  `phase['phase']` tracks
    how far the attempt got ('connect' -> 'exchange') so exhausted
    retries classify correctly.  Returns (header, response_file,
    sock)."""
    sock = _connect(remote, timeout_s, conf['connect_timeout_s'])
    phase['phase'] = 'exchange'
    try:
        mod_faults.fire('client.send')
        sock.sendall(json.dumps(req).encode() + b'\n')
        f = sock.makefile('rb')
        mod_faults.fire('client.recv')
        line = f.readline()
        if not line:
            raise OSError('server closed the connection before '
                          'responding')
        return json.loads(line.decode('utf-8')), f, sock
    except BaseException:
        sock.close()
        raise


def _read_exact(f, size):
    """Read exactly `size` payload bytes in chunks, yielding each;
    post-commit, so truncation is a RemoteTransportError."""
    left = size
    while left > 0:
        try:
            chunk = f.read(min(CHUNK, left))
        except OSError as e:
            raise RemoteTransportError(
                'remote response interrupted mid-payload',
                cause=DNError(str(e)))
        if not chunk:
            raise RemoteTransportError('remote response truncated '
                                       'mid-payload')
        yield chunk
        left -= len(chunk)


def _default_timeout_s():
    return float(os.environ.get('DN_SERVE_CLIENT_TIMEOUT_S', '3600'))


def _retry_delay_s(conf, attempt, header):
    """Backoff before the next attempt: the server's own
    retry_after_ms hint when the rejection carried one (±20% jitter —
    a shed burst must not retry in lockstep), the blind exponential
    otherwise."""
    hint = header.get('retry_after_ms') if header else None
    if hint is None and header:
        hint = (header.get('stats') or {}).get('retry_after_ms')
    if hint is not None:
        try:
            counter_bump('remote retry-after honored')
            return max(0.001,
                       float(hint) / 1000.0 * random.uniform(0.8,
                                                             1.2))
        except (TypeError, ValueError):
            pass
    return _backoff_s(conf, attempt)


def _attempt(remote, req, timeout_s, conf, phase, pooled):
    """One request attempt: the pooled multiplexed path when the
    endpoint speaks v2, the dial-per-request path otherwise.
    Returns (header, response_file, sock_or_None)."""
    if pooled and not mod_pool.get().is_v1(remote):
        header, payload = mod_pool.get().exchange(
            remote, req, timeout_s, conf['connect_timeout_s'], phase)
        return header, io.BytesIO(payload), None
    return _open_request(remote, req, timeout_s, conf, phase)


def _exchange_with_retry(remote, req, timeout_s, on_header,
                         pooled=False):
    """The shared retry loop: attempt the request up to
    1 + DN_REMOTE_RETRIES times, backing off between attempts on
    pre-commit transport failures and retryable server rejections
    (busy/draining/overloaded — honoring the server's retry_after_ms
    hint when present).  On a kept response, returns
    on_header(header, f) with the socket managed here.  Raises
    RemoteUnreachable / RemoteRetryExhausted on exhaustion (see
    module docstring) and RemoteTransportError from post-commit
    failures.  `pooled` rides the persistent multiplexed connection
    (pool.py) with transparent v1 fallback."""
    conf = retry_conf()
    attempts = conf['retries'] + 1
    last_err = None
    reached_server = False
    for attempt in range(1, attempts + 1):
        phase = {'phase': 'connect'}
        try:
            header, f, sock = _attempt(remote, req, timeout_s, conf,
                                       phase, pooled)
        except RemoteTransportError:
            raise                     # post-commit: never retried
        except (OSError, ValueError, mod_faults.FaultInjected) as e:
            last_err = e
            if phase['phase'] != 'connect':
                reached_server = True
            if attempt < attempts:
                counter_bump('remote transport retries')
                time.sleep(_backoff_s(conf, attempt))
                continue
            break
        if header.get('retryable') and attempt < attempts:
            # busy/draining/shed: the request was never admitted —
            # back off (the server's retry_after_ms when it sent
            # one) and try again (the last attempt keeps the
            # server's error response so the user sees the real
            # message)
            if sock is not None:
                sock.close()
            counter_bump('remote retryable rejections')
            time.sleep(_retry_delay_s(conf, attempt, header))
            continue
        try:
            return on_header(header, f)
        finally:
            if sock is not None:
                sock.close()
    detail = getattr(last_err, 'strerror', None) or str(last_err)
    if reached_server:
        raise RemoteRetryExhausted(
            'remote transport failed after %d attempt(s) '
            '(retryable): %s' % (attempts, detail))
    raise RemoteUnreachable(
        'serve endpoint unreachable after %d attempt(s): %s'
        % (attempts, detail))


def graft_remote_trace(tctx, header):
    """Graft the span subtree a server returned in its response
    header (``stats.trace``) into `tctx` under the caller's current
    span — the shared joined-tree seam for the `--remote` client AND
    the router's pooled partial path."""
    remote_doc = (header.get('stats') or {}).get('trace')
    if remote_doc:
        tctx.graft(remote_doc.get('spans') or remote_doc)


def _write_bytes(stream, data):
    """Verbatim byte pass-through: the underlying binary buffer when
    the stream has one (flushing pending text first so ordering
    holds), a decode otherwise (StringIO capture harnesses)."""
    if not data:
        return
    buf = getattr(stream, 'buffer', None)
    try:
        stream.flush()
    except Exception:
        pass
    if buf is not None:
        buf.write(data)
        buf.flush()
    else:
        stream.write(data.decode('utf-8', 'replace'))


def request(remote, req, timeout_s=None):
    """Send one request (with the retry/backoff armor) and stream the
    response through this process's stdout/stderr.  Returns the
    remote exit code.  Raises RemoteUnreachable while falling back is
    still safe, RemoteRetryExhausted / RemoteTransportError when it
    is not.

    Trace propagation: when this process has an active trace context
    (DN_TRACE / DN_SLOW_MS / --trace), the request carries the
    CLIENT-generated trace id in its ``trace`` header and asks the
    server for its span subtree, which is grafted under this
    request's exchange span — one joined client+server tree."""
    if timeout_s is None:
        timeout_s = _default_timeout_s()
    tctx = obs_trace.current_trace()
    if tctx is not None and 'trace' not in req:
        req = dict(req, trace={'id': tctx.trace_id, 'want': True})
    req = _annotate(req)

    def stream_through(header, f):
        if tctx is not None:
            graft_remote_trace(tctx, header)
        for size, stream in ((header.get('nout', 0), sys.stdout),
                             (header.get('nerr', 0), sys.stderr)):
            for chunk in _read_exact(f, size):
                _write_bytes(stream, chunk)
        return int(header.get('rc', 1))

    # scans stream UNBOUNDED output (every record): they keep the
    # dial-per-request path, whose payload flows through in 64K
    # chunks — the pooled path necessarily buffers a whole response
    # to demultiplex it, which is fine for query/build/stats-sized
    # payloads and an OOM hazard for a multi-GB scan
    pooled = req.get('op') != 'scan'
    with obs_trace.span('remote.exchange', endpoint=str(remote)):
        return _exchange_with_retry(remote, req, timeout_s,
                                    stream_through, pooled=pooled)


def _annotate(req):
    """Attach the ambient request envelope: the end-to-end deadline
    (DN_REMOTE_DEADLINE_MS — the server sheds work it cannot finish
    inside it, and the router propagates the remaining budget to
    member partials) and the tenant identity (DN_REMOTE_TENANT —
    admission fairness keys on it; defaults to peer identity
    server-side)."""
    extra = {}
    if 'deadline_ms' not in req:
        conf = retry_conf()
        if conf['deadline_ms'] > 0:
            extra['deadline_ms'] = conf['deadline_ms']
    if 'tenant' not in req:
        tenant = os.environ.get('DN_REMOTE_TENANT')
        if tenant:
            extra['tenant'] = tenant
    return dict(req, **extra) if extra else req


def request_bytes(remote, req, timeout_s=60.0, retry=False,
                  pooled=None):
    """request() for harnesses, probes, and the router's partials:
    returns (rc, header, stdout_bytes, stderr_bytes) instead of
    writing through the process streams.  Defaults to a single
    attempt; pass retry=True for the armored _exchange_with_retry
    path (health/stats probes do — one transient accept flap must not
    read as a dead server).  `pooled` rides the persistent
    multiplexed connection (defaults to True with retry, False for
    the raw single-shot dial harnesses depend on)."""
    if pooled is None:
        pooled = retry
    req = _annotate(req)

    def buffer_up(header, f):
        out = b''.join(_read_exact(f, header.get('nout', 0)))
        err = b''.join(_read_exact(f, header.get('nerr', 0)))
        return int(header.get('rc', 1)), header, out, err

    if retry:
        return _exchange_with_retry(remote, req, timeout_s,
                                    buffer_up, pooled=pooled)
    conf = retry_conf()
    phase = {'phase': 'connect'}
    if pooled and not mod_pool.get().is_v1(remote):
        header, payload = mod_pool.get().exchange(
            remote, req, timeout_s, conf['connect_timeout_s'], phase)
        return buffer_up(header, io.BytesIO(payload))
    header, f, sock = _open_request(remote, req, timeout_s, conf,
                                    phase)
    try:
        return buffer_up(header, f)
    finally:
        sock.close()


def run_or_fallback(remote, req):
    """request() with the unreachable-server contract: when NO
    attempt ever reached a server (RemoteUnreachable), print the
    fallback warning — with the attempt count — and return None so
    the caller runs the command locally.  Once a server may have seen
    the request (RemoteRetryExhausted) or already responded
    (RemoteTransportError), the error propagates: re-running locally
    could duplicate output or a build's side effects."""
    try:
        return request(remote, req)
    except (RemoteTransportError, RemoteRetryExhausted):
        raise
    except RemoteUnreachable as e:
        sys.stderr.write(
            'dn: warning: serve endpoint "%s" unreachable (%s); '
            'falling back to local execution\n' % (remote, e.message))
        return None


def stats(remote, timeout_s=5.0):
    """Fetch and parse the server's /stats document (bench + tests).
    Rides the _exchange_with_retry backoff path: a transient accept
    flap must not read as a dead server."""
    rc, header, out, err = request_bytes(remote, {'op': 'stats'},
                                         timeout_s=timeout_s,
                                         retry=True)
    return json.loads(out.decode('utf-8'))


class SubscribeUnsupported(DNError):
    """The endpoint cannot serve a standing query (a v1 server, a
    pre-push v2 server, or DN_SUB_MAX=0): the caller's correct move
    is falling back to polling."""


def subscribe_stream(remote, req, timeout_s=None, resume=None):
    """Register the standing query `req` on a DEDICATED v2 connection
    and yield one dict per pushed frame: ``{'kind', 'sub', 'seq',
    'epoch', 'payload', 'token'}`` with ``payload`` always the FULL
    reconstructed result bytes (delta frames are spliced here, against
    the previous frame's payload — protocol.apply_delta).  Each data
    frame is acked before the next is read, which is the backpressure
    contract: a consumer that stops iterating stops acking, and the
    server degrades it without wedging anyone else.

    The connection is deliberately NOT the shared pool: push frames
    are server-initiated and the pool's demux treats unsolicited
    frames as protocol noise.  `resume` is (token, last_payload) from
    a previous stream's final frame; a server holding byte-identical
    state answers 'current' and resumes deltas against it with no
    re-seed.  Raises SubscribeUnsupported against a pre-push or v1
    endpoint (fallback is safe), DNError on a rejected registration,
    and RemoteTransportError when the stream dies mid-push (reconnect
    with the resume token)."""
    from . import protocol as mod_protocol
    conf = retry_conf()
    if timeout_s is None:
        timeout_s = _default_timeout_s()
    req = dict(_annotate(req), op='subscribe')
    token = payload = None
    if resume is not None:
        token, payload = resume
        req['resume'] = token
    sock = _connect(remote, timeout_s, conf['connect_timeout_s'])
    try:
        sock.sendall(mod_protocol.encode_request(req, 1))
        f = sock.makefile('rb')
        line = f.readline()
        if not line:
            raise OSError('server closed the connection before '
                          'responding')
        header = json.loads(line.decode('utf-8'))
        out = b''.join(_read_exact(f, header.get('nout', 0)))
        err = b''.join(_read_exact(f, header.get('nerr', 0)))
        if header.get('id') is None:
            # a v1 server answered (and closed): it can never push
            raise SubscribeUnsupported(
                'endpoint speaks protocol 1; subscriptions need a '
                'persistent v2 connection')
        if int(header.get('rc', 1)) != 0:
            msg = err.decode('utf-8', 'replace').strip()
            if 'unsupported request op' in msg or \
                    'subscriptions disabled' in msg:
                raise SubscribeUnsupported(msg or 'subscriptions '
                                           'unsupported')
            e = DNError(msg or 'subscribe rejected')
            e.retryable = bool(header.get('retryable'))
            raise e
        reg = json.loads(out.decode('utf-8'))
        sid = reg['sub']
        resumed = bool(reg.get('resumed'))
        if resumed and payload is not None:
            yield {'kind': 'current', 'sub': sid,
                   'seq': reg.get('seq', 0), 'epoch': reg['epoch'],
                   'payload': payload, 'token': reg.get('token')}
        else:
            payload = None        # a full seed frame is on its way
        rid = 1
        while True:
            line = f.readline()
            if not line:
                raise RemoteTransportError(
                    'subscription stream interrupted (reconnect '
                    'with the resume token)')
            header = json.loads(line.decode('utf-8'))
            body = b''.join(_read_exact(f, header.get('nout', 0)))
            b''.join(_read_exact(f, header.get('nerr', 0)))
            if mod_protocol.classify_frame(header) == 'response':
                # an ack's answer; a failed ack means the server no
                # longer knows us — resync by reconnecting
                if int(header.get('rc', 1)) != 0:
                    raise RemoteTransportError(
                        'subscription ack rejected: %s'
                        % body.decode('utf-8', 'replace').strip())
                continue
            kind = header.get('kind')
            stats = header.get('stats') or {}
            if kind == 'end':
                return
            if kind == 'delta':
                patch = stats.get('delta') or {}
                if payload is None:
                    raise RemoteTransportError(
                        'delta frame without a base payload')
                payload = mod_protocol.apply_delta(
                    payload, patch.get('off'), patch.get('keep'),
                    body)
            else:
                payload = body
            seq = header.get('seq')
            yield {'kind': kind, 'sub': sid, 'seq': seq,
                   'epoch': header.get('epoch'), 'payload': payload,
                   'token': stats.get('token')}
            rid += 1
            try:
                sock.sendall(mod_protocol.encode_request(
                    {'op': 'sub_ack', 'sub': sid, 'seq': seq}, rid))
            except OSError:
                # the server may be gone with frames still buffered
                # (a drain pushes 'end' THEN closes): the ack is
                # advisory — keep reading; the 'end' frame or EOF
                # resolves the stream
                pass
    except (OSError, ValueError) as e:
        raise RemoteTransportError(
            'subscription stream failed: %s' % e)
    finally:
        sock.close()


def health(remote, timeout_s=5.0):
    """A health probe: the parsed health document, or {'ok': False,
    'error': ...} — what a scatter-gather router polls to pick live
    replicas.  Probes ride the _exchange_with_retry backoff path: a
    single-shot probe would turn one transient accept failure into a
    'dead member' verdict — exactly wrong under a circuit breaker,
    which needs DN_ROUTER_FAILURES *post-retry* verdicts before it
    opens."""
    try:
        rc, header, out, err = request_bytes(
            remote, {'op': 'health'}, timeout_s=timeout_s,
            retry=True)
        return json.loads(out.decode('utf-8'))
    except (OSError, ValueError, DNError) as e:
        return {'ok': False, 'error': str(e)}
