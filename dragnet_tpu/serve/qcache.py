"""Server-side query-result cache: repeated identical queries answer
from memory, skipping admission-slot compute entirely.

Correctness before speed — a hit must be byte-identical to
re-executing the query, so an entry is served only while THREE
staleness signals all agree:

* **Key**: admission.compute_key — the canonical coalescing key (op,
  datasource, config identity, normalized query document, interval) —
  already excludes everything that only affects output formatting.

* **Epoch**: index_query_mt.cache_epoch(), bumped by
  invalidate_index_tree — which the server's
  lifecycle.install_writer_invalidation hook fires on EVERY completed
  in-process index write (build, follow publish, compaction, rollup
  build).  Any write anywhere retires every entry: conservative,
  O(1), and exactly the invalidation contract the issue's write-hook
  machinery provides.

* **Validators**: stat identities of the queried tree's shard-bearing
  directories, re-checked on every hit.  A CROSS-process writer (a
  `dn build` run against a live server's tree) publishes by renaming
  into those directories, which changes their mtime — the in-process
  epoch can't see it, the validator does.

Memory accounting shares ONE budget with request admission
(resources.ResourceGovernor.reserve_cache): cached residency and
in-flight request footprint draw on the same DN_SERVE_MEM_BUDGET_MB
pool, so a full cache sheds admissions before the process swaps, and
admission pressure evicts cache entries rather than both sides
double-counting the same RAM.  The cache's own byte bound is
DN_SERVE_CACHE_MB (0 = disabled; the serve path is then byte-for-byte
the uncached one).
"""

import json
import os
import threading
from collections import OrderedDict

from .. import integrity as mod_integrity


def _estimate_nbytes(result):
    """Resident-size estimate of a ScanResult: the serialized length
    of its points plus pipeline counters — the same order of bytes a
    client response carries, which is what the budget is protecting
    against."""
    n = 256
    try:
        if result.points is not None:
            n += len(json.dumps(result.points, default=repr))
        if result.dry_run_files is not None:
            n += sum(len(p) + 16 for p in result.dry_run_files)
        for s in result.pipeline.stages:
            n += 64 + 32 * len(s.counters)
    except (TypeError, ValueError):
        n += 1 << 20        # unserializable points: assume big
    return n


def tree_validators(indexroot):
    """Stat identities of every directory a publish renames into
    (plus the `all` shard file).  None entries record absence — a
    directory appearing later is a change too.

    The integrity catalog rides along because the directory stats
    alone are blind to one cross-process case: a publish that renames
    into per-day subdirectories which ALL already exist changes
    by_day/<day> but not by_day itself.  Every commit rewrites the
    catalog atomically, so its stat identity is a per-publish change
    signal at the tree root — one extra os.stat per hit."""
    if not indexroot:
        return []
    paths = [indexroot,
             mod_integrity.catalog_path(indexroot),
             os.path.join(indexroot, 'all'),
             os.path.join(indexroot, 'by_day'),
             os.path.join(indexroot, 'by_hour'),
             os.path.join(indexroot, 'rollup', 'by_day'),
             os.path.join(indexroot, 'rollup', 'by_month')]
    out = []
    for p in paths:
        try:
            st = os.stat(p)
            out.append((p, (st.st_mtime_ns, st.st_size)))
        except OSError:
            out.append((p, None))
    return out


def _validators_ok(validators):
    for p, sig in validators:
        try:
            st = os.stat(p)
            cur = (st.st_mtime_ns, st.st_size)
        except OSError:
            cur = None
        if cur != sig:
            return False
    return True


class ResultCache(object):
    """LRU over ScanResults, bounded by bytes, validated by epoch +
    tree stat identity.  Thread-safe; governor reservations are only
    ever taken under the cache lock (one-directional lock order:
    cache -> governor, never the reverse)."""

    def __init__(self, budget_bytes, governor=None):
        self.budget = int(budget_bytes or 0)
        self.governor = governor
        self._lock = threading.Lock()
        self._entries = OrderedDict()
        self._bytes = 0
        self._hits = 0
        self._misses = 0
        self._stale = 0
        self._evictions = 0
        self._shed = 0

    def enabled(self):
        return self.budget > 0

    # -- internals (call with self._lock held) ----------------------------

    def _drop_locked(self, key, ent):
        # identity-checked: between a reader's two lock windows a put
        # may have replaced this key — dropping the NEW entry while
        # refunding the OLD entry's bytes would skew the accounting
        if self._entries.get(key) is not ent:
            return
        del self._entries[key]
        self._bytes -= ent['nbytes']
        if self.governor is not None:
            self.governor.release_cache(ent['nbytes'])

    def _evict_lru_locked(self):
        if not self._entries:
            return False
        key, ent = next(iter(self._entries.items()))
        self._drop_locked(key, ent)
        self._evictions += 1
        return True

    # -- the cache protocol ------------------------------------------------

    def get(self, key, epoch):
        """The cached ScanResult for `key`, or None.  The caller must
        clone_for_output() before formatting (exactly like a
        coalesced execution) — the cached result is shared."""
        if not self.enabled() or key is None:
            return None
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and ent['epoch'] == epoch:
                self._entries.move_to_end(key)
            elif ent is not None:
                self._drop_locked(key, ent)
                self._stale += 1
                ent = None
        if ent is None:
            with self._lock:
                self._misses += 1
            return None
        # stat checks outside the lock — no other thread can free
        # this entry's governor bytes out from under a concurrent
        # put: a drop only ever releases what _bytes still accounts
        if not _validators_ok(ent['validators']):
            with self._lock:
                self._drop_locked(key, ent)
                self._stale += 1
                self._misses += 1
            return None
        with self._lock:
            self._hits += 1
        return ent['result']

    def put(self, key, epoch, validators, result):
        """Insert a computed result.  Over-budget inserts evict LRU
        entries; when the SHARED memory budget (governor) refuses even
        after the cache is empty, the insert is shed — request
        admission always outranks cache residency."""
        if not self.enabled() or key is None:
            return False
        nbytes = _estimate_nbytes(result)
        if nbytes > self.budget:
            with self._lock:
                self._shed += 1
            return False
        ent = {'epoch': epoch, 'validators': validators,
               'result': result, 'nbytes': nbytes}
        with self._lock:
            old = self._entries.get(key)
            if old is not None:
                self._drop_locked(key, old)
            while self._bytes + nbytes > self.budget:
                if not self._evict_lru_locked():
                    break
            if self.governor is not None:
                while not self.governor.reserve_cache(nbytes):
                    if not self._evict_lru_locked():
                        self._shed += 1
                        return False
            self._entries[key] = ent
            self._bytes += nbytes
        return True

    def clear(self):
        """Drop everything and hand every reserved byte back (drain
        path, and the big hammer for tests)."""
        with self._lock:
            for key, ent in list(self._entries.items()):
                self._drop_locked(key, ent)

    def stats(self):
        with self._lock:
            hits, misses = self._hits, self._misses
            doc = {
                'enabled': self.enabled(),
                'budget_bytes': self.budget,
                'bytes': self._bytes,
                'entries': len(self._entries),
                'hits': hits,
                'misses': misses,
                'stale_drops': self._stale,
                'evictions': self._evictions,
                'shed': self._shed,
            }
        total = hits + misses
        doc['hit_rate'] = round(hits / total, 4) if total else 0.0
        return doc
