"""`dn top`: a live terminal operator console over the fleet view.

Plain ANSI redraw — no curses, no new dependencies: each frame homes
the cursor (ESC[H), draws the fleet header (epoch, members
up/draining/unreachable, qps, p50/p95, shed rate), the per-member
table, and the scrolling event tail, clearing to end-of-screen
(ESC[J) so shrinking frames leave no stale rows.  Polls the
``fleet_stats`` op at DN_TOP_INTERVAL_MS; a server that is not a
cluster member answers with a one-member fleet of itself, so the
console degrades to single-process mode against a bare `--remote`
socket with no mode switch.

A fetch failure paints an error banner and keeps polling (the server
coming back mid-incident is exactly when the operator is watching);
Ctrl-C exits cleanly.  `--once` renders a single frame with no ANSI
control codes — the scriptable/testable path.
"""

import json
import sys
import time

from ..errors import DNError

HOME = '\x1b[H'
CLEAR_TO_END = '\x1b[J'
BOLD, DIM, RESET = '\x1b[1m', '\x1b[2m', '\x1b[0m'

EVENT_TAIL_ROWS = 12


def _fmt(v, unit='', none='-'):
    if v is None:
        return none
    if isinstance(v, float):
        return ('%.1f%s' if v >= 10 else '%.2f%s') % (v, unit)
    return '%s%s' % (v, unit)


def _fmt_bytes(v, none='-'):
    if v is None:
        return none
    v = float(v)
    for unit in ('B', 'KB', 'MB', 'GB'):
        if v < 1024 or unit == 'GB':
            return ('%d%s' % (v, unit)) if unit == 'B' \
                else ('%.1f%s' % (v, unit))
        v /= 1024.0
    return none


def _member_state(row):
    if not row.get('ok'):
        return 'DOWN'
    if row.get('leaving'):
        return 'leaving'
    if row.get('draining'):
        return 'draining'
    if row.get('degraded_ro'):
        return 'read-only'       # disk critical: still serving reads
    if row.get('pending_epoch'):
        return 'handoff'
    if row.get('disk_mode') == 'low':
        return 'disk-low'
    return 'up'


def render_frame(doc, ansi=True):
    """The full frame for one fleet document; returns the string
    (render and transport separated so tests pin the layout without a
    terminal)."""
    b, d, r = (BOLD, DIM, RESET) if ansi else ('', '', '')
    lines = []
    agg = doc.get('aggregate') or {}
    lat = agg.get('latency') or {}
    when = time.strftime('%H:%M:%S',
                         time.localtime(doc.get('ts') or time.time()))
    epoch = doc.get('epoch')
    head = ('%sdn top%s  %s  epoch %s  members %d/%d up'
            % (b, r, when, epoch if epoch is not None else '-',
               doc.get('members_up', 0), doc.get('members_total', 0)))
    if doc.get('members_draining'):
        head += '  (%d draining)' % doc['members_draining']
    if doc.get('unreachable'):
        head += '  %sUNREACHABLE: %s%s' \
            % (b, ','.join(doc['unreachable']), r)
    if doc.get('epoch_skew'):
        head += '  %sepoch skew %d%s' % (b, doc['epoch_skew'], r)
    lines.append(head)
    lines.append(
        'qps %s  p50 %s  p95 %s  p99 %s  shed/s %s  requests %s  '
        'errors %s'
        % (_fmt(agg.get('qps_1m')), _fmt(lat.get('p50'), 'ms'),
           _fmt(lat.get('p95'), 'ms'), _fmt(lat.get('p99'), 'ms'),
           _fmt(agg.get('shed_rate_1m')), _fmt(agg.get('requests')),
           _fmt(agg.get('errors'))))
    rp = doc.get('repair') or {}
    if rp.get('queued') or rp.get('completed') or rp.get('failed'):
        lines.append('repair queued %d completed %d failed %d'
                     % (rp.get('queued', 0), rp.get('completed', 0),
                        rp.get('failed', 0)))
    # repeat-traffic line: only when some member runs a cache or a
    # maintenance timer (bare fleets keep the old frame byte-for-byte)
    if agg.get('cache_hit_rate') is not None or \
            agg.get('compact_backlog') is not None or \
            agg.get('rollup_coverage'):
        lines.append(
            'cache hit %s  rollup cov %s  compact backlog %s'
            % (_fmt(agg.get('cache_hit_rate')),
               _fmt(agg.get('rollup_coverage')),
               _fmt(agg.get('compact_backlog'))))
    # device-lane line: only when some member runs HBM residency
    # (host-only fleets keep the old frame byte-for-byte)
    if agg.get('device_residency_hit_rate') is not None or \
            agg.get('device_pinned_bytes') is not None:
        dev = ('device resid hit %s  pinned %s'
               % (_fmt(agg.get('device_residency_hit_rate')),
                  _fmt_bytes(agg.get('device_pinned_bytes'))))
        # index-query offload column: only once some member's device
        # index lane has dispatched (idle lanes keep the line short)
        if agg.get('index_device_dispatches') is not None:
            dev += ('  iq disp %s  sh/disp %s  h2d saved %s'
                    % (_fmt(agg.get('index_device_dispatches')),
                       _fmt(agg.get(
                           'index_device_shards_per_dispatch')),
                       _fmt_bytes(agg.get(
                           'index_device_h2d_saved_bytes'))))
        lines.append(dev)
    if doc.get('members_read_only'):
        lines.append('%sDISK: %d member(s) read-only (min free %s%%)'
                     '%s'
                     % (b, doc['members_read_only'],
                        _fmt(doc.get('min_disk_free_pct')), r))
    elif doc.get('min_disk_free_pct') is not None and \
            doc['min_disk_free_pct'] < 15:
        lines.append('disk: min free %s%%'
                     % _fmt(doc['min_disk_free_pct']))
    lines.append('')

    cols = ('member', 'state', 'epoch', 'qps', 'p50', 'p95',
            'inflight', 'shed', 'repair', 'lag', 'cache', 'backlog')
    widths = [11, 9, 7, 8, 9, 9, 10, 7, 7, 9, 7, 8]
    lines.append(d + ''.join(c.ljust(w)
                             for c, w in zip(cols, widths)) + r)
    breakers = doc.get('breakers') or {}
    for name in sorted((doc.get('members') or {})):
        row = doc['members'][name]
        state = _member_state(row)
        br = breakers.get(name) or {}
        if row.get('ok') and br.get('state') not in (None, 'closed'):
            state += '!'          # this router's breaker is not closed
        ep = row.get('epoch')
        if row.get('pending_epoch'):
            ep = '%s>%s' % (ep, row['pending_epoch'])
        vals = (
            name, state,
            _fmt(ep), _fmt(row.get('qps_1m')),
            _fmt(row.get('p50_ms'), 'ms'),
            _fmt(row.get('p95_ms'), 'ms'),
            '%s/%s' % (row.get('inflight', '-'),
                       row.get('queued', '-'))
            if row.get('ok') else '-',
            _fmt(row.get('shed')), _fmt(row.get('repair_queued')),
            _fmt(row.get('ingest_lag_ms'), 'ms'),
            _fmt(row.get('cache_hit_rate')),
            _fmt(row.get('compact_backlog')))
        line = ''.join(str(v).ljust(w)
                       for v, w in zip(vals, widths))
        lines.append(line)
    lines.append('')

    events = doc.get('events') or []
    if events:
        lines.append(d + 'events' + r)
        for e in events[-EVENT_TAIL_ROWS:]:
            ets = time.strftime(
                '%H:%M:%S', time.localtime(e.get('ts') or 0))
            attrs = {k: v for k, v in e.items()
                     if k not in ('ts', 'seq', 'type', 'member',
                                  'trace')}
            detail = ' '.join('%s=%s' % (k, v)
                              for k, v in sorted(attrs.items()))
            lines.append(('%s %-10s %-22s %s'
                          % (ets, e.get('member') or '-',
                             e.get('type') or '?', detail))[:118])
    elif doc.get('members') and not any(
            m.get('events') for m in doc['members'].values()
            if m.get('ok')):
        lines.append(d + 'events: journal disabled on every member '
                     '(set DN_EVENTS / DN_EVENTS_FILE)' + r)
    return '\n'.join(lines) + '\n'


def fetch_fleet(remote, timeout_s=30.0, events_limit=None):
    """One fleet_stats fetch; raises DNError on failure."""
    from . import client as mod_client
    req = {'op': 'fleet_stats'}
    if events_limit is not None:
        req['events'] = events_limit
    rc, header, out, err = mod_client.request_bytes(
        remote, req, timeout_s=timeout_s)
    if rc != 0:
        raise DNError(err.decode('utf-8', 'replace').strip()
                      or 'fleet_stats failed')
    try:
        return json.loads(out.decode('utf-8'))
    except ValueError as e:
        raise DNError('malformed fleet_stats response',
                      cause=DNError(str(e)))


def _top_subscribed(remote, interval_ms, once, out):
    """The push-path console (`dn top --subscribe`): one standing
    fleet subscription, frames arriving as the server publishes them
    — no re-poll, no per-refresh aggregation server-side.  Returns an
    exit code, or None when the endpoint cannot push (a v1 or
    pre-push server) and the caller should fall back to polling.  A
    mid-stream transport cut reconnects with the resume token; the
    server recognizing the token skips the re-seed."""
    from . import client as mod_client
    req = {'op': 'subscribe', 'watch': 'fleet',
           'interval_ms': max(100, int(interval_ms))}
    resume = None
    first = True
    failures = 0
    while True:
        stream = None
        try:
            stream = mod_client.subscribe_stream(remote, dict(req),
                                                 resume=resume)
            for fr in stream:
                failures = 0
                resume = (fr['token'], fr['payload'])
                doc = json.loads(fr['payload'].decode('utf-8'))
                if once:
                    out.write(render_frame(doc, ansi=False))
                    out.flush()
                    return 0
                frame = HOME + render_frame(doc, ansi=True) + \
                    CLEAR_TO_END
                if first:
                    frame = '\x1b[2J' + frame
                    first = False
                try:
                    out.write(frame)
                    out.flush()
                except (BrokenPipeError, OSError):
                    return 0
            # clean 'end' frame (server draining): reconnect and
            # keep watching — the replacement coming up is exactly
            # when the operator is looking
            time.sleep(interval_ms / 1000.0)
        except mod_client.SubscribeUnsupported:
            return None
        except KeyboardInterrupt:
            out.write('\n')
            return 0
        except (DNError, OSError, ValueError) as e:
            failures += 1
            if once or failures > 5:
                sys.stderr.write('dn: fleet subscription failed: '
                                 '%s\n' % getattr(e, 'message', e))
                return 1
            try:
                time.sleep(interval_ms / 1000.0)
            except KeyboardInterrupt:
                out.write('\n')
                return 0
        finally:
            if stream is not None:
                stream.close()


def top_main(remote, interval_ms, once=False, out=None,
             subscribe=False):
    """The console loop; returns the exit code.  `once` renders one
    frame without ANSI control codes and exits.  `subscribe` rides
    the push path (serve/subscribe.py) and falls back to polling —
    with a one-line notice — against servers that cannot push."""
    if out is None:
        out = sys.stdout
    if subscribe:
        rc = _top_subscribed(remote, interval_ms, once, out)
        if rc is not None:
            return rc
        sys.stderr.write('dn: server does not support subscriptions;'
                         ' falling back to polling\n')
    first = True
    while True:
        banner = None
        try:
            doc = fetch_fleet(remote,
                              timeout_s=max(30.0,
                                            interval_ms / 1000.0))
        except (DNError, OSError, ValueError) as e:
            if once:
                sys.stderr.write('dn: fleet fetch failed: %s\n'
                                 % getattr(e, 'message', e))
                return 1
            doc = None
            banner = ('fleet fetch failed: %s (retrying)'
                      % getattr(e, 'message', e))
        if once:
            out.write(render_frame(doc, ansi=False))
            out.flush()
            return 0
        frame = HOME
        if doc is not None:
            frame += render_frame(doc, ansi=True)
        else:
            frame += '%sdn top%s  %s\n' % (BOLD, RESET, banner)
        frame += CLEAR_TO_END
        if first:
            # one full clear on entry so prior shell output does not
            # bleed through between frames
            frame = '\x1b[2J' + frame
            first = False
        try:
            out.write(frame)
            out.flush()
        except (BrokenPipeError, OSError):
            return 0
        try:
            time.sleep(interval_ms / 1000.0)
        except KeyboardInterrupt:
            out.write('\n')
            return 0
