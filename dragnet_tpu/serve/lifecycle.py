"""Lifecycle hygiene for `dn serve`: pidfile + socket claim/reclaim,
liveness probing, drain-time cleanup, and writer-invalidation wiring.

Startup follows the classic daemon claim protocol: a pidfile and a
socket left behind by a crashed server ("stale") must not block the
next start, but a LIVE server must — so claiming probes before
reclaiming.  A unix socket path that accepts a connection and answers
a ping belongs to a live server (claim fails); one that refuses or
times out is an orphan and is unlinked.  The pidfile is the secondary
signal: a recorded pid that no longer exists (or whose socket is
dead) is stale and reclaimed.

Writer invalidation: the index writers already invalidate the reader
caches shard-by-shard as they land (index_build_mt ->
shard_cache_invalidate, covering the `_index_write` path too).  A
resident server additionally retires whole-tree derived state on
every completed write — `install_writer_invalidation` registers an
index-write hook that sweeps the handle cache + find memo under the
written root (catching DELETED shards a per-path invalidation can
never see) and counts the event for /stats.
"""

import os

from ..errors import DNError
from ..vpipe import counter_bump


def pidfile_for(socket_path, explicit=None):
    """Default pidfile: next to the unix socket.  TCP servers have no
    socket file, so they get a pidfile only when --pidfile says so."""
    if explicit:
        return explicit
    if socket_path:
        return socket_path + '.pid'
    return None


def probe(socket_path=None, port=None, host='127.0.0.1',
          timeout_s=2.0):
    """True when a live `dn serve` answers a ping at the address."""
    from . import client as mod_client
    if socket_path is not None:
        remote = socket_path
    else:
        remote = '%s:%d' % (host, int(port))
    try:
        rc, header, out, err = mod_client.request_bytes(
            remote, {'op': 'ping'}, timeout_s=timeout_s)
        return bool(header.get('ok'))
    except (OSError, ValueError, DNError):
        return False


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def claim(socket_path=None, port=None, pidfile=None, warn=None):
    """Take ownership of the serve endpoint, reclaiming stale litter.

    Raises DNError when a live server already owns it.  `warn(msg)` is
    told about each reclaimed artifact (stale pidfile, orphaned
    socket).  On success the pidfile (when any) records this pid."""
    def note(msg):
        if warn is not None:
            warn(msg)

    if pidfile and os.path.exists(pidfile):
        pid = None
        try:
            with open(pidfile) as f:
                pid = int(f.read().strip() or '0')
        except (OSError, ValueError):
            pid = None
        if pid and _pid_alive(pid) and \
                probe(socket_path=socket_path, port=port):
            raise DNError('dn serve already running (pid %d)' % pid)
        note('reclaiming stale pidfile "%s" (pid %s)'
             % (pidfile, pid if pid else 'unreadable'))
        try:
            os.unlink(pidfile)
        except OSError:
            pass

    if socket_path and os.path.exists(socket_path):
        if probe(socket_path=socket_path):
            raise DNError('dn serve already running on socket "%s"'
                          % socket_path)
        note('reclaiming orphaned socket "%s"' % socket_path)
        try:
            os.unlink(socket_path)
        except OSError as e:
            raise DNError('cannot reclaim socket "%s"' % socket_path,
                          cause=DNError(str(e)))

    if pidfile:
        try:
            with open(pidfile, 'w') as f:
                f.write('%d\n' % os.getpid())
        except OSError as e:
            raise DNError('cannot write pidfile "%s"' % pidfile,
                          cause=DNError(str(e)))


def release(socket_path=None, pidfile=None):
    """Drain-time cleanup: unlink the socket and pidfile (missing
    files are fine — release must be idempotent)."""
    for path in (socket_path, pidfile):
        if not path:
            continue
        try:
            os.unlink(path)
        except OSError:
            pass


def install_writer_invalidation():
    """Register the server's coherence hook on the index writers;
    returns the hook so the caller can unregister at drain."""
    from .. import index_build_mt as mod_ibmt
    from .. import index_query_mt as mod_iqmt

    def on_written(indexroot, paths):
        mod_iqmt.invalidate_index_tree(indexroot)
        counter_bump('index writer invalidations')

    mod_ibmt.register_index_write_hook(on_written)
    return on_written


def remove_writer_invalidation(hook):
    from .. import index_build_mt as mod_ibmt
    mod_ibmt.unregister_index_write_hook(hook)
