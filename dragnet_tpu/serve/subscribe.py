"""`dn subscribe`: standing queries with incremental aggregation and
pushed result frames.

Every dashboard before this PR polled the full query path — N viewers
of one metric cost N stacked aggregations per refresh, even though
`dn follow` already publishes the mini-batches that change the
answer.  This module extends the paper's "pre-aggregate once, answer
cheaply many times" to TIME: a subscriber registers a standing,
normalized QueryConfig over a persistent v2 connection; the manager
maintains the aggregation incrementally as publishes land and PUSHES
delta or full result frames, so fan-out per publish is one
incremental merge instead of N repeated scans.

The correctness contract is the headline: **a subscriber's pushed
frame at index-tree epoch E is byte-identical to a poll executed at
epoch E.**  That falls out structurally, not by re-verification:

* Subscriptions sharing one (datasource, config, query document,
  interval, output options) tuple share one GROUP.  A group's state
  is the per-shard key-item memo — ``{shard: (stat identity,
  key items)}`` — exactly the aggregate export the PR 8 cluster
  merge proved byte-identical to the single-process walk
  (router.partial_query / Aggregator.merge_key_items).
* A recompute re-enumerates the shard walk (the identical
  index_query_paths enumerate/litter/prune path a poll runs), folds
  ONLY shards whose stat identity changed (`dn follow` merge-publish
  rewrites a small set of hour shards per batch; everything else
  replays from the memo), drops deleted shards, and merges all
  per-shard items in global find order into a fresh aggregator.
* The result renders through the SAME output layer a poll uses
  (cli.dn_output under the server's thread-stdio capture), so the
  frame bytes equal the poll bytes by construction.

Dirty signals: the in-process index write hook
(index_build_mt.register_index_write_hook) fires for every completed
publish — builds, follow mini-batches, compaction, rollups — and a
revalidation tick at the coalesce cadence catches CROSS-process
writers via the same tree stat validators the query cache trusts
(qcache.tree_validators), bumping the writer-invalidation epoch
before recomputing so frame epochs and poll epochs agree.  The
coalesce latency (DN_SUB_COALESCE_MS) is the StreamBox-HBM-style
target bound: a dirty group waits that long to batch adjacent
publishes, then pushes once.

Backpressure rides the PR 10 write-queue machinery: pushes are
loop.send() enqueues (never block the pusher), a subscriber with
DN_SUB_QUEUE_DEPTH unacked frames is degraded to one coalesced FULL
frame when its acks catch up (deltas need a base the peer provably
has), and a peer that stops reading altogether is reaped by the
existing write deadline.  One stalled dashboard can never wedge the
publisher or delay healthy subscribers.

Wire shape: server-initiated frames on the v2 framing carry ``sub``
(the subscription id) instead of a request ``id`` — see
protocol.encode_push.  A v1 peer can never receive one: registration
itself requires a v2 frame.  Every frame carries a resume token; a
reconnecting subscriber presents it and is either told 'current'
(digest match — keep your payload, no re-seed) or re-seeded with a
full frame at the current epoch.
"""

import hashlib
import json
import os
import threading
import time

from .. import config as mod_config
from .. import faults as mod_faults
from .. import index_build_mt as mod_build
from .. import index_query_mt as mod_iqmt
from .. import query as mod_query
from ..errors import DNError
from ..obs import events as obs_events
from ..obs import metrics as obs_metrics
from . import admission as mod_admission
from . import protocol as mod_protocol
from . import qcache as mod_qcache

# output options a standing query may carry: everything else either
# writes run-varying bytes (counters, warnings) or is a local-only
# mode flag — both would break the pushed-vs-polled identity contract
_ALLOWED_OPTS = ('raw', 'points')


def _group_doc(req):
    """The canonical standing-query document: everything that
    determines the PUSHED BYTES (unlike admission.compute_key, the
    output options are included — a group caches rendered bytes, not
    a re-renderable result)."""
    watch = req.get('watch') or 'query'
    if watch == 'fleet':
        doc = {'watch': 'fleet',
               'events': req.get('events')
               if isinstance(req.get('events'), int) and
               not isinstance(req.get('events'), bool) and
               req.get('events') >= 0 else 50,
               'interval_ms': req.get('interval_ms')
               if isinstance(req.get('interval_ms'), int) and
               not isinstance(req.get('interval_ms'), bool) and
               req.get('interval_ms') >= 100 else 2000}
        return doc
    opts = req.get('opts') or {}
    return {
        'watch': 'query',
        'ds': req.get('ds'),
        'config': req.get('config'),
        'queryconfig': req.get('queryconfig'),
        'interval': req.get('interval') or 'day',
        'opts': {k: bool(opts.get(k)) for k in _ALLOWED_OPTS
                 if opts.get(k)},
    }


def _group_key(doc):
    blob = json.dumps(doc, sort_keys=True, separators=(',', ':'))
    return blob, hashlib.sha1(blob.encode('utf-8')).hexdigest()[:16]


def _payload_digest(payload):
    return hashlib.sha1(payload or b'').hexdigest()[:16]


class _OutOpts(object):
    """The minimal options surface cli.dn_output reads, rebuilt from
    a group's normalized output-option doc."""

    def __init__(self, doc):
        for name in ('raw', 'points', 'counters', 'gnuplot'):
            setattr(self, name, bool(doc.get(name)))
        self.dry_run = False


class Subscription(object):
    __slots__ = ('sid', 'conn', 'group', 'seq', 'acked', 'lagging',
                 'dirty', 'last_payload', 'peer', 'created',
                 'frames_full', 'frames_delta', 'sheds')

    def __init__(self, sid, conn, group):
        self.sid = sid
        self.conn = conn
        self.group = group
        self.seq = 0              # last frame sent
        self.acked = 0            # highest frame acked
        self.lagging = False      # over the unacked-depth bound
        self.dirty = False        # missed at least one group version
        self.last_payload = None  # delta base (shares group bytes)
        self.peer = conn.peer
        self.created = time.time()
        self.frames_full = 0
        self.frames_delta = 0
        self.sheds = 0


class Group(object):
    """One standing query's shared state: the per-shard memo, the
    current rendered payload, and the subscribers riding it.  One
    recompute per publish batch serves every member."""

    def __init__(self, key, kdigest, doc):
        self.key = key
        self.kdigest = kdigest
        self.doc = doc
        self.subs = set()
        self.memo = {}            # shard path -> (stat ident, items)
        self.payload = None       # current rendered stdout bytes
        self.digest = None
        self.epoch = 0
        self.version = 0          # bumps when the payload changes
        self.validators = None    # cross-process change detector
        self.dirty = True
        self.confirm_at = None    # routed reconvergence deadline
        self.last_error = None
        self.last_compute = 0.0
        self.recomputes = 0
        # serializes seed vs pusher recompute (reentrant: the seed
        # path holds it across _recompute, which the sweep also does)
        self.compute_lock = threading.RLock()


class SubscriptionManager(object):
    def __init__(self, server, conf=None):
        if conf is None:
            conf = mod_config.subscribe_config()
        if isinstance(conf, DNError):
            raise conf
        self.server = server
        self.conf = conf
        self.log = server.log
        self._lock = threading.RLock()
        self._groups = {}         # key -> Group
        self._subs = {}           # sid -> Subscription
        self._by_conn = {}        # conn fd -> set of sids
        self._next = 1
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = None
        self._hook = None
        self._counters = {'registered': 0, 'dropped': 0,
                          'resumed': 0, 'recomputes': 0,
                          'shards_folded': 0, 'shards_reused': 0,
                          'pushes': 0, 'push_bytes': 0,
                          'frames_full': 0, 'frames_delta': 0,
                          'lagging_sheds': 0, 'duplicate_acks': 0,
                          'reconfirms': 0, 'compute_errors': 0}

    # -- lifecycle --------------------------------------------------------

    def enabled(self):
        return self.conf['max'] > 0

    def start(self):
        if not self.enabled():
            return self
        self._hook = self._on_index_write
        mod_build.register_index_write_hook(self._hook)
        self._thread = threading.Thread(target=self._run,
                                        name='dn-subscribe',
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Drain: tell every subscriber the stream is over (a clean
        'end' frame beats a bare EOF — the client reconnects with its
        resume token instead of guessing), then stop the pusher."""
        self._stop.set()
        self._wake.set()
        if self._hook is not None:
            mod_build.unregister_index_write_hook(self._hook)
            self._hook = None
        with self._lock:
            subs = list(self._subs.values())
            self._subs.clear()
            self._groups.clear()
            self._by_conn.clear()
        loop = self.server.loop
        for sub in subs:
            if loop is not None and not sub.conn.closed:
                frame = mod_protocol.encode_push(
                    sub.sid, sub.seq + 1, sub.group.epoch, 'end',
                    extra={'reason': 'draining'})
                loop.send(sub.conn, frame, close_after=True)
        self._set_gauges()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None

    def _bump(self, name, n=1):
        with self._lock:
            self._counters[name] += n

    def _set_gauges(self):
        with self._lock:
            obs_metrics.set_gauge('sub_active', len(self._subs))
            obs_metrics.set_gauge('sub_groups', len(self._groups))

    # -- registration (worker threads) ------------------------------------

    def subscribe(self, conn, req, proto):
        """Register one standing query for `conn`.  Returns (rc, out,
        err, extra, subscription-or-None); the caller sends the
        response FIRST, then calls activate() so the seed frame can
        never outrun the registration ack."""
        if proto != mod_protocol.PROTO_V2:
            return (1, b'', b'dn: subscribe requires protocol 2 (a '
                    b'persistent connection); v1 peers cannot '
                    b'receive pushed frames\n', {}, None)
        if not self.enabled():
            return (1, b'', b'dn: subscriptions disabled '
                    b'(DN_SUB_MAX=0)\n', {}, None)
        if self.server.draining:
            return (1, b'', b'dn: server is draining\n',
                    {'retryable': True}, None)
        doc = _group_doc(req)
        if doc['watch'] == 'query':
            if not doc.get('ds'):
                return (1, b'', b'dn: subscribe: missing "ds"\n',
                        {}, None)
            bad = sorted(k for k, v in (req.get('opts') or {}).items()
                         if v and k not in _ALLOWED_OPTS)
            if bad:
                return (1, b'', ('dn: subscribe: option(s) %s cannot '
                                 'ride a standing query\n'
                                 % ','.join('"%s"' % b for b in bad))
                        .encode(), {}, None)
            qc = mod_query.query_load(doc['queryconfig'] or {})
            if isinstance(qc, DNError):
                return (1, b'', ('dn: %s\n' % qc.message).encode(),
                        {}, None)
        with self._lock:
            if len(self._subs) >= self.conf['max']:
                return (1, b'', ('dn: subscription limit reached '
                                 '(DN_SUB_MAX=%d)\n'
                                 % self.conf['max']).encode(),
                        {'retryable': True,
                         'retry_after_ms': 1000}, None)
            key, kdigest = _group_key(doc)
            group = self._groups.get(key)
            fresh = group is None
            if fresh:
                group = Group(key, kdigest, doc)
                self._groups[key] = group
        if fresh:
            # seed from one ordinary query at the registration epoch,
            # under an admission slot — a subscribe is real work and
            # must respect the overload posture (busy/draining answer
            # retryably, exactly like a poll)
            try:
                with group.compute_lock:
                    self._recompute(group, seed=True)
                if self.server.router is not None:
                    # a seed scatter right after another process's
                    # publish can catch a peer inside its stat-TTL
                    # window exactly like a sweep scatter can —
                    # confirm it too
                    group.confirm_at = (time.monotonic() +
                                        self._confirm_delay())
            except (mod_admission.BusyError,
                    mod_admission.DrainingError,
                    mod_admission.OverloadedError) as e:
                with self._lock:
                    if not group.subs:
                        self._groups.pop(key, None)
                return (1, b'', ('dn: %s\n' % e.message).encode(),
                        {'retryable': True,
                         'retry_after_ms':
                         getattr(e, 'retry_after_ms', None)}, None)
            except DNError as e:
                with self._lock:
                    if not group.subs:
                        self._groups.pop(key, None)
                return (1, b'', ('dn: %s\n' % e.message).encode(),
                        {}, None)
        with self._lock:
            sid = 's%d' % self._next
            self._next += 1
            sub = Subscription(sid, conn, group)
            group.subs.add(sub)
            self._subs[sid] = sub
            self._by_conn.setdefault(conn.fd, set()).add(sid)
            self._counters['registered'] += 1
        # resume: a token whose payload digest matches the group's
        # CURRENT bytes means the reconnecting client already holds
        # the answer — seed nothing, start deltas from its base
        resumed = False
        token = req.get('resume')
        if isinstance(token, dict) and \
                token.get('k') == group.kdigest and \
                token.get('d') == group.digest and \
                group.payload is not None:
            sub.last_payload = group.payload
            resumed = True
            self._bump('resumed')
        self.server.loop.pin(conn)
        self._set_gauges()
        if obs_events.enabled():
            obs_events.emit('subscribe.register', sub=sid,
                            watch=doc['watch'],
                            ds=doc.get('ds'), peer=sub.peer,
                            resumed=resumed)
        body = json.dumps({
            'sub': sid, 'epoch': group.epoch, 'seq': 0,
            'resumed': resumed,
            'token': self._token(group, 0),
        }, sort_keys=True) + '\n'
        return 0, body.encode(), b'', {}, sub

    def activate(self, sub):
        """Queue the seed frame (the registration response is already
        on the wire ahead of it).  A resumed subscriber needs none —
        its next frame comes with the next change."""
        if sub.last_payload is not None:
            return
        group = sub.group
        with self._lock:
            if sub.sid not in self._subs:
                return
            if group.payload is None:
                sub.dirty = True
                return
            self._send_frame(sub, group, force_full=True)

    def _token(self, group, seq):
        return {'k': group.kdigest, 'seq': seq,
                'epoch': group.epoch, 'd': group.digest}

    # -- acks / unsubscribe (worker threads) ------------------------------

    def ack(self, req):
        """One `sub_ack` control frame: advance the subscriber's
        acked watermark; a lagging subscriber whose window reopens
        gets its coalesced catch-up FULL frame here.  Duplicate and
        reordered acks are idempotent — the watermark only moves
        forward."""
        sid = req.get('sub')
        seq = req.get('seq')
        with self._lock:
            sub = self._subs.get(sid)
            if sub is None:
                return (1, b'', ('dn: unknown subscription %r\n'
                                 % (sid,)).encode(), {})
            if not isinstance(seq, int) or isinstance(seq, bool) or \
                    seq < 1 or seq > sub.seq:
                return (1, b'', ('dn: bad ack seq %r for "%s" '
                                 '(last sent %d)\n'
                                 % (seq, sid, sub.seq)).encode(), {})
            if seq <= sub.acked:
                self._counters['duplicate_acks'] += 1
                return 0, b'', b'', {}
            sub.acked = seq
            catch_up = (sub.dirty and
                        sub.seq - sub.acked <
                        self.conf['queue_depth'] and
                        sub.group.payload is not None)
            if catch_up:
                # degraded mode's exit: one full frame carrying the
                # CURRENT state, however many versions were skipped
                self._send_frame(sub, sub.group, force_full=True)
        return 0, b'', b'', {}

    def unsubscribe(self, req):
        sid = req.get('sub')
        with self._lock:
            sub = self._subs.get(sid)
            if sub is None:
                return (1, b'', ('dn: unknown subscription %r\n'
                                 % (sid,)).encode(), {})
            self._drop(sub, reason='unsubscribe')
        return 0, b'', b'', {}

    def _drop(self, sub, reason):
        """Caller holds the lock."""
        if self._subs.pop(sub.sid, None) is None:
            return
        sub.group.subs.discard(sub)
        sids = self._by_conn.get(sub.conn.fd)
        if sids is not None:
            sids.discard(sub.sid)
            if not sids:
                self._by_conn.pop(sub.conn.fd, None)
        if not sub.group.subs:
            # last rider gone: retire the group and its memo
            self._groups.pop(sub.group.key, None)
        self._counters['dropped'] += 1
        if not sub.conn.closed:
            self.server.loop.unpin(sub.conn)
        if obs_events.enabled():
            obs_events.emit('subscribe.drop', sub=sub.sid,
                            reason=reason, frames=sub.seq)
        self._set_gauges()

    def on_conn_close(self, conn):
        """Loop-thread callback: the subscriber died (EOF, reap,
        kill) — deregister everything it carried.  Quick dict
        surgery only."""
        with self._lock:
            sids = self._by_conn.pop(conn.fd, None)
            if not sids:
                return
            for sid in list(sids):
                sub = self._subs.get(sid)
                if sub is not None and sub.conn is conn:
                    self._drop(sub, reason='conn_closed')

    # -- dirty signals ----------------------------------------------------

    def _on_index_write(self, indexroot, shard_paths):
        """The in-process publish hook (builds, follow mini-batches,
        compaction, rollups): mark matching groups dirty and wake the
        pusher — the coalesce window starts now."""
        hit = False
        with self._lock:
            for group in self._groups.values():
                if group.doc['watch'] != 'query':
                    continue
                root = group.doc.get('_indexroot')
                if root and indexroot and \
                        os.path.normpath(root) == \
                        os.path.normpath(indexroot):
                    group.dirty = True
                    hit = True
        if hit:
            self._wake.set()

    # -- the pusher thread ------------------------------------------------

    def _run(self):
        period = self.conf['coalesce_ms'] / 1000.0
        while not self._stop.is_set():
            fired = self._wake.wait(period)
            if self._stop.is_set():
                return
            if fired:
                self._wake.clear()
                # the coalesce window: let the publish batch finish
                # landing, push once for all of it
                if self._stop.wait(period):
                    return
            try:
                self._sweep()
            except Exception as e:
                # the pusher must survive anything a recompute
                # throws: log, count, carry on — a wedged pusher
                # would silently freeze every dashboard
                self._bump('compute_errors')
                self.log.error('subscription sweep failed',
                               err=repr(e))

    def _sweep(self):
        with self._lock:
            groups = list(self._groups.values())
        now = time.monotonic()
        for group in groups:
            if self._stop.is_set():
                return
            signal = True
            if group.doc['watch'] == 'fleet':
                due = (now - group.last_compute) * 1000.0 >= \
                    group.doc['interval_ms']
                if not due:
                    continue
            else:
                signal = group.dirty or \
                    self._validators_changed(group)
                confirm = (group.confirm_at is not None and
                           now >= group.confirm_at)
                if not signal and not confirm:
                    self._flush_dirty_subs(group)
                    continue
                if not signal:
                    self._bump('reconfirms')
            with group.compute_lock:
                group.dirty = False
                try:
                    changed = self._recompute(group)
                except DNError as e:
                    # keep the last good payload; retry next tick
                    group.dirty = True
                    group.last_error = e.message
                    self._bump('compute_errors')
                    continue
                except Exception as e:
                    group.dirty = True
                    group.last_error = repr(e)
                    self._bump('compute_errors')
                    continue
            if group.doc['watch'] == 'query' and \
                    self.server.router is not None:
                # routed reconvergence: a scatter answered by a peer
                # PROCESS that did not see this write's hook can lag
                # by the peer's stat-TTL memo window (the poll path
                # self-heals by re-scattering every request; a
                # standing query scatters only when signalled).  One
                # confirming scatter after the window expires either
                # observes the settled bytes (unchanged -> converged,
                # stop) or pushes the newer state and re-arms
                if signal or changed:
                    group.confirm_at = now + self._confirm_delay()
                else:
                    group.confirm_at = None
            if changed:
                self._push_group(group)
            else:
                self._flush_dirty_subs(group)

    def _flush_dirty_subs(self, group):
        """Subscribers that missed a frame for a reason OTHER than
        their own lag (joined while the seed was still computing,
        shed once and acked quietly): hand them the current payload
        as soon as their window allows."""
        with self._lock:
            for sub in list(group.subs):
                if sub.dirty and not sub.conn.closed and \
                        group.payload is not None and \
                        sub.seq - sub.acked < self.conf['queue_depth']:
                    self._send_frame(sub, group, force_full=True)

    def _validators_changed(self, group):
        """Cross-process writers (a `dn follow` publishing from its
        own process) never fire OUR write hook; the tree validators
        — the same stat identities the query cache trusts — catch
        them at the coalesce cadence.  A detected change bumps the
        writer-invalidation epoch first, so the frame's epoch and a
        poll's epoch agree."""
        root = group.doc.get('_indexroot')
        if not root or group.validators is None:
            return group.validators is None
        current = mod_qcache.tree_validators(root)
        if current != group.validators:
            mod_iqmt.invalidate_index_tree(root)
            return True
        return False

    def _confirm_delay(self):
        """How long a routed group waits before its confirming
        scatter: past every peer process's stat-TTL memo window,
        plus a coalesce period of slack for the publish batch to
        finish landing."""
        return (mod_iqmt.stat_ttl_s() +
                self.conf['coalesce_ms'] / 1000.0 + 0.1)

    # -- recompute --------------------------------------------------------

    def _recompute(self, group, seed=False):
        """One incremental merge for the whole group, every
        subscriber's next frame.  Returns True when the rendered
        payload changed.  Raises DNError on a failed compute (the
        caller keeps the previous payload)."""
        if group.doc['watch'] == 'fleet':
            return self._recompute_fleet(group)
        return self._recompute_query(group, seed=seed)

    def _recompute_fleet(self, group):
        from . import fleet as mod_fleet
        doc = mod_fleet.fleet_doc(self.server,
                                  events_limit=group.doc['events'])
        payload = (json.dumps(doc, sort_keys=True, indent=2) +
                   '\n').encode()
        group.last_compute = time.monotonic()
        return self._install_payload(group, payload,
                                     mod_iqmt.cache_epoch())

    def _recompute_query(self, group, seed=False):
        from .. import datasource_for_name
        from . import server as mod_server
        t0 = time.monotonic()
        doc = group.doc
        backend = mod_config.ConfigBackendLocal(doc.get('config')
                                                or None)
        err, config = backend.load()
        if err is not None and not getattr(err, 'is_enoent', False):
            raise err
        ds = datasource_for_name(config, doc['ds'])
        if isinstance(ds, DNError):
            raise ds
        qc = mod_query.query_load(doc['queryconfig'] or {})
        if isinstance(qc, DNError):
            raise qc
        doc['_indexroot'] = getattr(ds, 'ds_indexpath', None)

        slot = lease = None
        if seed:
            lease = self.server._admit_resources('query', ds)
            try:
                slot = self.server.admission.acquire()
            except BaseException:
                lease.release()
                raise
        try:
            # capture the epoch BEFORE the walk (the qcache's
            # ordering): a write racing this recompute re-dirties
            # the group — via the hook or the validators — and the
            # next sweep reconverges; the frame's epoch is never
            # newer than its bytes
            epoch = mod_iqmt.cache_epoch()
            validators = mod_qcache.tree_validators(
                doc['_indexroot'])
            with self.server._tree_lock(ds, doc['ds']).read():
                if self.server.router is not None:
                    result = self._routed_result(ds, doc, qc)
                else:
                    result = self._incremental_result(group, ds, qc)
        finally:
            if slot is not None:
                slot.release()
            if lease is not None:
                lease.release()

        # render through the SAME output layer a poll uses — the
        # byte-identity contract is this line, not a comparison
        cap = mod_server._Capture()
        with mod_server.bound_stdio(cap):
            mod_cli = _cli()
            mod_cli.dn_output(qc, _OutOpts(doc.get('opts') or {}),
                              result, doc['ds'])
        payload, _ = cap.finish()
        group.validators = validators
        group.last_compute = time.monotonic()
        group.recomputes += 1
        self._bump('recomputes')
        obs_metrics.inc('sub_group_recomputes_total')
        obs_metrics.observe('sub_recompute_ms',
                            (time.monotonic() - t0) * 1000.0)
        return self._install_payload(group, payload, epoch)

    def _incremental_result(self, group, ds, qc):
        """The heart of the subsystem: re-enumerate the walk, fold
        only shards whose stat identity changed, replay the rest from
        the memo, merge in global find order.  Structurally
        byte-identical to a poll by the PR 8 partial-merge
        contract."""
        from ..aggr import Aggregator
        from ..datasource_file import ScanResult
        from ..vpipe import Pipeline

        doc = group.doc
        pipeline = Pipeline()
        root, timeformat, files = ds.index_query_paths(
            qc, doc['interval'], pipeline)
        idents = {}
        for p, st in files:
            try:
                idents[p] = (st.st_mtime_ns, st.st_size)
            except AttributeError:
                s = os.stat(p)
                idents[p] = (s.st_mtime_ns, s.st_size)
        paths = [p for p, st in files]
        paths, _ = mod_iqmt.prune_shards(paths, timeformat,
                                         qc.qc_after, qc.qc_before)
        from .. import integrity as mod_integrity
        if mod_integrity.verify_mode() != 'off':
            mod_integrity.check_missing(
                ds.ds_indexpath, paths,
                subdir=os.path.basename(root)
                if timeformat is not None else None,
                timeformat=timeformat, after_ms=qc.qc_after,
                before_ms=qc.qc_before)

        memo = group.memo
        changed = [p for p in paths
                   if p not in memo or memo[p][0] != idents[p]]
        reused = len(paths) - len(changed)
        fresh = {}
        state = {'i': 0}

        def on_items(items):
            path = changed[state['i']]
            state['i'] += 1
            fresh[path] = (idents[path], list(items))

        mod_iqmt.run_shard_queries(changed, qc,
                                   mod_iqmt.iq_threads(), on_items)
        # rebuild the memo from THIS walk's shard set: deleted and
        # compacted-away shards fall out here instead of leaking
        group.memo = {p: fresh[p] if p in fresh else memo[p]
                      for p in paths}
        self._bump('shards_folded', len(changed))
        self._bump('shards_reused', reused)
        obs_metrics.inc('sub_shards_folded_total', len(changed))
        obs_metrics.inc('sub_shards_reused_total', reused)

        index_list = pipeline.stage('Index List')
        aggr = Aggregator(qc, stage=pipeline.stage(
            'Index Result Aggregator'))
        for p in paths:
            items = group.memo[p][1]
            npts = len(items)
            if npts == 0:
                continue
            index_list.bump('ninputs', npts)
            index_list.bump('noutputs', npts)
            aggr.stage.bump('ninputs', npts)
            aggr.merge_key_items(items)
        index_list.bump_hidden('index shards queried', len(paths))
        return ScanResult(pipeline, points=aggr.points(), query=qc)

    def _routed_result(self, ds, doc, qc):
        """Cluster mode: the member's own walk only covers its
        partitions, so a standing query scatters like a poll does —
        still ONE scatter per publish batch for every subscriber of
        the group."""
        req = {'op': 'query', 'ds': doc['ds'],
               'config': doc.get('config'),
               'queryconfig': doc['queryconfig'],
               'interval': doc['interval']}
        result, missing = self.server.router.scatter(
            ds, doc['ds'], qc, doc['interval'], req)
        if missing:
            raise DNError('standing query degraded: partition(s) %s '
                          'unavailable'
                          % ','.join(str(p) for p in missing))
        return result

    def _install_payload(self, group, payload, epoch):
        digest = _payload_digest(payload)
        if group.payload is not None and digest == group.digest \
                and payload == group.payload:
            group.epoch = epoch
            return False
        group.payload = payload
        group.digest = digest
        group.epoch = epoch
        group.version += 1
        return True

    # -- pushing ----------------------------------------------------------

    def _push_group(self, group):
        with self._lock:
            subs = list(group.subs)
            for sub in subs:
                if sub.conn.closed:
                    continue
                self._send_frame(sub, group)

    def _send_frame(self, sub, group, force_full=False):
        """Caller holds the lock.  One frame for one subscriber —
        or a shed, if its unacked window is full (the frame is NOT
        queued; the catch-up full frame rides its next ack)."""
        pending = sub.seq - sub.acked
        if pending >= self.conf['queue_depth']:
            sub.sheds += 1
            sub.dirty = True
            self._counters['lagging_sheds'] += 1
            obs_metrics.inc('sub_lagging_sheds_total')
            if not sub.lagging:
                sub.lagging = True
                if obs_events.enabled():
                    obs_events.emit('subscribe.lagging', sub=sub.sid,
                                    pending=pending, peer=sub.peer)
            return
        payload = group.payload
        seq = sub.seq + 1
        kind = 'full'
        body = payload
        extra = {'token': self._token(group, seq),
                 'version': group.version}
        delta_pct = self.conf['delta_pct']
        if not force_full and not sub.lagging and delta_pct > 0 and \
                sub.last_payload is not None:
            off, keep, ins = mod_protocol.byte_delta(
                sub.last_payload, payload)
            if len(ins) * 100 <= len(payload) * delta_pct:
                kind = 'delta'
                body = ins
                extra['delta'] = {'off': off, 'keep': keep,
                                  'base_seq': sub.seq}
        frame = mod_protocol.encode_push(sub.sid, seq, group.epoch,
                                         kind, body, extra)
        try:
            mod_faults.fire('serve.push_torn')
        except mod_faults.FaultInjected:
            # a torn push frame: half the bytes then EOF — the
            # client must detect the cut stream and resume, never
            # hang or mis-splice
            self.server.loop.send(sub.conn,
                                  frame[:max(1, len(frame) // 2)],
                                  close_after=True)
            return
        self.server.loop.send(sub.conn, frame)
        sub.seq = seq
        sub.last_payload = payload
        sub.dirty = False
        sub.lagging = False
        if kind == 'delta':
            sub.frames_delta += 1
            self._counters['frames_delta'] += 1
            obs_metrics.inc('sub_frames_delta_total')
        else:
            sub.frames_full += 1
            self._counters['frames_full'] += 1
            obs_metrics.inc('sub_frames_full_total')
        self._counters['pushes'] += 1
        self._counters['push_bytes'] += len(frame)
        obs_metrics.inc('sub_pushes_total')
        obs_metrics.inc('sub_push_bytes_total', len(frame))

    # -- observability ----------------------------------------------------

    def stats_doc(self):
        with self._lock:
            groups = []
            for g in self._groups.values():
                groups.append({
                    'watch': g.doc['watch'],
                    'ds': g.doc.get('ds'),
                    'subscribers': len(g.subs),
                    'version': g.version,
                    'epoch': g.epoch,
                    'payload_bytes': len(g.payload)
                    if g.payload is not None else 0,
                    'memo_shards': len(g.memo),
                    'recomputes': g.recomputes,
                    'last_error': g.last_error,
                })
            subs = []
            for s in self._subs.values():
                subs.append({
                    'sub': s.sid, 'peer': s.peer,
                    'seq': s.seq, 'acked': s.acked,
                    'lagging': s.lagging,
                    'frames_full': s.frames_full,
                    'frames_delta': s.frames_delta,
                    'sheds': s.sheds,
                })
            return {
                'enabled': self.enabled(),
                'active': len(self._subs),
                'max': self.conf['max'],
                'coalesce_ms': self.conf['coalesce_ms'],
                'queue_depth': self.conf['queue_depth'],
                'delta_pct': self.conf['delta_pct'],
                'counters': dict(self._counters),
                'groups': groups,
                'subscribers': subs,
            }


def _cli():
    from .. import cli as mod_cli
    return mod_cli
