"""Partition handoff (shard streaming) and the rebalance planner.

When a pending epoch assigns a member shards it does not hold — a
fresh joiner, a widened replica set, or a partition moved toward load
— the member STREAMS those shards from their committed owners before
the epoch commits, so the cutover never serves a short shard set:

* Donor side: the `shard_manifest` op enumerates a member's shards
  for the requested committed partitions — every interval tree
  (by_hour / by_day / all), journal/tmp/quarantine litter filtered
  exactly like a query walk — as (relpath, size, crc32) triples; the
  `shard_fetch` op returns one shard's raw bytes (tree read-locked,
  so a concurrent build can never hand out a half-written shard).
  Both ops are epoch-gated like query partials.
* Joiner side: HandoffPuller plans in SHARD terms, not partition ids
  (partition boundaries renumber freely across epochs — 3 partitions
  may become 5): the global shard list is the union of committed
  owners' manifests, the needed set is the shards the PENDING map
  assigns to this member that are not already present byte-identical
  (size + crc match — a shared-filesystem deployment streams
  nothing), and each fetch rides the pooled multiplexed connection
  (serve/pool.py) with failover across donor replicas.  Fetched
  bytes land as journal-style tmps (`<shard>.<pid>.<seq>` — readers
  filter them, and the crash-recovery sweep quarantines them if we
  die) and rename into place only after the crc verifies.

A SIGKILLed joiner loses nothing but its own progress: the committed
map is untouched, already-renamed shards are complete and verified,
and a restart re-pulls idempotently (present-and-identical shards are
skipped).  `handoff_ready` flips only when every needed shard landed;
until then the member rejects partials for the affected partitions
retryably (server.py) — degraded never silently short.

The planner (propose_moves) turns per-member load — query_partial
counts and the PR 7 latency histograms out of /stats — into a bounded
set of partition moves from the hottest member toward the coldest,
emitted as a new topology document for begin_transition.
"""

import json
import os
import threading
import zlib

from ..errors import DNError
from .. import config as mod_config
from .. import faults as mod_faults
from .. import index_journal as mod_journal
from .. import integrity as mod_integrity
from ..obs import events as obs_events
from ..obs import metrics as obs_metrics

# shards larger than this stream in bounded range-fetches instead of
# one buffered response: the protocol buffers whole payloads on both
# sides, and a multi-GB sqlite shard must not drive the donor (or
# joiner) to OOM mid-resize
FETCH_CHUNK_BYTES = 8 << 20

# (size, crc32) of a file, streamed — now owned by integrity.py (the
# manifest triples and the integrity catalog must agree by
# construction); the old name stays for handoff callers
file_crc = mod_integrity.file_crc


def _interval_trees(ds):
    """[(interval, root, timeformat)] for the datasource's index
    trees (the same roots index_find_params hands a query)."""
    out = []
    for interval in ('hour', 'day', 'all'):
        params = ds.index_find_params(interval, None, None)
        if isinstance(params, DNError):
            continue
        out.append((interval, params[0], params[1]))
    return out


def iter_shards(ds):
    """Every shard file in the datasource's index trees as
    (relpath, abspath, timeformat), litter filtered, in sorted
    order (deterministic across members of a shared tree)."""
    indexroot = ds.ds_indexpath
    for interval, root, timeformat in _interval_trees(ds):
        if os.path.isfile(root):
            # the `all` interval may be a single shard file
            if not mod_journal.is_index_litter(root):
                yield (os.path.relpath(root, indexroot), root,
                       timeformat)
            continue
        if not os.path.isdir(root):
            continue
        for r, dirs, names in os.walk(root):
            dirs[:] = sorted(d for d in dirs
                             if not mod_journal.is_index_litter(d))
            for name in sorted(names):
                if mod_journal.is_index_litter(name):
                    continue
                path = os.path.join(r, name)
                yield (os.path.relpath(path, indexroot), path,
                       timeformat)


def shard_manifest(ds, topology, partition_ids):
    """The donor-side manifest: [[relpath, size, crc32], ...] for
    every shard of `partition_ids` under `topology`'s assignment.
    Fires the handoff.manifest fault seam."""
    mod_faults.fire('handoff.manifest')
    want = set(partition_ids)
    out = []
    for rel, path, timeformat in iter_shards(ds):
        if topology.partition_of(path, timeformat) not in want:
            continue
        try:
            size, crc = file_crc(path)
        except OSError:
            # raced a concurrent retire: a shard that vanished is not
            # ours to offer
            continue
        out.append([rel, size, crc])
    return out


def safe_rel(indexroot, rel):
    """Resolve a manifest relpath under the index root, refusing
    escapes and litter names — the donor must never hand out a file a
    query walk would not serve."""
    if not isinstance(rel, str) or not rel or rel.startswith('/'):
        raise DNError('bad shard relpath: %r' % (rel,))
    norm = os.path.normpath(rel)
    if norm.startswith('..') or os.path.isabs(norm):
        raise DNError('bad shard relpath: %r' % (rel,))
    if mod_journal.is_index_litter(norm):
        raise DNError('shard relpath names build litter: %r' % (rel,))
    return os.path.join(indexroot, norm)


def read_shard(ds, rel, offset=0, length=None):
    """Donor-side shard read for the `shard_fetch` op: the raw bytes
    of one shard file, or the `[offset, offset+length)` range of it
    (large shards stream in bounded chunks).  The caller holds the
    tree read lock."""
    path = safe_rel(ds.ds_indexpath, rel)
    try:
        with open(path, 'rb') as f:
            if offset:
                f.seek(offset)
            return f.read(length) if length is not None else f.read()
    except OSError as e:
        raise DNError('shard "%s" unreadable' % rel,
                      cause=DNError(str(e)))


def _shard_timeformats(ds):
    """{interval-tree subdir: timeformat} for mapping a manifest
    relpath back to its assignment rule."""
    out = {}
    for interval, root, timeformat in _interval_trees(ds):
        out[os.path.basename(root)] = timeformat
    return out


# -- the shared fetch-and-land path -----------------------------------------
#
# One verified way for bytes to enter a tree over the wire: bounded
# range fetches off the pooled connection, assembled into a
# journal-style tmp (readers filter it, the recovery sweep
# quarantines it if we die), crc-checked against the expected
# (size, crc), fsynced, atomically renamed, and recorded in the
# integrity catalog.  The handoff joiner (HandoffPuller) and the
# self-healing repair path (serve/scrub.py) both ride it.

def fetch_shard_range(endpoint, dsname, cfg_path, epoch, rel,
                      offset, length, timeout_s):
    """One `shard_fetch` exchange; returns the raw bytes or raises
    DNError/OSError."""
    from . import client as mod_client
    req = {'op': 'shard_fetch', 'ds': dsname, 'config': cfg_path,
           'epoch': epoch, 'rel': rel}
    if length is not None:
        req['offset'] = offset
        req['length'] = length
    rc, header, out, err = mod_client.request_bytes(
        endpoint, req, timeout_s=timeout_s, retry=True)
    if rc != 0:
        raise DNError(err.decode('utf-8', 'replace').strip() or
                      'shard_fetch failed')
    return out


def land_shard(endpoint, dsname, cfg_path, epoch, rel, size, crc,
               dest, timeout_s, indexroot=None):
    """Stream one shard from a donor into place: bounded range
    fetches (FETCH_CHUNK_BYTES at a time — neither side ever buffers
    a whole multi-GB shard) appended to a journal-style tmp, crc
    verified over the assembled bytes, fsync, atomic rename, catalog
    entry landed (when `indexroot` is given) so the fetched copy
    verifies like a locally-published one."""
    d = os.path.dirname(dest)
    if d and not os.path.isdir(d):
        os.makedirs(d, exist_ok=True)
    tmp = dest + '.' + mod_journal.new_build_id()
    try:
        got_crc = 0
        with open(tmp, 'wb') as f:
            if size <= FETCH_CHUNK_BYTES:
                data = fetch_shard_range(endpoint, dsname, cfg_path,
                                         epoch, rel, 0, None,
                                         timeout_s)
                if len(data) != size:
                    raise DNError(
                        'shard "%s": %d bytes, expected %d '
                        '(donor tree changed?)'
                        % (rel, len(data), size))
                got_crc = zlib.crc32(data)
                f.write(data)
            else:
                written = 0
                while written < size:
                    want = min(FETCH_CHUNK_BYTES, size - written)
                    data = fetch_shard_range(
                        endpoint, dsname, cfg_path, epoch, rel,
                        written, want, timeout_s)
                    if len(data) != want:
                        raise DNError(
                            'shard "%s": short range at %d '
                            '(donor tree changed?)' % (rel, written))
                    got_crc = zlib.crc32(data, got_crc)
                    f.write(data)
                    written += want
            f.flush()
            os.fsync(f.fileno())
        if (got_crc & 0xffffffff) != crc:
            raise DNError(
                'shard "%s": bytes do not match the expected crc '
                '(donor tree changed?)' % rel)
        mod_faults.fire('handoff.apply', torn_path=tmp)
        os.rename(tmp, dest)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if indexroot is not None:
        mod_integrity.update_catalog(
            indexroot,
            add={mod_integrity.shard_rel(indexroot, dest):
                 (size, crc)})


class HandoffPuller(object):
    """The joiner-side shard streamer for one pending epoch.

    Runs on its own thread; `ready` flips True only when every shard
    the pending map assigns to this member is present and verified.
    status() feeds the /stats `topology` section and the `topology`
    op the coordinator polls for commit readiness."""

    def __init__(self, committed, pending, member, topo_conf=None,
                 log=None, governor=None):
        if topo_conf is None:
            topo_conf = mod_config.topo_config()
        if isinstance(topo_conf, DNError):
            raise topo_conf
        self.committed = committed
        self.pending = pending
        self.member = member
        self.target_epoch = pending.epoch
        self.conf = topo_conf
        self.log = log
        # resource governance (resources.py): handoff fetches are
        # background disk consumers — low pressure PAUSES the pull
        # (resumes when space frees), critical fails it with the
        # clean retryable disk_full error (the topology watcher's
        # retry_failed_handoff restarts it every poll, so recovery
        # is automatic there too)
        self.governor = governor
        self.ready = False
        self.failed = False
        self.error = None
        # partitions whose shard set may still be incomplete: ALL of
        # this member's pending partitions until the plan proves
        # otherwise (server.py rejects partials for these, retryably,
        # until ready)
        self.affected_pids = set(pending.partitions_of(member))
        self._lock = threading.Lock()
        self.counters = {'shards_needed': 0, 'shards_streamed': 0,
                         'bytes_streamed': 0, 'shards_skipped': 0,
                         'fetch_failures': 0, 'manifest_failures': 0}
        self._stale = threading.Event()
        self._done = threading.Event()
        self._thread = None

    # -- lifecycle --------------------------------------------------------

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name='dn-handoff-pull',
                daemon=True)
            self._thread.start()
        return self

    def stop(self):
        """Mark the pull stale (superseded epoch / server drain): the
        thread exits at the next shard boundary."""
        self._stale.set()

    def wait(self, timeout_s=None):
        return self._done.wait(timeout_s)

    def _bump(self, name, n=1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def status(self):
        with self._lock:
            counters = dict(self.counters)
        return {
            'epoch': self.target_epoch,
            'ready': self.ready,
            'failed': self.failed,
            'error': self.error,
            'partitions_moving': sorted(self.affected_pids),
            'counters': counters,
        }

    # -- the pull ---------------------------------------------------------

    def _run(self):
        try:
            missing = self._pull()
            if self._stale.is_set():
                return
            if missing:
                self.failed = True
                self.error = ('%d shard(s) could not be streamed '
                              '(e.g. %s)'
                              % (len(missing), missing[0]))
            else:
                self.ready = True
            obs_metrics.set_gauge('handoff_ready',
                                  1.0 if self.ready else 0.0)
            obs_events.emit(
                'handoff.ready' if self.ready else 'handoff.failed',
                epoch=self.target_epoch, error=self.error,
                partitions=sorted(self.affected_pids))
        except Exception as e:
            self.failed = True
            self.error = str(e)
            obs_events.emit('handoff.failed', epoch=self.target_epoch,
                            error=self.error)
            if self.log is not None:
                self.log.error('handoff pull failed', err=repr(e))
        finally:
            self._done.set()

    def _datasources(self):
        """Every file datasource with an index tree under this
        member's config (the topology's per-member config when
        declared, the process default otherwise)."""
        from .. import datasource_for_name
        cfg_path = self.pending.member_config(self.member)
        backend = mod_config.ConfigBackendLocal(cfg_path or None)
        err, config = backend.load()
        if err is not None and not getattr(err, 'is_enoent', False):
            raise err
        out = []
        for dsname, dsdoc in config.datasource_list():
            idx = (dsdoc.get('ds_backend_config') or {}) \
                .get('indexPath')
            if not idx:
                continue
            ds = datasource_for_name(config, dsname)
            if isinstance(ds, DNError):
                continue
            out.append((dsname, ds, backend.cbl_path))
        return out

    def _request(self, endpoint, req, timeout_s):
        from . import client as mod_client
        return mod_client.request_bytes(endpoint, req,
                                        timeout_s=timeout_s,
                                        retry=True)

    def _pull(self):
        """Stream every needed shard; returns the relpaths that could
        not be fetched (empty = ready)."""
        timeout_s = self.conf['handoff_timeout_s']
        retries = self.conf['handoff_retries']
        missing = []
        affected = set()
        for dsname, ds, cfg_path in self._datasources():
            if self._stale.is_set():
                return missing
            # 1. the global shard list, from committed owners
            manifest = {}      # rel -> (size, crc, [donor names])
            for pid in self.committed.partition_ids():
                if self.member in self.committed.replicas(pid):
                    # we are ourselves a committed owner of this
                    # partition: our tree already holds its complete
                    # shard set — enumerate locally instead of
                    # depending on another donor surviving
                    got = None
                    for attempt in range(retries + 1):
                        try:
                            got = shard_manifest(ds,
                                                 self.committed,
                                                 [pid])
                            break
                        except DNError:
                            self._bump('manifest_failures')
                    if got is None:
                        missing.append('%s: partition %d local '
                                       'manifest failed'
                                       % (dsname, pid))
                        continue
                    for rel, size, crc in got:
                        manifest[rel] = (size, crc, [])
                    continue
                donors = [m for m in self.committed.replicas(pid)
                          if m != self.member]
                got = None
                attempts = max(1, retries + 1) * \
                    max(1, len(donors))
                for attempt in range(attempts):
                    donor = donors[attempt % len(donors)]
                    try:
                        rc, header, out, err = self._request(
                            self.committed.endpoint(donor),
                            {'op': 'shard_manifest', 'ds': dsname,
                             'config': cfg_path,
                             'epoch': self.committed.epoch,
                             'partitions': [pid]}, timeout_s)
                        if rc == 0:
                            got = json.loads(
                                out.decode('utf-8'))['shards']
                            break
                    except (OSError, ValueError, KeyError,
                            DNError):
                        pass
                    self._bump('manifest_failures')
                if got is None:
                    # no committed owner would tell us what this
                    # partition holds: completeness is UNPROVABLE,
                    # so the pull must not report ready — an empty
                    # answer here silently dropped shards
                    missing.append('%s: partition %d manifest '
                                   'unavailable' % (dsname, pid))
                    with self._lock:
                        self.affected_pids |= set(
                            self.pending.partitions_of(self.member))
                    continue
                for rel, size, crc in got:
                    # every committed replica of the pid can donate
                    # this shard: the fetch fails over across them
                    manifest[rel] = (size, crc, list(donors))
            # 2. the needed set, in PENDING-map terms
            my_pids = set(self.pending.partitions_of(self.member))
            fmt_by_dir = _shard_timeformats(ds)
            needed = []
            for rel in sorted(manifest):
                size, crc, donors = manifest[rel]
                timeformat = fmt_by_dir.get(
                    rel.split(os.sep)[0] if os.sep in rel else rel)
                pid = self.pending.partition_of(rel, timeformat)
                if pid not in my_pids:
                    continue
                dest = safe_rel(ds.ds_indexpath, rel)
                try:
                    have_size, have_crc = file_crc(dest)
                    if have_size == size and have_crc == crc:
                        self._bump('shards_skipped')
                        continue
                except OSError:
                    pass
                affected.add(pid)
                needed.append((rel, size, crc, donors, dest))
            self._bump('shards_needed', len(needed))
            # 3. stream
            streamed_any = False
            for rel, size, crc, donors, dest in needed:
                if self._stale.is_set():
                    return missing
                self._wait_writable()
                if self._fetch_shard(dsname, cfg_path, rel, size,
                                     crc, donors, dest,
                                     timeout_s, retries,
                                     ds.ds_indexpath):
                    streamed_any = True
                else:
                    missing.append(rel)
            if streamed_any:
                # resident readers must re-walk: renamed-in shards
                # change the tree under any cached find memo
                from .. import index_query_mt as mod_iqmt
                mod_iqmt.invalidate_index_tree(ds.ds_indexpath)
        # narrow the reject window to partitions that actually had
        # shards in motion (a member whose assignment is unchanged
        # must not reject its own traffic while others hand off) —
        # but only when the pull proved complete: an unprovable pull
        # keeps the conservative full set
        with self._lock:
            if not missing:
                self.affected_pids = affected
        return missing

    def _wait_writable(self):
        """The per-shard resource gate: hold the pull while the
        governor reports low pressure (stop/stale still interrupt
        instantly), and fail it cleanly — retryable disk_full — once
        the disk goes critical: streaming more shards onto a full
        disk can only make the incident worse."""
        gov = self.governor
        if gov is None:
            return
        from .. import resources as mod_resources
        paused = False
        while not self._stale.is_set() and gov.mode() == 'low':
            if not paused:
                paused = True
                obs_events.emit_burst('resource.paused',
                                      key='handoff',
                                      component='handoff')
                obs_metrics.inc('resource_paused_total',
                                component='handoff')
                if self.log is not None:
                    self.log.info('handoff pull paused: disk low')
            self._stale.wait(0.5)
        if not self._stale.is_set() and gov.is_read_only():
            raise mod_resources.disk_full_error('handoff pull')

    def _fetch_shard(self, dsname, cfg_path, rel, size, crc, donors,
                     dest, timeout_s, retries, indexroot):
        """One shard: fetch bytes from a donor (failing over), verify
        size+crc, land via the shared land_shard path (journal-style
        tmp + crc + rename + catalog entry).  Returns True on
        success."""
        if not donors:
            # locally-enumerated shard that somehow went missing
            # before the present-check: nobody to fetch it from
            self._bump('fetch_failures')
            return False
        attempts = max(1, retries + 1) * max(1, len(donors))
        for attempt in range(attempts):
            donor = donors[attempt % len(donors)]
            try:
                mod_faults.fire('handoff.fetch')
                land_shard(self.committed.endpoint(donor), dsname,
                           cfg_path, self.committed.epoch, rel,
                           size, crc, dest, timeout_s,
                           indexroot=indexroot)
                self._bump('shards_streamed')
                self._bump('bytes_streamed', size)
                obs_metrics.inc('handoff_shards_streamed_total')
                obs_metrics.inc('handoff_bytes_streamed_total',
                                size)
                return True
            except (OSError, ValueError, DNError) as e:
                self._bump('fetch_failures')
                if self.log is not None:
                    self.log.warn('shard fetch failed', rel=rel,
                                  donor=donor, err=str(e))
        return False


# -- the rebalance planner --------------------------------------------------

def member_load_score(stats_doc):
    """One member's load score from its /stats document: served
    partial count (the partition work actually done) plus the live
    queue pressure, tie-broken by the observed per-op latency
    (PR 7 histograms)."""
    req = stats_doc.get('requests') or {}
    by_op = req.get('by_op') or {}
    partials = by_op.get('query_partial', 0) + by_op.get('query', 0)
    depth = stats_doc.get('inflight') or {}
    pressure = (depth.get('active', 0) or 0) + \
        (depth.get('queued', 0) or 0)
    p95 = 0.0
    hists = (stats_doc.get('metrics') or {}).get('histograms') or {}
    for name, ent in hists.items():
        if name.startswith('serve_op_latency_ms') and \
                'query' in name:
            p95 = max(p95, ent.get('p90') or 0.0)
    return float(partials + 10 * pressure) + p95 / 1000.0


def collect_loads(topology, timeout_s=5.0):
    """{member: load score} from each member's /stats (unreachable
    members score None — the planner never moves TOWARD a member it
    cannot see)."""
    from . import client as mod_client
    loads = {}
    for name in topology.member_names():
        try:
            doc = mod_client.stats(topology.endpoint(name),
                                   timeout_s=timeout_s)
            loads[name] = member_load_score(doc)
        except (OSError, ValueError, DNError):
            loads[name] = None
    return loads


def propose_moves(topology, loads, max_moves=None, ratio=1.5):
    """Propose up to `max_moves` partition moves from the
    hottest-loaded member toward the coldest: in each step the
    hottest member's lowest-id primary partition that the coldest
    does not replicate swaps that replica slot.  Deterministic for a
    given (topology, loads).  Returns (new_doc_or_None, decisions):
    None when the spread is already within `ratio` (or nothing can
    move)."""
    if max_moves is None:
        conf = mod_config.topo_config()
        max_moves = 2 if isinstance(conf, DNError) \
            else conf['max_moves']
    doc = topology.doc()
    known = {m: s for m, s in loads.items()
             if s is not None and m in doc['members']}
    if len(known) < 2:
        return None, []
    work = dict(known)
    decisions = []
    for _ in range(max_moves):
        hot = max(sorted(work), key=lambda m: work[m])
        cold = min(sorted(work), key=lambda m: work[m])
        if work[hot] <= max(1.0, work[cold] * ratio):
            break
        moved = None
        for p in doc['partitions']:
            replicas = p['replicas']
            if replicas and replicas[0] == hot and \
                    cold not in replicas:
                moved = p
                break
        if moved is None:
            # the hot member fronts nothing movable: try any replica
            # slot it holds that the cold member does not
            for p in doc['partitions']:
                if hot in p['replicas'] and \
                        cold not in p['replicas']:
                    moved = p
                    break
        if moved is None:
            break
        idx = moved['replicas'].index(hot)
        moved['replicas'][idx] = cold
        decisions.append({'partition': moved['id'], 'from': hot,
                          'to': cold,
                          'load_from': round(work[hot], 3),
                          'load_to': round(work[cold], 3)})
        shift = (work[hot] - work[cold]) / 2.0
        work[hot] -= shift
        work[cold] += shift
        obs_metrics.inc('rebalance_moves_proposed_total')
    if not decisions:
        return None, []
    doc['epoch'] = topology.epoch + 1
    return doc, decisions
