"""Admission control, per-tenant fairness, per-request deadlines, and
request coalescing for `dn serve`.

Four mechanisms keep a resident server healthy under concurrent load,
in the order a request meets them:

* Coalescing (`Coalescer`): identical in-flight computations — same
  datasource, same query shape, same config identity — share ONE
  execution.  The first request in becomes the leader and computes;
  followers attach and wait for the leader's result (StreamBox-HBM's
  target-latency batching of concurrent pipeline work, applied to the
  serving tier).  Compatible requests that differ only in OUTPUT
  options (--raw vs --points vs pretty vs --counters) coalesce too:
  the compute key deliberately excludes formatting, and the server
  demuxes one shared ScanResult through each request's own output
  path.  Because the shared run goes through the default stacked
  cross-shard execution (index_query_stack), N concurrent index
  queries over the same tree cost one stacked aggregation.

* Per-tenant admission (`Admission`): at most `max_inflight`
  executions run at once; up to `queue_depth` more may wait — but the
  waiting room is now PER TENANT (tenants identified by the request's
  `tenant` field, defaulting to the connection's peer identity), each
  tenant bounded by `tenant_quota` queued requests and dequeued by
  WEIGHTED FAIR scheduling (stride scheduling over configured
  weights): a dashboard flooding one tenant's queue saturates its own
  quota and is rejected 429-style, while every other tenant's
  requests keep being admitted in weight proportion.  Beyond the
  global queue depth (or the tenant's quota) the request fails FAST
  with a retryable BusyError carrying `retry_after_ms` derived from
  the observed service time, instead of joining an unbounded convoy.
  Coalesced followers do not consume slots — attaching to an
  in-flight execution is the cheap path the whole design rewards.

* Load shedding (`OverloadedError`): a request whose propagated
  deadline cannot be met — the remaining budget is smaller than the
  observed typical service time, or the deadline expires while still
  queued — is shed EARLY with a clean retryable error carrying
  `retry_after_ms`.  Shed and expired work never occupies an
  execution slot (StreamBox-HBM's target-latency discipline: work
  that will miss its latency target is not worth starting).

* Deadlines: each request runs under `DN_SERVE_DEADLINE_MS` (or its
  own `deadline_ms`) on a reaper-armored thread
  (device_scan.run_with_deadline) — a wedged device op or a
  pathological query costs the client a bounded wait and a DNError,
  never a hung connection.  A coalesced follower shares its leader's
  fate: if the leader's execution times out, every attached request
  reports the deadline error.
"""

import json
import threading
import time
from collections import deque
from contextlib import contextmanager

from ..errors import DNError
from .. import faults as mod_faults
from ..obs import events as obs_events
from ..obs import metrics as obs_metrics


class BusyError(DNError):
    """Queue-full fast rejection (the 429 analog).  Retryable: the
    client's backoff loop may try again, after `retry_after_ms` when
    the server derived one from observed service time."""

    def __init__(self, message, retry_after_ms=None, cause=None):
        super(BusyError, self).__init__(message, cause=cause)
        self.retry_after_ms = retry_after_ms


class OverloadedError(BusyError):
    """Deadline-aware load shed (the 503 analog): the request's
    remaining deadline budget cannot cover the observed service time,
    so it is rejected EARLY — before occupying an execution slot —
    with a retry hint.  Subclasses BusyError so every existing
    retryable-rejection contract applies unchanged."""


class DeadlineError(DNError):
    """Per-request deadline expiry (the 504 analog)."""


class DrainingError(DNError):
    """The server is draining (SIGTERM/stop): queued-but-unadmitted
    requests get this clean, retryable rejection instead of a
    connection reset when the process exits.  A retrying client (or
    the scatter-gather router) re-sends to the replacement server."""


class Slot(object):
    """One admitted execution slot.  release() is IDEMPOTENT: a
    deadline-expired request's reaper frees the slot immediately while
    the abandoned job thread's own finally releases again when (if)
    the wedged operation eventually finishes — only the first call
    counts, so accounting never goes negative and a permanently
    wedged op cannot pin a slot forever."""

    __slots__ = ('_admission', '_released')

    def __init__(self, admission):
        self._admission = admission
        self._released = False

    def release(self):
        self._admission._release(self)


class _Ticket(object):
    """One queued waiter: granted by the fair scheduler, woken via the
    shared condition."""

    __slots__ = ('tenant', 'granted', 'cancelled')

    def __init__(self, tenant):
        self.tenant = tenant
        self.granted = False
        self.cancelled = False


class _Tenant(object):
    """Per-tenant admission state: the FIFO of waiting tickets, the
    stride-scheduling pass value, and fairness accounting."""

    __slots__ = ('name', 'weight', 'waiting', 'vpass', 'counters')

    def __init__(self, name, weight):
        self.name = name
        self.weight = max(1, weight)
        self.waiting = deque()
        self.vpass = 0.0
        self.counters = {'requests': 0, 'admitted': 0,
                         'rejected_busy': 0, 'shed_overload': 0,
                         'completed': 0}


_DEFAULT_TENANT = 'default'

# tenants default to peer identity, so a long-lived TCP server sees
# an unbounded stream of them: the table is pruned (idle entries
# evicted, counters aggregated) past this size
_TENANT_TABLE_CAP = 4096


class Admission(object):
    """Bounded execution slots with per-tenant bounded waiting rooms
    and weighted-fair dequeue.  The legacy two-argument constructor
    (global slots + one waiting room) still works: with no tenant
    quota/weights configured every caller lands in one default tenant
    and behaves exactly like the PR 5 gate."""

    def __init__(self, max_inflight, queue_depth, tenant_quota=0,
                 tenant_weights=None, tenant_default_weight=1):
        self.max_inflight = max_inflight
        self.queue_depth = queue_depth
        # 0 = no per-tenant cap (the global queue_depth still binds)
        self.tenant_quota = tenant_quota
        self.tenant_weights = dict(tenant_weights or {})
        self.tenant_default_weight = max(1, tenant_default_weight)
        self._cond = threading.Condition()
        self._tenants = {}
        # names of tenants with non-empty waiting queues: the fair
        # scheduler and the no-barging fast path scan THIS, not the
        # whole ever-seen tenant table
        self._active = set()
        # the scheduler's global virtual time: the pass value of the
        # last granted tenant.  Tenants joining (or REJOINING)
        # contention clamp to it, so a pass accumulated in a past
        # flood — or a zero pass minted during a lull — can never buy
        # starvation-length runs against the other side
        self._vtime = 0.0
        self._evicted = {}
        self._evicted_n = 0
        self._inflight = 0
        self._queued = 0
        self._draining = False
        # observed service time (EWMA, ms): the retry_after_ms and
        # early-shed estimate.  None until the first completion.
        self._service_ewma_ms = None
        self._shed_overload = 0
        self._shed_expired = 0

    # -- tenants -----------------------------------------------------------

    def _tenant(self, name):
        # call with self._cond held
        name = name or _DEFAULT_TENANT
        t = self._tenants.get(name)
        if t is None:
            weight = self.tenant_weights.get(
                name, self.tenant_default_weight)
            t = _Tenant(name, weight)
            # a newcomer must not replay history: start at the
            # scheduler's virtual time so it gets its fair share
            # from NOW, not a catch-up burst
            t.vpass = self._vtime
            self._tenants[name] = t
            if len(self._tenants) > _TENANT_TABLE_CAP:
                self._prune(keep=name)
        return t

    def _prune(self, keep=None):
        # call with _cond held: evict idle tenants (no queued work),
        # aggregating their counters so totals stay honest
        for name in [n for n, x in self._tenants.items()
                     if not x.waiting and n != keep]:
            ev = self._tenants.pop(name)
            self._active.discard(name)
            for k, v in ev.counters.items():
                self._evicted[k] = self._evicted.get(k, 0) + v
            self._evicted_n += 1

    def _pick_next(self):
        """The weighted-fair dequeue (call with _cond held): among
        tenants with waiters, grant the one with the smallest pass
        value, then advance its pass by 1/weight — a weight-3 tenant
        is granted 3x as often as a weight-1 tenant under contention.
        Returns the granted _Ticket or None."""
        best = None
        for name in self._active:
            t = self._tenants[name]
            if best is None or t.vpass < best.vpass:
                best = t
        if best is None:
            return None
        ticket = best.waiting.popleft()
        if not best.waiting:
            self._active.discard(best.name)
        self._vtime = best.vpass
        best.vpass += 1.0 / best.weight
        ticket.granted = True
        return ticket

    # -- service-time estimate / retry hints -------------------------------

    def note_service_ms(self, ms):
        """Feed the observed-service-time EWMA (one sample per
        completed data execution); the source of retry_after_ms and
        the early-shed estimate."""
        with self._cond:
            if self._service_ewma_ms is None:
                self._service_ewma_ms = float(ms)
            else:
                self._service_ewma_ms += \
                    0.2 * (float(ms) - self._service_ewma_ms)

    def _est_service_ms(self):
        # call with _cond held; a cold server guesses 100ms
        return self._service_ewma_ms \
            if self._service_ewma_ms is not None else 100.0

    def _retry_after_ms(self):
        """An honest retry hint: roughly when a freed slot could take
        new work — observed service time scaled by the queue's depth
        relative to capacity (call with _cond held)."""
        est = self._est_service_ms()
        load = (self._queued + 1.0) / max(1, self.max_inflight)
        return int(min(30000.0, max(25.0, est * load)))

    def retry_after_ms(self):
        with self._cond:
            return self._retry_after_ms()

    # -- lifecycle ---------------------------------------------------------

    def shutdown(self):
        """Begin draining: every queued waiter (and every future
        acquire) raises DrainingError instead of waiting for a slot —
        in-flight executions are unaffected and finish normally."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def _release(self, slot):
        with self._cond:
            if slot._released:
                return
            slot._released = True
            self._inflight -= 1
            if not self._draining:
                ticket = self._pick_next()
                if ticket is not None:
                    self._inflight += 1
            self._cond.notify_all()

    def acquire(self, tenant=None, deadline_at=None):
        """Take an execution slot for `tenant`, waiting in its
        bounded queue if needed.  Returns a Slot (release it
        exactly-or-more-than once).  Raises BusyError immediately
        when the global queue or the tenant's quota is full,
        OverloadedError when `deadline_at` (a monotonic timestamp)
        cannot be met, DrainingError once shutdown() was called.  The
        rejections carry retry_after_ms derived from observed
        service time."""
        # the chaos seam fires OUTSIDE the condition lock: a
        # delay-kind arming must stall only this request, never every
        # acquire/release path behind the shared lock
        try:
            mod_faults.fire('tenant.flood')
        except mod_faults.FaultInjected as e:
            with self._cond:
                t = self._tenant(tenant)
                t.counters['requests'] += 1
                t.counters['rejected_busy'] += 1
                raise BusyError(
                    'server busy: %s' % e.message,
                    retry_after_ms=self._retry_after_ms())
        with self._cond:
            t = self._tenant(tenant)
            t.counters['requests'] += 1
            if self._draining:
                raise DrainingError('server draining: request not '
                                    'admitted; retry another replica')
            now = time.monotonic()
            if deadline_at is not None and now >= deadline_at:
                t.counters['shed_overload'] += 1
                self._shed_expired += 1
                raise OverloadedError(
                    'server overloaded: request deadline already '
                    'expired before admission',
                    retry_after_ms=self._retry_after_ms())
            if self._inflight < self.max_inflight and \
                    not self._active:
                self._inflight += 1
                t.counters['admitted'] += 1
                obs_metrics.observe('serve_queue_wait_ms', 0.0)
                return Slot(self)
            # the request must queue: shed it early if its deadline
            # cannot cover even one typical service time (it would
            # wait, run, and still miss — don't burn the slot)
            if deadline_at is not None and \
                    (deadline_at - now) * 1000.0 < \
                    self._est_service_ms():
                t.counters['shed_overload'] += 1
                self._shed_overload += 1
                obs_metrics.inc('serve_shed_total', reason='overload')
                if obs_events.enabled():
                    # coalesced: a shed STORM is one journal entry
                    # per window with the burst count, not a ring
                    # flush of everything else
                    obs_events.emit_burst('serve.shed',
                                          key='overload',
                                          reason='overload',
                                          tenant=t.name)
                raise OverloadedError(
                    'server overloaded: remaining deadline (%d ms) '
                    'below observed service time (%d ms); shed'
                    % (int((deadline_at - now) * 1000),
                       int(self._est_service_ms())),
                    retry_after_ms=self._retry_after_ms())
            if self._queued >= self.queue_depth:
                t.counters['rejected_busy'] += 1
                raise BusyError(
                    'server busy: %d request(s) in flight, %d queued '
                    '(DN_SERVE_MAX_INFLIGHT=%d DN_SERVE_QUEUE_DEPTH=%d)'
                    % (self._inflight, self._queued, self.max_inflight,
                       self.queue_depth),
                    retry_after_ms=self._retry_after_ms())
            if self.tenant_quota and \
                    len(t.waiting) >= self.tenant_quota:
                t.counters['rejected_busy'] += 1
                raise BusyError(
                    'server busy: tenant "%s" has %d request(s) '
                    'queued (DN_SERVE_TENANT_QUOTA=%d)'
                    % (t.name, len(t.waiting), self.tenant_quota),
                    retry_after_ms=self._retry_after_ms())
            ticket = _Ticket(t.name)
            t.waiting.append(ticket)
            if t.name not in self._active:
                # (re)joining contention: clamp a stale pass — high
                # from a past flood, or low from being created in a
                # lull — to the live virtual time, else the gap buys
                # starvation-length grant runs
                t.vpass = max(t.vpass, self._vtime)
                self._active.add(t.name)
            self._queued += 1
            try:
                with obs_metrics.timed_stage(
                        'serve.queue_wait',
                        metric='serve_queue_wait_ms', labels={}):
                    while not ticket.granted:
                        if self._draining:
                            self._cancel(t, ticket)
                            raise DrainingError(
                                'server draining: request not '
                                'admitted; retry another replica')
                        timeout = None
                        if deadline_at is not None:
                            timeout = deadline_at - time.monotonic()
                            if timeout <= 0:
                                self._cancel(t, ticket)
                                t.counters['shed_overload'] += 1
                                self._shed_expired += 1
                                obs_metrics.inc('serve_shed_total',
                                                reason='expired')
                                if obs_events.enabled():
                                    obs_events.emit_burst(
                                        'serve.shed',
                                        key='expired',
                                        reason='expired',
                                        tenant=t.name)
                                raise OverloadedError(
                                    'server overloaded: deadline '
                                    'expired while queued; shed',
                                    retry_after_ms=(
                                        self._retry_after_ms()))
                        self._cond.wait(timeout)
            finally:
                self._queued -= 1
            # granted by the scheduler (which already took the slot)
            t.counters['admitted'] += 1
            return Slot(self)

    def _cancel(self, tenant, ticket):
        # call with _cond held: withdraw an ungranted ticket; if the
        # scheduler granted it in the same instant, hand the slot on
        if ticket.granted:
            ticket.cancelled = True
            self._inflight -= 1
            nxt = self._pick_next()
            if nxt is not None:
                self._inflight += 1
            self._cond.notify_all()
        else:
            try:
                tenant.waiting.remove(ticket)
            except ValueError:
                pass
            if not tenant.waiting:
                self._active.discard(tenant.name)

    def note_completed(self, tenant=None):
        """Fairness accounting: one request for `tenant` ran to
        completion (the soak's per-tenant completion ratios)."""
        with self._cond:
            self._tenant(tenant).counters['completed'] += 1

    def depth(self):
        with self._cond:
            return {'active': self._inflight, 'queued': self._queued,
                    'max_inflight': self.max_inflight,
                    'queue_depth': self.queue_depth}

    def tenants_doc(self):
        """The /stats `tenants` section: per-tenant weights, queue
        depths, and admission/shed/completion counters, plus the
        shed totals and the live service-time estimate."""
        with self._cond:
            return {
                'quota': self.tenant_quota,
                'default_weight': self.tenant_default_weight,
                'service_est_ms': round(self._est_service_ms(), 3),
                'shed_overload': self._shed_overload,
                'shed_expired': self._shed_expired,
                'evicted_tenants': self._evicted_n,
                'tenants': {
                    t.name: dict(t.counters, weight=t.weight,
                                 queued=len(t.waiting))
                    for t in self._tenants.values()},
            }


class TreeLock(object):
    """Writer-priority reader/writer lock, one per index tree: index
    queries hold the read side while they execute, builds hold the
    write side — so a query never enumerates a tree mid-rewrite (the
    writer's tmp+rename discipline makes each SHARD atomic, but the
    tree as a whole grows tmp litter and partial shard sets while a
    build runs, and a resident server overlaps those freely).  Writer
    priority keeps a build from starving under a steady query load."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    @contextmanager
    def read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
        try:
            yield
        finally:
            with self._cond:
                self._readers -= 1
                self._cond.notify_all()

    @contextmanager
    def write(self):
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()


class _Execution(object):
    __slots__ = ('done', 'value', 'error', 'followers')

    def __init__(self):
        self.done = threading.Event()
        self.value = None
        self.error = None
        self.followers = 0


# followers never wait forever even if a leader thread dies without
# publishing (a bug, but one that must not strand client connections)
_FOLLOW_CAP_S = 3600.0


class Coalescer(object):
    """Share one execution across identical in-flight requests.

    run(key, compute) returns (value, shared): the leader executes
    `compute()` and publishes; followers wait and receive the same
    value (or re-raise the same error).  The key is removed from the
    in-flight table BEFORE the result publishes, so a request arriving
    after completion always starts a fresh execution — this is
    in-flight sharing only, never a result cache (writer invalidation
    stays trivial: there is nothing stale to invalidate)."""

    def __init__(self, enabled=True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._inflight = {}
        self._stats = {'executions': 0, 'coalesced': 0}

    def run(self, key, compute, lease=None):
        if not self.enabled or key is None:
            with self._lock:
                self._stats['executions'] += 1
            return compute(), False
        with self._lock:
            ex = self._inflight.get(key)
            if ex is None:
                ex = _Execution()
                self._inflight[key] = ex
                self._stats['executions'] += 1
                leader = True
            else:
                ex.followers += 1
                self._stats['coalesced'] += 1
                leader = False
        if not leader:
            with obs_metrics.timed_stage(
                    'serve.coalesce_wait',
                    metric='serve_coalesce_wait_ms', labels={}):
                done = ex.done.wait(_FOLLOW_CAP_S)
            if not done:
                raise DeadlineError('coalesced execution never '
                                    'completed')
            if ex.error is not None:
                raise ex.error
            return ex.value, True
        if lease is not None:
            # the reaper's handle on this execution: a leader whose
            # request deadline expires must be abandon()ed so new
            # arrivals recompute instead of attaching to it forever
            lease['key'] = key
            lease['ex'] = ex
        try:
            ex.value = compute()
        except BaseException as e:
            ex.error = e
            raise
        finally:
            with self._lock:
                # identity-checked: abandon() may have replaced this
                # key with a fresh execution already
                if self._inflight.get(key) is ex:
                    self._inflight.pop(key)
            ex.done.set()
        return ex.value, False

    def abandon(self, key, ex):
        """Retire a leader's in-flight registration after its request
        deadline expired: the wedged execution must stop attracting
        followers, and any already attached must wake with the
        deadline error (they share their leader's fate).  No-op when
        the execution already completed or was replaced."""
        if key is None or ex is None:
            return
        with self._lock:
            if self._inflight.get(key) is not ex:
                return
            self._inflight.pop(key)
        if ex.error is None:
            ex.error = DeadlineError(
                'coalesced execution abandoned (leader request '
                'deadline expired)')
        ex.done.set()

    def stats(self):
        with self._lock:
            return dict(self._stats, inflight=len(self._inflight))


def compute_key(req, config_ident):
    """Canonical coalescing key for a data request: everything that
    determines the COMPUTED result (op, datasource, query document,
    interval, dry-run, plus the config file's identity so an edited
    datasource definition never shares with its predecessor) and
    nothing that only affects output formatting."""
    if req.get('op') not in ('scan', 'query', 'query_partial'):
        return None              # builds and debug ops never coalesce
    doc = {
        'op': req.get('op'),
        'ds': req.get('ds'),
        'config': config_ident,
        'queryconfig': req.get('queryconfig'),
        'interval': req.get('interval'),
        'dry_run': bool((req.get('opts') or {}).get('dry_run')),
    }
    if req.get('op') == 'query_partial':
        # partition-scoped partials only share when they cover the
        # same partitions under the same topology generation
        doc['partitions'] = sorted(req.get('partitions') or [])
        doc['epoch'] = req.get('epoch')
    return json.dumps(doc, sort_keys=True, separators=(',', ':'))
