"""Admission control, per-request deadlines, and request coalescing
for `dn serve`.

Three mechanisms keep a resident server healthy under concurrent
load, in the order a request meets them:

* Coalescing (`Coalescer`): identical in-flight computations — same
  datasource, same query shape, same config identity — share ONE
  execution.  The first request in becomes the leader and computes;
  followers attach and wait for the leader's result (StreamBox-HBM's
  target-latency batching of concurrent pipeline work, applied to the
  serving tier).  Compatible requests that differ only in OUTPUT
  options (--raw vs --points vs pretty vs --counters) coalesce too:
  the compute key deliberately excludes formatting, and the server
  demuxes one shared ScanResult through each request's own output
  path.  Because the shared run goes through the default stacked
  cross-shard execution (index_query_stack), N concurrent index
  queries over the same tree cost one stacked aggregation.

* Admission (`Admission`): at most `max_inflight` executions run at
  once; up to `queue_depth` more may wait for a slot; beyond that the
  request fails FAST with a 429-style DNError ("server busy") instead
  of joining an unbounded convoy.  Coalesced followers do not consume
  slots — attaching to an in-flight execution is the cheap path the
  whole design exists to reward.

* Deadlines: each request runs under `DN_SERVE_DEADLINE_MS` (or its
  own `deadline_ms`) on a reaper-armored thread
  (device_scan.run_with_deadline) — a wedged device op or a
  pathological query costs the client a bounded wait and a DNError,
  never a hung connection.  A coalesced follower shares its leader's
  fate: if the leader's execution times out, every attached request
  reports the deadline error.
"""

import json
import threading
from contextlib import contextmanager

from ..errors import DNError
from ..obs import metrics as obs_metrics


class BusyError(DNError):
    """Queue-full fast rejection (the 429 analog).  Retryable: the
    client's backoff loop may try again."""


class DeadlineError(DNError):
    """Per-request deadline expiry (the 504 analog)."""


class DrainingError(DNError):
    """The server is draining (SIGTERM/stop): queued-but-unadmitted
    requests get this clean, retryable rejection instead of a
    connection reset when the process exits.  A retrying client (or
    the future scatter-gather router) re-sends to the replacement
    server."""


class Slot(object):
    """One admitted execution slot.  release() is IDEMPOTENT: a
    deadline-expired request's reaper frees the slot immediately while
    the abandoned job thread's own finally releases again when (if)
    the wedged operation eventually finishes — only the first call
    counts, so accounting never goes negative and a permanently
    wedged op cannot pin a slot forever."""

    __slots__ = ('_admission', '_released')

    def __init__(self, admission):
        self._admission = admission
        self._released = False

    def release(self):
        with self._admission._cond:
            if self._released:
                return
            self._released = True
            self._admission._inflight -= 1
            self._admission._cond.notify()


class Admission(object):
    """Bounded execution slots with a bounded waiting room."""

    def __init__(self, max_inflight, queue_depth):
        self.max_inflight = max_inflight
        self.queue_depth = queue_depth
        self._cond = threading.Condition()
        self._inflight = 0
        self._queued = 0
        self._draining = False

    def shutdown(self):
        """Begin draining: every queued waiter (and every future
        acquire) raises DrainingError instead of waiting for a slot —
        in-flight executions are unaffected and finish normally."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def acquire(self):
        """Take an execution slot, waiting in the bounded queue if
        needed.  Returns a Slot (release it exactly-or-more-than
        once).  Raises BusyError immediately when the queue is full,
        DrainingError once shutdown() was called."""
        with self._cond:
            if self._draining:
                raise DrainingError('server draining: request not '
                                    'admitted; retry another replica')
            if self._inflight < self.max_inflight:
                self._inflight += 1
                obs_metrics.observe('serve_queue_wait_ms', 0.0)
                return Slot(self)
            if self._queued >= self.queue_depth:
                raise BusyError(
                    'server busy: %d request(s) in flight, %d queued '
                    '(DN_SERVE_MAX_INFLIGHT=%d DN_SERVE_QUEUE_DEPTH=%d)'
                    % (self._inflight, self._queued, self.max_inflight,
                       self.queue_depth))
            self._queued += 1
            try:
                with obs_metrics.timed_stage(
                        'serve.queue_wait',
                        metric='serve_queue_wait_ms', labels={}):
                    while self._inflight >= self.max_inflight:
                        if self._draining:
                            raise DrainingError(
                                'server draining: request not '
                                'admitted; retry another replica')
                        self._cond.wait()
            finally:
                self._queued -= 1
            self._inflight += 1
            return Slot(self)

    def depth(self):
        with self._cond:
            return {'active': self._inflight, 'queued': self._queued,
                    'max_inflight': self.max_inflight,
                    'queue_depth': self.queue_depth}


class TreeLock(object):
    """Writer-priority reader/writer lock, one per index tree: index
    queries hold the read side while they execute, builds hold the
    write side — so a query never enumerates a tree mid-rewrite (the
    writer's tmp+rename discipline makes each SHARD atomic, but the
    tree as a whole grows tmp litter and partial shard sets while a
    build runs, and a resident server overlaps those freely).  Writer
    priority keeps a build from starving under a steady query load."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    @contextmanager
    def read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
        try:
            yield
        finally:
            with self._cond:
                self._readers -= 1
                self._cond.notify_all()

    @contextmanager
    def write(self):
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()


class _Execution(object):
    __slots__ = ('done', 'value', 'error', 'followers')

    def __init__(self):
        self.done = threading.Event()
        self.value = None
        self.error = None
        self.followers = 0


# followers never wait forever even if a leader thread dies without
# publishing (a bug, but one that must not strand client connections)
_FOLLOW_CAP_S = 3600.0


class Coalescer(object):
    """Share one execution across identical in-flight requests.

    run(key, compute) returns (value, shared): the leader executes
    `compute()` and publishes; followers wait and receive the same
    value (or re-raise the same error).  The key is removed from the
    in-flight table BEFORE the result publishes, so a request arriving
    after completion always starts a fresh execution — this is
    in-flight sharing only, never a result cache (writer invalidation
    stays trivial: there is nothing stale to invalidate)."""

    def __init__(self, enabled=True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._inflight = {}
        self._stats = {'executions': 0, 'coalesced': 0}

    def run(self, key, compute, lease=None):
        if not self.enabled or key is None:
            with self._lock:
                self._stats['executions'] += 1
            return compute(), False
        with self._lock:
            ex = self._inflight.get(key)
            if ex is None:
                ex = _Execution()
                self._inflight[key] = ex
                self._stats['executions'] += 1
                leader = True
            else:
                ex.followers += 1
                self._stats['coalesced'] += 1
                leader = False
        if not leader:
            with obs_metrics.timed_stage(
                    'serve.coalesce_wait',
                    metric='serve_coalesce_wait_ms', labels={}):
                done = ex.done.wait(_FOLLOW_CAP_S)
            if not done:
                raise DeadlineError('coalesced execution never '
                                    'completed')
            if ex.error is not None:
                raise ex.error
            return ex.value, True
        if lease is not None:
            # the reaper's handle on this execution: a leader whose
            # request deadline expires must be abandon()ed so new
            # arrivals recompute instead of attaching to it forever
            lease['key'] = key
            lease['ex'] = ex
        try:
            ex.value = compute()
        except BaseException as e:
            ex.error = e
            raise
        finally:
            with self._lock:
                # identity-checked: abandon() may have replaced this
                # key with a fresh execution already
                if self._inflight.get(key) is ex:
                    self._inflight.pop(key)
            ex.done.set()
        return ex.value, False

    def abandon(self, key, ex):
        """Retire a leader's in-flight registration after its request
        deadline expired: the wedged execution must stop attracting
        followers, and any already attached must wake with the
        deadline error (they share their leader's fate).  No-op when
        the execution already completed or was replaced."""
        if key is None or ex is None:
            return
        with self._lock:
            if self._inflight.get(key) is not ex:
                return
            self._inflight.pop(key)
        if ex.error is None:
            ex.error = DeadlineError(
                'coalesced execution abandoned (leader request '
                'deadline expired)')
        ex.done.set()

    def stats(self):
        with self._lock:
            return dict(self._stats, inflight=len(self._inflight))


def compute_key(req, config_ident):
    """Canonical coalescing key for a data request: everything that
    determines the COMPUTED result (op, datasource, query document,
    interval, dry-run, plus the config file's identity so an edited
    datasource definition never shares with its predecessor) and
    nothing that only affects output formatting."""
    if req.get('op') not in ('scan', 'query', 'query_partial'):
        return None              # builds and debug ops never coalesce
    doc = {
        'op': req.get('op'),
        'ds': req.get('ds'),
        'config': config_ident,
        'queryconfig': req.get('queryconfig'),
        'interval': req.get('interval'),
        'dry_run': bool((req.get('opts') or {}).get('dry_run')),
    }
    if req.get('op') == 'query_partial':
        # partition-scoped partials only share when they cover the
        # same partitions under the same topology generation
        doc['partitions'] = sorted(req.get('partitions') or [])
        doc['epoch'] = req.get('epoch')
    return json.dumps(doc, sort_keys=True, separators=(',', ':'))
