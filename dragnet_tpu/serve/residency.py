"""Device-memory residency for `dn serve`: keep the device lane's hot
state in HBM across requests, and fetch only final results over the
slow D2H path.

The measured transport asymmetry from bench round 5 (~1 GB/s H2D vs
~12-18 MB/s D2H over the tunneled plugin) dictates the design: the
expensive direction is OFF the chip, so a resident server must (a)
upload each stacked index column at most once while it stays valid,
(b) keep the folded high-cardinality accumulator ON the device between
requests, and (c) pay the D2H fetch once per distinct accumulator, not
once per request.  A repeat of the same stacked aggregation answers
from the pinned accumulator with zero transfer in either direction.

Entries pin two things: the device-side dense accumulator (the HBM
bytes `pinned_bytes` reports) and its one fetched host copy (what a
hit returns, byte-identical by construction — it IS the array the
first execution produced).  Keyed by the content digest of the staged
device inputs, so two requests whose stacked columns differ can never
alias.

Invalidation is the result cache's epoch contract (serve/qcache.py):
`index_query_mt.cache_epoch()`, bumped by the server's
`install_writer_invalidation` hook on every completed in-process index
write.  Any write anywhere retires every pinned entry — conservative,
O(1), and HBM never serves stale sums.  `clear()` drops every device
reference at drain so the backend can reclaim the memory.

Budgeted LRU, like the result cache — but against the DEVICE budget
(DN_DEVICE_RESIDENCY_MB), not the host governor: HBM is the scarce
resource here and is not part of the DN_SERVE_MEM_BUDGET_MB pool.
0 (the default) disables residency; the device lane then uploads and
fetches per request exactly as before — byte-identical either way.

The module-level singleton (`configure`/`active`/`deconfigure`) is the
seam the index-query device lane reads: a bare CLI process never
configures it, so `dn query` costs nothing and changes nothing.
"""

import hashlib
import threading
import time
from collections import OrderedDict

_LOCK = threading.Lock()
_ACTIVE = None


def configure(budget_bytes):
    """Install the process-wide residency manager (server startup).
    Returns the manager; a zero budget installs a disabled one so
    /stats still reports the knob honestly."""
    global _ACTIVE
    mgr = DeviceResidency(budget_bytes)
    with _LOCK:
        _ACTIVE = mgr
    from ..obs import metrics as obs_metrics
    obs_metrics.set_residency_source(stats)
    return mgr


def deconfigure():
    """Drop the manager and every pinned device array (drain path)."""
    global _ACTIVE
    with _LOCK:
        mgr, _ACTIVE = _ACTIVE, None
    if mgr is not None:
        mgr.clear()
    from ..obs import metrics as obs_metrics
    obs_metrics.set_residency_source(None)


def active():
    """The enabled manager, or None — the device lane's fast check."""
    mgr = _ACTIVE
    return mgr if mgr is not None and mgr.enabled() else None


def stats():
    """The active manager's stats doc ({'enabled': False} when none
    is configured) — /stats, fleet aggregation, and the device gauges
    all read this one shape."""
    mgr = _ACTIVE
    return mgr.stats() if mgr is not None else {'enabled': False}


def content_key(kind, arrays, shape):
    """Digest-of-content cache key for a set of staged device inputs:
    two uploads collide only when every byte agrees, so a pinned
    accumulator can never answer for different columns.  `shape` folds
    in the static program parameters (padded sizes) that select the
    compiled program."""
    h = hashlib.blake2b(digest_size=16)
    for arr in arrays:
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return (kind, shape, h.hexdigest())


def _device_deleted(x):
    """True when a pinned device reference no longer owns its buffer.
    The pipelined scan fold donates accumulator arguments, and a
    donated jax.Array reports is_deleted() — a pin that aliased one
    would hold no HBM and must read as a miss, never as residency."""
    if isinstance(x, (tuple, list)):
        return any(_device_deleted(v) for v in x)
    fn = getattr(x, 'is_deleted', None)
    if callable(fn):
        try:
            return bool(fn())
        except Exception:
            return False
    return False


class DeviceResidency(object):
    """LRU of device-resident accumulators, bounded by HBM bytes,
    invalidated by the writer epoch.  Thread-safe — the serve workers
    race on it."""

    def __init__(self, budget_bytes, shard_share=None):
        self.budget = int(budget_bytes or 0)
        if shard_share is None:
            import os
            try:
                shard_share = float(os.environ.get(
                    'DN_INDEX_RESIDENCY_SHARE', '0.5'))
            except ValueError:
                shard_share = 0.5
        self.shard_share = min(max(float(shard_share), 0.0), 1.0)
        self._lock = threading.Lock()
        self._entries = OrderedDict()
        self._bytes = 0
        self._shard_bytes = 0
        self._hits = 0
        self._misses = 0
        self._stale = 0
        self._evictions = 0
        self._shed = 0
        self._h2d_saved = 0
        self._d2h_saved = 0

    def enabled(self):
        return self.budget > 0

    # -- internals (call with self._lock held) ----------------------------

    def _drop_locked(self, key, ent):
        if self._entries.get(key) is not ent:
            return
        del self._entries[key]
        self._bytes -= ent['nbytes']
        if ent.get('kind') == 'shard':
            self._shard_bytes -= ent['nbytes']

    def _evict_lru_locked(self, kind=None):
        for key, ent in self._entries.items():
            if kind is not None and ent.get('kind') != kind:
                continue
            self._drop_locked(key, ent)
            self._evictions += 1
            return True
        return False

    def _evict_global_locked(self):
        """Global-budget eviction prefers the host-side (whole-result)
        pins: the shard share exists precisely so staged shard columns
        survive distinct-query churn — whole-result pins only answer
        exact repeats, so they are the cheaper loss.  Shard pins go
        only when nothing else is left."""
        for key, ent in self._entries.items():
            if ent.get('kind') == 'shard':
                continue
            self._drop_locked(key, ent)
            self._evictions += 1
            return True
        return self._evict_lru_locked(kind='shard')

    # -- the residency protocol --------------------------------------------

    def get(self, key, epoch):
        """The pinned host copy for `key`, or None.  A hit counts the
        transfers it avoided: the inputs' H2D upload and the
        accumulator's D2H fetch."""
        if not self.enabled() or key is None:
            return None
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and ent.get('kind') == 'shard':
                ent = None       # device-only pin: not this protocol
            if ent is not None and ent['epoch'] != epoch:
                self._drop_locked(key, ent)
                self._stale += 1
                ent = None
            if ent is not None and _device_deleted(ent['device']):
                self._drop_locked(key, ent)
                self._stale += 1
                ent = None
            if ent is None:
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            self._h2d_saved += ent['h2d_bytes']
            self._d2h_saved += ent['nbytes']
            return ent['host']

    def put(self, key, epoch, device, host, h2d_bytes):
        """Pin a freshly computed accumulator: `device` is the
        device-side array (held alive = resident in HBM), `host` its
        one fetched copy, `h2d_bytes` what the inputs cost to upload
        (the savings a future hit books).  Over-budget pins evict LRU;
        an accumulator alone over budget is shed."""
        if not self.enabled() or key is None:
            return False
        try:
            nbytes = int(device.nbytes)
        except (AttributeError, TypeError):
            nbytes = int(getattr(host, 'nbytes', 0) or 0)
        if nbytes <= 0 or nbytes > self.budget:
            with self._lock:
                self._shed += 1
            return False
        ent = {'epoch': epoch, 'device': device, 'host': host,
               'nbytes': nbytes, 'h2d_bytes': int(h2d_bytes or 0),
               'ts': time.time()}
        with self._lock:
            old = self._entries.get(key)
            if old is not None:
                self._drop_locked(key, old)
            while self._bytes + nbytes > self.budget:
                if not self._evict_global_locked():
                    break
            self._entries[key] = ent
            self._bytes += nbytes
        return True

    # -- per-shard device-tensor pins (device_index.py) --------------------

    def get_device(self, key, epoch):
        """The pinned DEVICE tensors for a staged shard (tuple of
        jax arrays), or None.  Unlike get(), nothing is fetched — a
        hit hands the device references straight back into the next
        dispatch and books only the H2D upload it skipped."""
        if not self.enabled() or key is None:
            return None
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and (ent.get('kind') != 'shard'
                                    or ent['epoch'] != epoch
                                    or _device_deleted(ent['device'])):
                if ent.get('kind') == 'shard':
                    self._drop_locked(key, ent)
                    self._stale += 1
                ent = None
            if ent is None:
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            self._h2d_saved += ent['h2d_bytes']
            return ent['device']

    def put_device(self, key, epoch, device, nbytes, h2d_bytes=None):
        """Pin one shard's staged device tensors (no host copy — the
        host never needs them back).  Bounded twice: by the global HBM
        budget AND by the shard share (DN_INDEX_RESIDENCY_SHARE of the
        budget), so shard columns cannot starve the pinned
        accumulators that answer exact repeats with zero transfer."""
        if not self.enabled() or key is None:
            return False
        nbytes = int(nbytes or 0)
        cap = int(self.budget * self.shard_share)
        if nbytes <= 0 or nbytes > cap:
            with self._lock:
                self._shed += 1
            return False
        ent = {'epoch': epoch, 'device': device, 'host': None,
               'nbytes': nbytes, 'kind': 'shard',
               'h2d_bytes': int(h2d_bytes if h2d_bytes is not None
                                else nbytes),
               'ts': time.time()}
        with self._lock:
            old = self._entries.get(key)
            if old is not None:
                self._drop_locked(key, old)
            while self._shard_bytes + nbytes > cap:
                if not self._evict_lru_locked(kind='shard'):
                    break
            while self._bytes + nbytes > self.budget:
                if not self._evict_global_locked():
                    break
            self._entries[key] = ent
            self._bytes += nbytes
            self._shard_bytes += nbytes
        return True

    def clear(self):
        """Release every pinned device array (drain, invalidation
        hammer for tests)."""
        with self._lock:
            for key, ent in list(self._entries.items()):
                self._drop_locked(key, ent)

    def drop_host_pins(self):
        """Drop every whole-result (host-copy) pin, keeping the shard
        pins — the state distinct-query churn converges to under
        budget pressure (_evict_global_locked goes host-first).  Bench
        and tests use this to exercise the pinned-shard repeat path
        deterministically."""
        with self._lock:
            for key, ent in list(self._entries.items()):
                if ent.get('kind') != 'shard':
                    self._drop_locked(key, ent)

    def stats(self):
        with self._lock:
            hits, misses = self._hits, self._misses
            doc = {
                'enabled': self.enabled(),
                'budget_bytes': self.budget,
                'bytes': self._bytes,
                'entries': len(self._entries),
                'shard_bytes': self._shard_bytes,
                'shard_share': self.shard_share,
                'hits': hits,
                'misses': misses,
                'stale_drops': self._stale,
                'evictions': self._evictions,
                'shed': self._shed,
                'h2d_saved_bytes': self._h2d_saved,
                'd2h_saved_bytes': self._d2h_saved,
            }
        total = hits + misses
        doc['hit_rate'] = round(hits / total, 4) if total else 0.0
        return doc


# -- serve-start pre-warm ---------------------------------------------------

# padded (rows, segments) shapes worth compiling before the first
# request: the pow2 ladder index_query_stack pads real queries into
_PREWARM_SHAPES = ((1 << 10, 1 << 8), (1 << 14, 1 << 10))


def prewarm(shapes=_PREWARM_SHAPES, deadline_s=None):
    """Serve-start device pre-warm: initialize the backend, compile
    the stacked index-query programs for representative shapes, and
    report the persisted audition cache — all BEFORE the first
    request pays for any of it.  Runs the whole thing under the probe
    deadline on the caller's (background) thread: a wedged plugin
    costs a bounded wait and an honest 'timeout' doc, never a hung
    server.  Returns {'state', 'backend', 'programs', 'auditions',
    'audition_path', 'ms'}."""
    from .. import device_scan as mod_ds
    doc = {'state': 'failed', 'backend': None, 'programs': 0,
           'auditions': 0, 'audition_path': None, 'ms': 0.0}
    if deadline_s is None:
        deadline_s = mod_ds.probe_deadline_s()
    t0 = time.monotonic()

    def warm():
        import numpy as np
        from ..ops import backend_ready
        from .. import index_query_stack as mod_iqs
        if not backend_ready():
            return None
        compiled = 0
        for pn, pu in shapes:
            prog = mod_iqs._sums_program(pn, pu)
            out = prog(np.zeros(pn, dtype=np.int64),
                       np.zeros(pn, dtype=np.int64))
            np.asarray(out)          # force compile + execute
            compiled += 1
        return compiled

    status, compiled = mod_ds.run_with_deadline(warm, deadline_s,
                                                'serve-prewarm')
    if status == 'ok' and compiled is not None:
        doc['state'] = 'ok'
        doc['programs'] = compiled
        doc['backend'] = mod_ds._backend_id()
    elif status == 'timeout':
        doc['state'] = 'timeout'
    path, entries, wins = mod_ds.audition_cache_entries()
    doc['audition_path'] = path
    doc['auditions'] = entries
    doc['audition_wins'] = wins
    doc['ms'] = round((time.monotonic() - t0) * 1000.0, 3)
    from ..obs import metrics as obs_metrics
    obs_metrics.set_gauge('device_prewarm_ok',
                          1.0 if doc['state'] == 'ok' else 0.0)
    obs_metrics.set_gauge('device_prewarm_ms', doc['ms'])
    return doc
