"""Scatter-gather query routing for a `dn serve` cluster.

Any cluster member can be the router for an incoming index query: it
fans one partition-scoped partial query (`query_partial`) to a live
replica of every partition in the topology, merges the partial
aggregates through the Aggregator key-items wire format, and formats
the merged result through the unmodified CLI output layer — so a
routed query's RESULT bytes are identical to a single-process run.
(`--counters` debug output is explicitly outside that contract: it
renders pipeline stages, and the router's merge pipeline is not the
single-process find/walk pipeline — each member ran its own walk.)

Byte-identity is structural, not hopeful: final output order depends
on the FIRST-OCCURRENCE order of string-like group keys across the
whole shard set (aggr.js_key_order), so partials travel as
PER-SHARD key-item lists (each member answers for its shards in find
order) and the router merges every shard — across all partitions —
in global find order (the path-component sort below).  The merge loop
is the same write_key replay `datasource_file.query` runs for its own
shard fan-in.

Failure-first design (the headline of this layer):

* Per-member circuit breakers (closed -> open after
  DN_ROUTER_FAILURES consecutive failures -> half-open one trial
  after DN_ROUTER_COOLDOWN_MS), fed by both a background health
  prober (the PR 6 `health` op, DN_ROUTER_PROBE_MS cadence) and live
  dispatch outcomes.
* Automatic failover: a partial that fails on one replica
  (connect/transport errors, retryable rejections, epoch mismatch)
  moves to the next-ranked replica.  Replica ranking demotes DRAINING
  members before their socket dies and open-breaker members to
  last-resort (they are still dialed when nothing better exists — the
  breaker must never turn a blip into a guaranteed outage).
* Hedged reads: when a partial is slower than the observed p95
  (floored at DN_ROUTER_HEDGE_MS; 0 disables), the router fires a
  duplicate at the next replica and keeps whichever answers first;
  fired/won/wasted counts are accounted.
* Clean degraded results: when EVERY replica of a partition is down,
  DN_ROUTER_PARTIAL picks the contract — 'error' raises a retryable
  DNError naming the missing partitions; 'allow' merges the live
  partitions and names the missing ones in the response header.
  Never a hang (DN_ROUTER_FETCH_TIMEOUT_S bounds each fetch), never
  a traceback, never silently short bytes.

Every decision lands in the obs layer: router_* counters and the
router_partial_ms histogram (which also feeds the hedge delay),
router.scatter/router.partial/router.merge spans, and the /stats
`cluster` section (serve/server.py).

Dynamic topology (serve/coordinator.py): the serving map can change
while the router runs.  update_topology() swaps the map atomically —
departed members' prober threads stop and their pooled connections
close/evict (no thread or fd leak, no log-noise probing of dead
endpoints), new members get fresh states and probers.  Every scatter
snapshots ONE topology (a whole query is answered under exactly one
epoch — never a mix of partition maps), and a member that answers a
partial with an epoch-mismatch rejection raises TopologyEpochError so
the server can re-fetch the current map and retry the scatter.
"""

import json
import os
import queue
import threading
import time

from ..errors import DNError
from .. import config as mod_config
from .. import faults as mod_faults
from .. import vpipe as mod_vpipe
from ..obs import events as obs_events
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace


class RouterPartitionError(DNError):
    """Every replica of >= 1 partition is down and DN_ROUTER_PARTIAL
    is 'error': a clean, retryable degraded response naming the
    missing partitions (the `missing_partitions` attribute rides into
    the response header)."""

    def __init__(self, missing, detail, retry_after_ms=None):
        super(RouterPartitionError, self).__init__(
            'cluster partition(s) unavailable: %s (%s)'
            % (','.join(str(p) for p in missing), detail))
        self.missing_partitions = list(missing)
        self.retryable = True
        # when the partitions failed because members were SHEDDING
        # (busy/overloaded, not down), the members' retry hints ride
        # up to the client — shed != down, and the client should back
        # off exactly as long as the most loaded member asked
        self.retry_after_ms = retry_after_ms


class TopologyEpochError(DNError):
    """A member rejected a partial because it serves a NEWER topology
    epoch than the one this scatter ran under: the router's map is
    stale.  Retryable — the server re-polls the coordinator source
    and retries the whole scatter under the refreshed map."""

    def __init__(self, detail, current_epoch=None):
        super(TopologyEpochError, self).__init__(
            'topology epoch stale during scatter: %s' % detail)
        self.retryable = True
        self.epoch_mismatch = True
        self.current_epoch = current_epoch


class _BreakerOpen(Exception):
    """Internal: a dial was suppressed by an open breaker."""


# every router counter also lands in the typed registry as
# ``router_<name>_total`` (_bump below); module-level so the
# Prometheus-exposition completeness gate can enumerate the family
# without constructing a Router
COUNTER_NAMES = ('scatters', 'partials_local', 'partials_remote',
                 'failovers', 'hedges_fired', 'hedges_won',
                 'hedges_wasted', 'degraded', 'partial_responses',
                 'breaker_skips', 'breaker_forced_dials',
                 'epoch_updates', 'epoch_mismatches',
                 'corrupt_failovers')


# -- circuit breaker --------------------------------------------------------

class Breaker(object):
    """Per-member circuit breaker: CLOSED (healthy) -> OPEN after
    `failures` consecutive failures -> HALF_OPEN one trial after
    `cooldown_ms` -> CLOSED on trial success / back to OPEN on trial
    failure.  allow() consumes the half-open trial; record_success /
    record_failure feed it from probes and live dispatches alike."""

    CLOSED, OPEN, HALF_OPEN = 'closed', 'open', 'half-open'

    def __init__(self, failures, cooldown_ms, clock=time.monotonic,
                 name=None):
        self._lock = threading.Lock()
        self._clock = clock
        self.failures_threshold = failures
        self.cooldown_s = cooldown_ms / 1000.0
        self.name = name
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self._opened_at = None
        self._trial_inflight = False
        self.transitions = {self.CLOSED: 0, self.OPEN: 0,
                            self.HALF_OPEN: 0}

    def _to(self, state):
        prior = self.state
        self.state = state
        self.transitions[state] += 1
        if obs_events.enabled():
            # probes flip breakers with no request active: no trace
            obs_events.emit('breaker.' + state, member=self.name,
                            prior=prior,
                            failures=self.consecutive_failures)

    def allow(self):
        """May a request be sent to this member right now?"""
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN:
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._to(self.HALF_OPEN)
                    self._trial_inflight = True
                    return True
                return False
            # HALF_OPEN: exactly one trial in flight at a time
            if self._trial_inflight:
                return False
            self._trial_inflight = True
            return True

    def record_success(self):
        with self._lock:
            self.consecutive_failures = 0
            self._trial_inflight = False
            if self.state != self.CLOSED:
                self._to(self.CLOSED)

    def record_failure(self):
        with self._lock:
            self.consecutive_failures += 1
            self._trial_inflight = False
            if self.state == self.HALF_OPEN:
                self._opened_at = self._clock()
                self._to(self.OPEN)
            elif self.state == self.CLOSED and \
                    self.consecutive_failures >= \
                    self.failures_threshold:
                self._opened_at = self._clock()
                self._to(self.OPEN)

    def snapshot(self):
        with self._lock:
            return {'state': self.state,
                    'consecutive_failures': self.consecutive_failures,
                    'transitions': dict(self.transitions)}


class MemberState(object):
    """What the router knows about one member: endpoint, breaker, and
    the last health-probe verdict."""

    def __init__(self, name, endpoint, breaker):
        self.name = name
        self.endpoint = endpoint
        self.breaker = breaker
        self.lock = threading.Lock()
        self.draining = False
        # disk-critical read-only member (resources.py): still
        # serving queries byte-identically, demoted only for
        # write-shaped dispatch
        self.degraded_ro = False
        self.last_ok = None        # monotonic of last good signal
        # set when the member leaves the topology: its prober thread
        # exits at the next wakeup instead of probing a dead endpoint
        # forever (the pre-dynamic-topology leak)
        self.gone = threading.Event()

    def note_health(self, doc):
        ok = bool(doc.get('ok'))
        with self.lock:
            self.draining = bool(doc.get('draining'))
            self.degraded_ro = bool(doc.get('degraded_ro'))
            if ok:
                self.last_ok = time.monotonic()
        if ok:
            self.breaker.record_success()
        else:
            self.breaker.record_failure()

    def snapshot(self):
        with self.lock:
            draining = self.draining
            degraded_ro = self.degraded_ro
            last_ok = self.last_ok
        snap = self.breaker.snapshot()
        snap.update({'endpoint': self.endpoint, 'draining': draining,
                     'degraded_ro': degraded_ro,
                     'last_ok_age_s':
                     round(time.monotonic() - last_ok, 3)
                     if last_ok is not None else None})
        return snap


# -- member-side partial execution ------------------------------------------

def partial_query(ds, query, interval, topology, partition_ids):
    """Execute an index query over THIS member's slice of the shard
    set: the identical enumerate/sweep/litter-filter/prune walk a
    single-process query performs (datasource_file.index_query_paths),
    restricted to the shards `partition_ids` own, each shard's
    aggregate exported as key items in find order.  Returns
    [[relpath, [[keys..., ], weight], ...], ...] — the JSON wire shape
    of the `query_partial` op."""
    from .. import index_query_mt as mod_iqmt
    from ..vpipe import Pipeline
    pipeline = Pipeline()
    root, timeformat, files = ds.index_query_paths(query, interval,
                                                   pipeline)
    paths = [p for p, st in files]
    paths, _ = mod_iqmt.prune_shards(paths, timeformat,
                                     query.qc_after, query.qc_before)
    want = set(partition_ids)
    paths = [p for p in paths
             if topology.partition_of(p, timeformat) in want]
    mod_vpipe.counter_bump('cluster partial shards', len(paths))
    # verified reads: a catalogued shard of OUR partitions missing
    # from the walk (quarantined post-corruption, not yet repaired)
    # rejects the partial retryably — the router fails over to a
    # replica that has the bytes, instead of this member silently
    # merging a short shard set
    from .. import integrity as mod_integrity
    if mod_integrity.verify_mode() != 'off':
        mod_integrity.check_missing(
            ds.ds_indexpath, paths,
            subdir=os.path.basename(root)
            if timeformat is not None else None,
            timeformat=timeformat, after_ms=query.qc_after,
            before_ms=query.qc_before,
            partition_filter=lambda p:
            topology.partition_of(p, timeformat) in want)
    indexroot = ds.ds_indexpath
    shards = []
    state = {'i': 0}

    def on_items(items):
        # run_shard_queries reports once per shard in `paths` order
        path = paths[state['i']]
        state['i'] += 1
        shards.append([os.path.relpath(path, indexroot),
                       [[list(k), w] for k, w in items]])

    mod_iqmt.run_shard_queries(paths, query, mod_iqmt.iq_threads(),
                               on_items)
    return shards


# -- the router -------------------------------------------------------------

class Router(object):
    """The scatter-gather executor one cluster member runs.

    `local_exec(partition_ids, req)` is the server-provided callable
    that executes a partial for partitions THIS member owns without
    dialing itself (admission slot + tree read-lock inside) — routing
    through our own socket could deadlock a full admission queue.
    `self_draining()` reports the local server's drain state so the
    self replica demotes exactly like a remote draining member."""

    def __init__(self, topology, member, conf=None, local_exec=None,
                 self_draining=None, self_degraded=None):
        if conf is None:
            conf = mod_config.router_config()
        if isinstance(conf, DNError):
            raise conf
        self.topo = topology
        self.member = member
        self.conf = conf
        self.local_exec = local_exec
        self.self_draining = self_draining or (lambda: False)
        # the local server's read-only (disk critical) state, the
        # self-member analog of a probed degraded_ro flag
        self.self_degraded = self_degraded or (lambda: False)
        self.states = {}
        for name in topology.member_names():
            self.states[name] = MemberState(
                name, topology.endpoint(name),
                Breaker(conf['failures'], conf['cooldown_ms'],
                        name=name))
        self._stop = threading.Event()
        self._prober_started = False
        self._prober_threads = []
        # serializes topology swaps against each other; scatters
        # never take it — they snapshot self.topo once per scatter
        self._swap_lock = threading.Lock()
        self._lock = threading.Lock()
        self._counters = {name: 0 for name in COUNTER_NAMES}
        # the hedge-delay source: observed partial latencies (also
        # exported through the typed registry as router_partial_ms)
        self._latency = obs_metrics.Histogram()
        self._latency_lock = threading.Lock()

    # -- lifecycle --------------------------------------------------------

    def start(self):
        if not self._prober_started:
            # ONE prober thread per member: a probe of a hard-down
            # TCP member can block for the client's full retry
            # budget, and a shared serial sweep would starve every
            # other member's breaker/draining freshness of exactly
            # the signal DN_ROUTER_PROBE_MS promises
            self._prober_started = True
            with self._swap_lock:
                for name, st in list(self.states.items()):
                    self._start_prober(name, st)
        return self

    def stop(self):
        self._stop.set()
        for st in list(self.states.values()):
            st.gone.set()
        for t in self._prober_threads:
            t.join(2.0)
        self._prober_threads = []
        self._prober_started = False

    def update_topology(self, topology):
        """Swap the serving map while live (the dynamic-topology
        cutover).  Departed members are retired — prober thread
        stopped (MemberState.gone), pooled connection closed and
        evicted — new members get fresh states (and probers when
        probing runs), and a retained member whose endpoint moved
        drops its old connection.  In-flight scatters finish on the
        topology they snapshotted; members that already cut over
        reject them with the epoch-mismatch contract and the server
        retries under the new map."""
        from . import pool as mod_pool
        with self._swap_lock:
            new_names = set(topology.member_names())
            kept_endpoints = {topology.endpoint(n)
                              for n in new_names}
            for name in list(self.states):
                if name in new_names:
                    continue
                st = self.states.pop(name)
                st.gone.set()
                if st.endpoint not in kept_endpoints:
                    mod_pool.get().close_endpoint(st.endpoint)
            for name in sorted(new_names):
                st = self.states.get(name)
                if st is None:
                    st = MemberState(
                        name, topology.endpoint(name),
                        Breaker(self.conf['failures'],
                                self.conf['cooldown_ms'],
                                name=name))
                    self.states[name] = st
                    if self._prober_started:
                        self._start_prober(name, st)
                elif st.endpoint != topology.endpoint(name):
                    old_ep = st.endpoint
                    st.endpoint = topology.endpoint(name)
                    if old_ep not in kept_endpoints:
                        mod_pool.get().close_endpoint(old_ep)
            self.topo = topology
        self._bump('epoch_updates')
        obs_trace.event('router.topology', epoch=topology.epoch)

    # -- health probing ---------------------------------------------------

    def _start_prober(self, name, st):
        # call with _swap_lock held.  Prune exited probers (departed
        # members') first — a long-lived member under topology churn
        # must not accumulate dead Thread objects forever
        self._prober_threads = [t for t in self._prober_threads
                                if t.is_alive()]
        t = threading.Thread(
            target=self._probe_loop, args=(name, st),
            name='dn-router-probe-%s' % name, daemon=True)
        t.start()
        self._prober_threads.append(t)

    def _probe_loop(self, name, st):
        from . import client as mod_client
        period = self.conf['probe_ms'] / 1000.0
        while not st.gone.wait(period):
            if self._stop.is_set():
                return
            if name == self.member:
                st.note_health({'ok': True,
                                'draining': self.self_draining()})
                continue
            doc = mod_client.health(st.endpoint,
                                    timeout_s=min(
                                        5.0, period * 4 + 1.0))
            if self._stop.is_set() or st.gone.is_set():
                return
            st.note_health(doc)

    def probe_once(self):
        """One synchronous probe sweep (tests, and a cold router that
        wants member state before its first scatter)."""
        from . import client as mod_client
        for name, st in list(self.states.items()):
            if name == self.member:
                st.note_health({'ok': True,
                                'draining': self.self_draining()})
            else:
                st.note_health(mod_client.health(st.endpoint,
                                                 timeout_s=5.0))

    # -- accounting -------------------------------------------------------

    def _bump(self, name, n=1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n
        obs_metrics.inc('router_%s_total' % name, n)

    def _observe_latency(self, ms):
        with self._latency_lock:
            self._latency.observe(ms)
        obs_metrics.observe('router_partial_ms', ms)

    def _hedge_delay_s(self):
        """The hedge trigger: the larger of DN_ROUTER_HEDGE_MS and
        the observed p95 partial latency (a hedge should chase the
        tail, not the median); None when hedging is disabled."""
        floor_ms = self.conf['hedge_ms']
        if floor_ms <= 0:
            return None
        with self._latency_lock:
            p95 = self._latency.quantile(0.95) \
                if self._latency.total >= 8 else None
        return max(floor_ms, p95 or 0.0) / 1000.0

    def stats_doc(self):
        with self._lock:
            counters = dict(self._counters)
        return {
            'member': self.member,
            'epoch': self.topo.epoch,
            'assign': self.topo.assign,
            'partitions_owned': self.topo.partitions_of(self.member),
            'partitions': len(self.topo.partitions),
            'counters': counters,
            'members': {name: st.snapshot()
                        for name, st in self.states.items()},
        }

    # -- replica ranking --------------------------------------------------

    def _rank(self, replicas, write_shaped=False):
        """Dispatch preference: healthy members first (self preferred
        — a local partial never pays the socket), draining members
        demoted, open-breaker members last-resort.  `write_shaped`
        additionally demotes read-only (disk-critical ``degraded_ro``)
        members: they keep serving queries byte-identically, so READ
        dispatch ranks them exactly like healthy members, but a
        write-shaped op would only bounce off their disk_full
        rejection.  Returns the full list — a last-resort member is
        still better than a degraded response."""
        def score(name):
            st = self.states.get(name)
            if st is None:
                # left the topology mid-scatter: worst rank, and the
                # dial itself fails cleanly into the failover path
                return (4, 1, replicas.index(name))
            snap = st.breaker.snapshot()
            with st.lock:
                draining = st.draining
                degraded_ro = st.degraded_ro
            if name == self.member:
                draining = draining or self.self_draining()
                degraded_ro = degraded_ro or self.self_degraded()
            penalty = 0
            if draining:
                penalty += 1
            if write_shaped and degraded_ro:
                penalty += 1
            if snap['state'] == Breaker.OPEN:
                penalty += 2
            return (penalty, 0 if name == self.member else 1,
                    replicas.index(name))
        return sorted(replicas, key=score)

    def rank_for_write(self, replicas):
        """Replica preference for write-shaped dispatch (remote
        builds, repair/handoff landing targets): read-only members
        rank behind writable ones."""
        return self._rank(replicas, write_shaped=True)

    # -- partial fetch ----------------------------------------------------

    def _fetch_one(self, name, pid, partial_req, timeout_s,
                   force=False):
        """One partial attempt at one member; returns the shard list
        or raises (DNError for member-reported failures, OSError/
        ValueError for transport, _BreakerOpen for a suppressed
        dial).  Breaker accounting happens here.  `force` bypasses
        the breaker gate (outcomes still feed it): the exhaustion
        path force-dials suppressed replicas before degrading — an
        open breaker must never turn a blip into a guaranteed
        outage."""
        from . import client as mod_client
        t0 = time.monotonic()
        if name == self.member:
            with obs_trace.span('router.partial', member=name,
                                partition=pid, local=True):
                shards = self.local_exec(partial_req['partitions'],
                                         partial_req)
            self._bump('partials_local')
            self._observe_latency((time.monotonic() - t0) * 1000.0)
            return shards
        st = self.states.get(name)
        if st is None:
            raise DNError('member "%s" left the topology' % name)
        if not force and not st.breaker.allow():
            self._bump('breaker_skips')
            raise _BreakerOpen(name)
        # trace propagation over the pooled v2 path: the partial
        # carries the active trace id and asks for the member's span
        # subtree, exactly like the v1 `--remote` client path — a
        # traced routed query yields ONE joined tree spanning router
        # and members (the member's subtree grafts under this
        # router.partial span below)
        tctx = obs_trace.current_trace()
        if tctx is not None and 'trace' not in partial_req:
            partial_req = dict(partial_req,
                               trace={'id': tctx.trace_id,
                                      'want': True})
        try:
            with obs_trace.span('router.partial', member=name,
                                partition=pid):
                # partials ride the pooled persistent connection (one
                # socket per member, multiplexed across partitions
                # and concurrent scatters) — no dial per partial
                rc, header, out, err = mod_client.request_bytes(
                    st.endpoint, partial_req, timeout_s=timeout_s,
                    pooled=True)
                if tctx is not None:
                    mod_client.graft_remote_trace(tctx, header)
        except (OSError, ValueError, DNError) as e:
            st.breaker.record_failure()
            raise DNError('member "%s"' % name,
                          cause=DNError(str(e)))
        if rc != 0:
            # the member answered: it is alive (busy/draining/epoch
            # mismatch are retryable rejections, not breaker food)
            if header.get('retryable'):
                st.breaker.record_success()
            else:
                st.breaker.record_failure()
            msg = err.decode('utf-8', 'replace').strip() or \
                'partial failed'
            e = DNError('member "%s": %s' % (name, msg))
            if header.get('retryable'):
                e.retryable = True
                e.retry_after_ms = header.get('retry_after_ms')
            hstats = header.get('stats') or {}
            if hstats.get('epoch_mismatch'):
                # the member serves a different epoch than this
                # scatter's snapshot: surfaced so scatter() can tell
                # a stale MAP from a dead member
                e.epoch_mismatch = True
                e.current_epoch = hstats.get('current_epoch')
            if hstats.get('corrupt_shard'):
                # the member detected (or is missing) a corrupt
                # shard: it is ALIVE and self-healing — the failover
                # to the next replica is the whole contract (counted
                # uniformly in _fetch_partition, which also sees the
                # LOCAL partial's ShardIntegrityError)
                e.corrupt_shard = hstats.get('corrupt_shard')
            raise e
        st.breaker.record_success()
        try:
            doc = json.loads(out.decode('utf-8'))
            shards = doc['shards']
        except (ValueError, KeyError, UnicodeDecodeError) as e:
            raise DNError('member "%s": malformed partial response'
                          % name, cause=DNError(str(e)))
        self._bump('partials_remote')
        self._observe_latency((time.monotonic() - t0) * 1000.0)
        return shards

    def _fetch_partition(self, pid, partial_req, scope, topo):
        """Fetch one partition's partial with failover + hedging
        under the scatter's topology snapshot `topo`.  Returns the
        shard list; raises DNError when every replica failed."""
        with mod_vpipe.adopt_scope(scope):
            mod_faults.fire('router.dispatch')
            ranked = self._rank(topo.replicas(pid))
            timeout_s = self.conf['fetch_timeout_s']
            if partial_req.get('deadline_ms'):
                # a propagated deadline bounds the fetch too: waiting
                # longer than the client will cannot help
                timeout_s = min(
                    timeout_s,
                    partial_req['deadline_ms'] / 1000.0 + 1.0)
            resultq = queue.Queue()
            launched = []

            def launch(name, role, force=False):
                launched.append(name)

                def body():
                    with mod_vpipe.adopt_scope(scope):
                        try:
                            resultq.put(
                                (role, name, True,
                                 self._fetch_one(name, pid,
                                                 partial_req,
                                                 timeout_s,
                                                 force=force)))
                        except _BreakerOpen:
                            resultq.put((role, name, False, None))
                        except (DNError, Exception) as e:
                            resultq.put((role, name, False, e))
                t = threading.Thread(
                    target=body, daemon=True,
                    name='dn-router-p%s-%s' % (pid, name))
                t.start()

            errors = []
            skipped = []
            hedge_delay = self._hedge_delay_s()
            hedged = False
            forced = False
            outstanding = 1
            nxt = 1
            launch(ranked[0], 'primary')
            deadline = time.monotonic() + timeout_s * len(ranked) + 5
            while outstanding > 0:
                wait = deadline - time.monotonic()
                if wait <= 0:
                    break
                if not hedged and hedge_delay is not None and \
                        nxt < len(ranked):
                    wait = min(wait, hedge_delay)
                try:
                    role, name, ok, value = resultq.get(timeout=wait)
                except queue.Empty:
                    if not hedged and hedge_delay is not None and \
                            nxt < len(ranked):
                        # the in-flight partial is slower than the
                        # tail: duplicate it at the next replica and
                        # keep whichever answers first
                        hedged = True
                        self._bump('hedges_fired')
                        obs_trace.event('router.hedge',
                                        partition=pid,
                                        member=ranked[nxt])
                        if obs_events.enabled():
                            obs_events.emit('router.hedge',
                                            partition=pid,
                                            to=ranked[nxt])
                        launch(ranked[nxt], 'hedge')
                        nxt += 1
                        outstanding += 1
                    continue
                outstanding -= 1
                if ok:
                    if hedged:
                        # the loser is abandoned; its eventual result
                        # is discarded — account the cancellation
                        if role == 'hedge':
                            self._bump('hedges_won')
                        else:
                            self._bump('hedges_wasted')
                    return value
                if value is not None:
                    errors.append(value)
                    if getattr(value, 'corrupt_shard', None) \
                            is not None:
                        # a replica rejected because its shard bytes
                        # are damaged (it repairs itself meanwhile):
                        # the failover below is working as designed
                        self._bump('corrupt_failovers')
                else:
                    skipped.append(name)
                if nxt < len(ranked):
                    self._bump('failovers')
                    obs_trace.event('router.failover', partition=pid,
                                    to=ranked[nxt])
                    if obs_events.enabled():
                        obs_events.emit(
                            'router.failover', partition=pid,
                            to=ranked[nxt], frm=name,
                            error=getattr(value, 'message', None)
                            if value is not None else 'breaker open')
                    launch(ranked[nxt], 'failover')
                    nxt += 1
                    outstanding += 1
                elif outstanding == 0 and skipped and not forced:
                    # every remaining candidate was suppressed by an
                    # open breaker: before degrading, force one real
                    # dial at each — a breaker still inside its
                    # cooldown must never turn a transient blip into
                    # a guaranteed outage when it holds the only
                    # live replica
                    forced = True
                    for skip_name in skipped:
                        self._bump('breaker_forced_dials')
                        obs_trace.event('router.breaker_force',
                                        partition=pid,
                                        member=skip_name)
                        if obs_events.enabled():
                            obs_events.emit('router.breaker_force',
                                            partition=pid,
                                            to=skip_name)
                        launch(skip_name, 'forced', force=True)
                        outstanding += 1
            detail = '; '.join(
                getattr(e, 'message', None) or str(e)
                for e in errors[-2:]) or 'no replica reachable'
        e = DNError('partition %d: all replicas failed '
                    '(tried %s): %s'
                    % (pid, ','.join(launched), detail))
        hints = [getattr(x, 'retry_after_ms', None) for x in errors]
        hints = [h for h in hints if h is not None]
        if hints:
            e.retry_after_ms = max(hints)
        mism = [x for x in errors
                if getattr(x, 'epoch_mismatch', False)]
        if mism:
            # at least one replica is serving a different epoch: the
            # scatter's map may be stale, not the partition dead
            e.epoch_mismatch = True
            epochs = [getattr(x, 'current_epoch', None)
                      for x in mism]
            epochs = [v for v in epochs if isinstance(v, int)]
            if epochs:
                e.current_epoch = max(epochs)
        raise e

    # -- scatter-gather ---------------------------------------------------

    def scatter(self, ds, dsname, query, interval, req,
                deadline_at=None):
        """Fan `req` (an index query) across every partition and
        merge.  Returns (ScanResult, missing_partition_ids); raises
        RouterPartitionError in DN_ROUTER_PARTIAL=error mode when any
        partition has no live replica.  `deadline_at` (monotonic) is
        the routed request's propagated deadline: the REMAINING
        budget rides every member partial as its deadline_ms, so a
        member sheds partials it cannot finish in time instead of
        computing past the client's patience."""
        from ..aggr import Aggregator
        from ..datasource_file import ScanResult
        from ..vpipe import Pipeline

        self._bump('scatters')
        # ONE topology snapshot per scatter: every partial of this
        # query runs under the same epoch's partition map, so the
        # merge can never mix two epochs' shard assignments even
        # while a cutover swaps self.topo mid-flight
        topo = self.topo
        pids = topo.partition_ids()
        partial_req = {
            'op': 'query_partial', 'ds': dsname,
            'config': req.get('config'),
            'interval': interval,
            'queryconfig': req.get('queryconfig'),
            'epoch': topo.epoch,
        }
        if req.get('tenant'):
            # fairness identity rides the hop: a member under load
            # sheds per originating tenant, not per router
            partial_req['tenant'] = req['tenant']
        if deadline_at is not None:
            remaining_ms = int((deadline_at - time.monotonic())
                               * 1000.0)
            partial_req['deadline_ms'] = max(1, remaining_ms)
        scope = mod_vpipe.current_scope()
        results = {}
        failures = {}
        threads = []
        lock = threading.Lock()

        def fetch(pid):
            preq = dict(partial_req, partitions=[pid])
            try:
                shards = self._fetch_partition(pid, preq, scope,
                                               topo)
                with lock:
                    results[pid] = shards
            except DNError as e:
                with lock:
                    failures[pid] = e
            except Exception as e:
                # a partition must NEVER drop out silently: any
                # non-DNError bug in the fetch path becomes a named
                # failure (degraded response), not a short merge
                with lock:
                    failures[pid] = DNError(
                        'partition %d: internal fetch error: %r'
                        % (pid, e))

        with obs_trace.span('router.scatter', partitions=len(pids)):
            for pid in pids:
                t = threading.Thread(target=fetch, args=(pid,),
                                     daemon=True,
                                     name='dn-scatter-%s' % pid)
                threads.append(t)
                t.start()
            for t in threads:
                t.join()

        missing = sorted(failures)
        if missing:
            mism = [p for p in missing
                    if getattr(failures[p], 'epoch_mismatch', False)]
            if mism:
                # a member is on a different epoch: this is OUR map
                # being stale, not a dead partition — raise the
                # resync signal instead of a degraded result in
                # EITHER partial mode (serving a partial merge under
                # a stale map could drop partitions that moved)
                self._bump('epoch_mismatches')
                obs_metrics.inc('topo_epoch_mismatch_total')
                epochs = [getattr(failures[p], 'current_epoch', None)
                          for p in mism]
                epochs = [v for v in epochs if isinstance(v, int)]
                raise TopologyEpochError(
                    failures[mism[0]].message,
                    current_epoch=max(epochs) if epochs else None)
            self._bump('degraded')
            detail = '; '.join(
                failures[p].message for p in missing[:2])
            if obs_events.enabled():
                obs_events.emit('router.degraded',
                                partitions=list(missing),
                                error=detail)
            hints = [getattr(failures[p], 'retry_after_ms', None)
                     for p in missing]
            hints = [h for h in hints if h is not None]
            if self.conf['partial'] == 'error':
                raise RouterPartitionError(
                    missing, detail,
                    retry_after_ms=max(hints) if hints else None)
            self._bump('partial_responses')

        # merge in GLOBAL find order: every member reported its shards
        # in its own find order; the path-component sort reproduces
        # the single-process walk order across partitions, so string
        # keys first-occur in the identical order
        pipeline = Pipeline()
        index_list = pipeline.stage('Index List')
        aggr = Aggregator(query,
                          stage=pipeline.stage(
                              'Index Result Aggregator'))
        all_shards = []
        for pid in sorted(results):
            all_shards.extend(results[pid])
        all_shards.sort(key=lambda s: tuple(s[0].split('/')))
        with obs_trace.span('router.merge', shards=len(all_shards)):
            mod_faults.fire('router.merge')
            seen = set()
            aggr_stage = aggr.stage
            for relpath, items in all_shards:
                if relpath in seen:
                    # partitions are disjoint by construction; a
                    # shard arriving twice means mismatched topologies
                    # slipped past the epoch gate — refuse to
                    # double-count
                    raise DNError('cluster merge: shard "%s" '
                                  'reported by two partitions '
                                  '(topology mismatch?)' % relpath)
                seen.add(relpath)
                npts = len(items)
                if npts == 0:
                    continue
                index_list.bump('ninputs', npts)
                index_list.bump('noutputs', npts)
                aggr_stage.bump('ninputs', npts)
                aggr.merge_key_items([(tuple(k), w)
                                      for k, w in items])
        index_list.bump_hidden('index shards queried',
                               len(all_shards))
        return (ScanResult(pipeline, points=aggr.points(),
                           query=query), missing)
