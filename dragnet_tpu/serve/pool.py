"""Client-side pooled persistent connections for the serve protocol
(v2, serve/protocol.py).

Before this PR every `--remote` request, every router partial, and
every health/stats probe dialed a fresh socket — the wrong shape for
high fan-in (each dial burns a round trip and a file descriptor, and
a SYN-backlog blip reads as member death to the circuit breaker).
The pool keeps ONE long-lived multiplexed connection per endpoint:

* `exchange()` assigns the request a connection-unique id, sends one
  v2 frame, and parks on a per-request waiter; a background reader
  thread demultiplexes response frames by id, so any number of
  threads share the connection concurrently (the router's whole
  partial fan-out rides one socket per member).
* Negotiation is transparent: a v1 server ignores the proto/id
  fields, answers a correct v1 response (no `id`) and closes — the
  reader delivers it to the sole outstanding waiter, the endpoint is
  marked v1, and future requests fall back to dial-per-request
  (serve/client.py handles that path).
* Failure classification preserves the retry contract: a connection
  that dies BEFORE a waiter's header is pre-commit (plain OSError —
  the caller's retry loop re-dials); one that dies mid-payload AFTER
  that waiter's header arrived is post-commit (RemoteTransportError —
  never silently retried).

The pool is process-global (`get()`); `reset()` closes everything
(tests, and forked children must not share sockets).
"""

import itertools
import json
import socket
import threading
import time

from ..errors import DNError
from .. import faults as mod_faults
from ..vpipe import counter_bump
from . import protocol as mod_protocol


class _Waiter(object):
    __slots__ = ('event', 'header', 'payload', 'error')

    def __init__(self):
        self.event = threading.Event()
        self.header = None
        self.payload = b''
        self.error = None


def _transport_error():
    from . import client as mod_client
    return mod_client.RemoteTransportError


class PooledConn(object):
    """One endpoint's persistent multiplexed connection."""

    def __init__(self, endpoint, connect_timeout_s):
        from . import client as mod_client
        # client._connect fires the client.connect fault seam and
        # applies the connect deadline; a pooled conn then goes fully
        # blocking — per-request deadlines are the waiters' timeouts,
        # and an idle-reaped conn just shows up as EOF to the reader
        self.endpoint = endpoint
        self.sock = mod_client._connect(endpoint, None,
                                        connect_timeout_s)
        self.sock.settimeout(None)
        self._file = self.sock.makefile('rb')
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._waiters = {}
        self._ids = itertools.count(1)
        # ids in actual wire order — only needed until the FIRST
        # response settles the peer's protocol (a v1 answer goes to
        # the oldest-sent waiter); cleared and no longer tracked once
        # the conn is confirmed v2
        self._sent_order = []
        self._confirmed_v2 = False
        self.broken = False
        self.saw_v1 = False
        self.last_delivery = time.monotonic()
        t = threading.Thread(target=self._reader,
                             name='dn-pool-reader', daemon=True)
        t.start()

    # -- reader (demux) ----------------------------------------------------

    def _reader(self):
        err = None
        try:
            while True:
                line = self._file.readline(
                    mod_protocol.MAX_FRAME_BYTES)
                if not line:
                    break
                header = json.loads(line.decode('utf-8'))
                self.last_delivery = time.monotonic()
                nout = int(header.get('nout', 0))
                nerr = int(header.get('nerr', 0))
                rid = header.get('id')
                payload, short = self._read_payload(nout + nerr)
                if short:
                    # THIS response's header arrived but its payload
                    # was cut: post-commit for its waiter alone
                    self._deliver(rid, None, None, _transport_error()(
                        'remote response truncated mid-payload'))
                    break
                if rid is None:
                    if header.get('sub') is not None:
                        # a server-initiated subscription push frame:
                        # never a pool concern (subscribe_stream uses
                        # its own dedicated connection) — a stray one
                        # here means a subscription leaked onto the
                        # pooled conn; discard it rather than
                        # misreading it as a v1 downgrade
                        counter_bump('remote pool push discarded')
                        continue
                    # a v1 server answered our v2 frame: correct
                    # response, no multiplexing — deliver to the
                    # oldest-sent waiter and downgrade the endpoint
                    self.saw_v1 = True
                    self._deliver_v1(header, payload)
                    break
                if not self._confirmed_v2:
                    self._confirmed_v2 = True
                    with self._lock:
                        self._sent_order = []
                self._deliver(rid, header, payload, None)
        except (OSError, ValueError) as e:
            err = e
        finally:
            self._fail_all(err, from_reader=True)

    def _read_payload(self, size):
        chunks = []
        left = size
        while left > 0:
            chunk = self._file.read(min(1 << 16, left))
            if not chunk:
                return b''.join(chunks), True
            chunks.append(chunk)
            left -= len(chunk)
        return b''.join(chunks), False

    def _deliver(self, rid, header, payload, error):
        with self._lock:
            w = self._waiters.pop(rid, None)
        if w is None:
            return               # timed-out waiter: discard
        w.header, w.payload, w.error = header, payload, error
        w.event.set()

    def _deliver_v1(self, header, payload):
        """A v1 server answered the FIRST request line it read off
        this connection — sends are serialized under _wlock, so that
        is the oldest entry of _sent_order still waiting.  Deliver
        to exactly that waiter (any others fail pre-commit when the
        reader exits, and retry against the now-downgraded
        endpoint)."""
        with self._lock:
            rid = None
            while self._sent_order:
                cand = self._sent_order.pop(0)
                if cand in self._waiters:
                    rid = cand
                    break
        if rid is not None:
            self._deliver(rid, header, payload, None)

    def _fail_all(self, err, from_reader=False):
        """EOF/transport death: every still-parked waiter never saw
        its header — pre-commit, retry-safe.  Only the reader thread
        may close the makefile (close() takes the buffer lock a
        reader blocked in readline() already holds — another thread
        closing it would deadlock); everyone else shuts the SOCKET
        down, which wakes that blocked read with EOF."""
        self.broken = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        if from_reader:
            try:
                self._file.close()
            except (OSError, ValueError):
                pass
        with self._lock:
            waiters = list(self._waiters.values())
            self._waiters.clear()
        detail = str(err) if err is not None else \
            'pooled connection closed before the response header'
        for w in waiters:
            if not w.event.is_set():
                w.error = OSError(detail)
                w.event.set()

    # -- exchange ----------------------------------------------------------

    def exchange(self, req, timeout_s, phase):
        """Send one request, wait for its response.  Returns
        (header, payload_bytes).  Raises OSError pre-commit,
        RemoteTransportError post-commit.  `phase['phase']` flips to
        'exchange' once the frame is on the wire (the retry loop's
        reached-a-server classification)."""
        if self.broken:
            raise OSError('pooled connection is broken')
        rid = next(self._ids)
        w = _Waiter()
        with self._lock:
            if self.broken:
                raise OSError('pooled connection is broken')
            self._waiters[rid] = w
        # the connection is established: like _open_request, anything
        # past here counts as having reached a server (the retry
        # loop's RemoteRetryExhausted-vs-Unreachable classification)
        phase['phase'] = 'exchange'
        try:
            frame = mod_protocol.encode_request(req, rid)
            mod_faults.fire('client.send')
            with self._wlock:
                if not self._confirmed_v2:
                    # record wire order BEFORE the bytes leave: a
                    # fast v1 peer can answer and EOF before this
                    # thread runs again, and _deliver_v1 must find
                    # the rid or the response is dropped on the
                    # floor (a stale entry from a failed send is
                    # harmless — _deliver_v1 skips rids with no
                    # parked waiter)
                    with self._lock:
                        self._sent_order.append(rid)
                self.sock.sendall(frame)
            sent_at = time.monotonic()
            mod_faults.fire('client.recv')
            if not w.event.wait(timeout_s):
                # OUR response never came.  Kill the shared conn only
                # when it delivered NOTHING since our send — then it
                # is plausibly wedged; if other requests' frames kept
                # arriving the conn is demonstrably alive and a
                # short-timeout probe must not fail every concurrent
                # in-flight exchange on it
                if self.last_delivery < sent_at:
                    self._fail_all(OSError(
                        'pooled exchange timed out after %.1fs'
                        % timeout_s))
                raise OSError('pooled exchange timed out after %.1fs'
                              % timeout_s)
            if w.error is not None:
                raise w.error
            return w.header, w.payload
        finally:
            with self._lock:
                self._waiters.pop(rid, None)


class ConnectionPool(object):
    """Endpoint -> PooledConn, with v1 downgrade memory and
    reuse/dial accounting (bench-fanin reads these)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._conns = {}
        self._v1 = set()
        self._pid = None
        self.counters = {'dials': 0, 'reuses': 0, 'downgrades': 0,
                         'invalidated': 0, 'evicted': 0}

    def _bump(self, name, n=1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def is_v1(self, endpoint):
        with self._lock:
            self._check_pid()
            return endpoint in self._v1

    def _check_pid(self):
        # a forked child must never share the parent's sockets or
        # reader threads: start fresh (call with _lock held)
        import os
        pid = os.getpid()
        if self._pid != pid:
            self._pid = pid
            self._conns = {}
            self._v1 = set()

    def _get(self, endpoint, connect_timeout_s):
        with self._lock:
            self._check_pid()
            conn = self._conns.get(endpoint)
            if conn is not None and not conn.broken:
                self.counters['reuses'] += 1
                return conn
        # dial outside the pool lock (a dead endpoint must not stall
        # other endpoints' exchanges), then publish
        conn = PooledConn(endpoint, connect_timeout_s)
        with self._lock:
            current = self._conns.get(endpoint)
            if current is not None and not current.broken:
                # someone else dialed first: use theirs
                conn._fail_all(OSError('redundant dial'))
                self.counters['reuses'] += 1
                return current
            self._conns[endpoint] = conn
            self.counters['dials'] += 1
        return conn

    def invalidate(self, endpoint, conn=None):
        with self._lock:
            current = self._conns.get(endpoint)
            if current is not None and \
                    (conn is None or current is conn):
                self._conns.pop(endpoint, None)
                self.counters['invalidated'] += 1
                current.broken = True
        if conn is not None:
            conn._fail_all(OSError('connection invalidated'))

    def exchange(self, endpoint, req, timeout_s, connect_timeout_s,
                 phase):
        """One request over the pooled connection.  Returns (header,
        payload).  Raises OSError/ValueError pre-commit (retry-safe),
        RemoteTransportError post-commit.  Callers must check
        is_v1() first and use the dial-per-request path for
        downgraded endpoints."""
        conn = self._get(endpoint, connect_timeout_s)
        try:
            header, payload = conn.exchange(req, timeout_s, phase)
        except (DNError, OSError, ValueError):
            # even a failed exchange may have LEARNED the endpoint is
            # v1 (one concurrent first-contact waiter got the real
            # response; the rest fail here pre-commit): record the
            # downgrade so retries take the dial path immediately
            if conn.saw_v1:
                self._mark_v1(endpoint)
            self.invalidate(endpoint, conn)
            raise
        if conn.saw_v1:
            self._mark_v1(endpoint)
            self.invalidate(endpoint, conn)
        return header, payload

    def _mark_v1(self, endpoint):
        with self._lock:
            if endpoint not in self._v1:
                self._v1.add(endpoint)
                self.counters['downgrades'] += 1
        counter_bump('remote pool v1 downgrades')

    def close_endpoint(self, endpoint):
        """Retire an endpoint that left the serving topology: its
        pooled connection closes NOW (waking the demux reader and any
        parked waiters with a clean pre-commit error) and its
        v1-downgrade memory drops, so a member re-added later starts
        fresh.  Without this, a departed member's socket, reader
        thread, and downgrade verdict linger until process exit.
        Returns True when a live connection was actually closed."""
        with self._lock:
            conn = self._conns.pop(endpoint, None)
            self._v1.discard(endpoint)
            if conn is not None:
                self.counters['evicted'] += 1
        if conn is not None:
            conn._fail_all(OSError('endpoint removed from topology'))
            return True
        return False

    def reset(self):
        with self._lock:
            conns = list(self._conns.values())
            self._conns = {}
            self._v1 = set()
        for conn in conns:
            conn._fail_all(OSError('pool reset'))

    def stats(self):
        with self._lock:
            doc = dict(self.counters)
            doc['open'] = sum(1 for c in self._conns.values()
                              if not c.broken)
        return doc


_POOL = ConnectionPool()


def get():
    """The process-global pool."""
    return _POOL
