"""The `dn serve` readiness front end: one selector thread owns every
client connection, so thousands of idle connections cost zero threads
and a half-dead peer can never pin a worker.

PR 5's server spent one thread per accepted connection, parked in a
blocking ``makefile('rb').readline()`` — a peer that sent half a
header (slow-loris, wedged NIC, dead VM) pinned that thread for the
socket timeout, and enough of them pinned the process.  This loop
replaces that shape (Diba's transport/execution split: transport is a
stage of its own):

* **Reads** are non-blocking: bytes land in a per-connection
  LineBuffer; each complete request line is handed to the server's
  dispatcher (which spawns/queues execution work — never blocks the
  loop).
* **Writes** are queued: workers enqueue response frames with
  ``send()`` (thread-safe, never blocks on the peer); the loop drains
  them as the socket accepts bytes, so a slow reader costs queue
  memory, not a worker.
* **Deadlines and reaping** ride the loop's tick:
  - a connection holding a PARTIAL request line longer than
    ``read_deadline_ms`` is reaped (the slow-loris bound),
  - a response pending longer than ``write_deadline_ms`` is reaped
    (the slow-reader bound),
  - a connection with no traffic and no in-flight work for
    ``idle_ms`` is reaped (the fd-leak bound).  0 disables each.

The loop knows framing only as "newline-terminated lines"; protocol
interpretation (v1 vs v2, ids, payloads) stays in server.py, and
execution stays in the worker threads behind admission control.
"""

import os
import selectors
import socket
import struct
import threading
import time
from collections import deque

from . import protocol as mod_protocol

_RECV_CHUNK = 1 << 16


def peer_identity(sock):
    """The transport-level tenant hint for an accepted socket: the
    peer uid for unix sockets (SO_PEERCRED), the peer address for
    TCP.  Requests may override with an explicit `tenant` field."""
    try:
        if sock.family == socket.AF_UNIX:
            creds = sock.getsockopt(socket.SOL_SOCKET,
                                    socket.SO_PEERCRED,
                                    struct.calcsize('3i'))
            pid, uid, gid = struct.unpack('3i', creds)
            return 'uid:%d' % uid
        host, port = sock.getpeername()[:2]
        return 'ip:%s' % host
    except (OSError, AttributeError, ValueError):
        return 'peer:unknown'


class Conn(object):
    """One accepted connection's loop-side state.  The loop thread
    owns everything except `inflight_ids`, which workers also touch
    (under `ids_lock`) when they retire a completed request id."""

    __slots__ = ('sock', 'fd', 'peer', 'rbuf', 'wbufs', 'wpos',
                 'proto', 'inflight', 'close_after_flush', 'closed',
                 'last_activity', 'read_started', 'write_started',
                 'inflight_ids', 'ids_lock', 'paused', 'registered',
                 'pinned')

    def __init__(self, sock, peer):
        self.sock = sock
        self.fd = sock.fileno()
        self.peer = peer
        self.rbuf = mod_protocol.LineBuffer()
        self.wbufs = deque()
        self.wpos = 0
        self.proto = None           # unknown until the first frame
        self.inflight = 0           # dispatched, not yet responded
        self.close_after_flush = False
        self.closed = False
        now = time.monotonic()
        self.last_activity = now
        self.read_started = None    # partial frame's first byte
        self.write_started = None   # oldest unflushed response
        self.inflight_ids = set()   # v2 duplicate-id guard
        self.ids_lock = threading.Lock()
        self.paused = False         # v1: one request, then no reads
        self.registered = False     # currently in the selector
        self.pinned = 0             # live subscriptions: no idle reap

    def pending_write(self):
        return bool(self.wbufs)


class IOLoop(object):
    """The selector loop.  `on_request(conn, line)` runs ON the loop
    thread for every complete request line and must return quickly
    (parse + hand off); `on_overflow(conn)` likewise when a frame
    exceeds the size bound.  `on_accept(conn)` may veto a connection
    by returning False (fault injection)."""

    def __init__(self, listener, conf, on_request, on_overflow=None,
                 on_accept=None, on_close=None, log=None):
        self.listener = listener
        self.conf = conf
        self.on_request = on_request
        self.on_overflow = on_overflow
        self.on_accept = on_accept
        # on_close(conn) fires on the loop thread for every closed
        # connection — how a SubscriptionManager learns its peer died
        # (serve/subscribe.py).  Must be quick and must not raise.
        self.on_close = on_close
        self.log = log
        self._sel = selectors.DefaultSelector()
        listener.setblocking(False)
        self._sel.register(listener, selectors.EVENT_READ, 'accept')
        r, w = os.pipe()
        os.set_blocking(r, False)
        os.set_blocking(w, False)
        self._wake_r, self._wake_w = r, w
        self._sel.register(r, selectors.EVENT_READ, 'wake')
        self._actions = deque()
        self._alock = threading.Lock()
        self._accepting = True
        self._shutdown_at = None     # flush deadline once stopping
        self._finished = threading.Event()
        self._thread = None
        self._conns = {}
        self._clock = threading.Lock()
        self.counters = {'conns_accepted': 0, 'conns_closed': 0,
                         'frames_in': 0, 'reaped_idle': 0,
                         'reaped_read_deadline': 0,
                         'reaped_write_deadline': 0,
                         'oversized_frames': 0, 'v2_conns': 0}

    # -- cross-thread API --------------------------------------------------

    def _wake(self):
        try:
            os.write(self._wake_w, b'x')
        except (BlockingIOError, OSError):
            pass

    def _enqueue(self, action):
        with self._alock:
            self._actions.append(action)
        self._wake()

    def send(self, conn, data, close_after=False, completes=False):
        """Queue response bytes on `conn` (thread-safe; never blocks
        on the peer).  `completes` marks the end of one dispatched
        request (decrements the in-flight count the reaper consults);
        `close_after` closes the connection once the bytes flush
        (v1's one-shot contract)."""
        self._enqueue(('send', conn, data, close_after, completes))

    def close_conn(self, conn, completes=False):
        """Close `conn` without a response (fault injection, torn
        frames)."""
        self._enqueue(('close', conn, None, False, completes))

    def pin(self, conn):
        """Exempt `conn` from idle reaping (thread-safe): a
        registered subscriber is QUIET by design — no requests, no
        pending writes between pushes — and must not be garbage-
        collected as an fd leak.  Counted, so overlapping
        subscriptions compose; the read/write deadlines still apply
        (a wedged peer is reaped, pinned or not)."""
        self._enqueue(('pin', conn, None, False, False))

    def unpin(self, conn):
        self._enqueue(('unpin', conn, None, False, False))

    def stop_accepting(self):
        self._enqueue(('stop_accept', None, None, False, False))

    def shutdown(self, flush_s):
        """Stop the loop: drain pending writes for up to `flush_s`,
        then close every connection and exit.  Blocks until the loop
        thread finishes."""
        self._enqueue(('shutdown', None, flush_s, False, False))
        self._finished.wait(flush_s + 5.0)
        if self._thread is not None:
            self._thread.join(2.0)

    def start(self):
        self._thread = threading.Thread(target=self.run,
                                        name='dn-serve-ioloop',
                                        daemon=True)
        self._thread.start()
        return self

    def stats(self):
        with self._clock:
            doc = dict(self.counters)
        doc['conns_open'] = len(self._conns)
        return doc

    def _bump(self, name, n=1):
        with self._clock:
            self.counters[name] = self.counters.get(name, 0) + n

    # -- the loop ----------------------------------------------------------

    def run(self):
        try:
            while True:
                try:
                    events = self._sel.select(0.1)
                except OSError:
                    break
                for key, mask in events:
                    tag = key.data
                    if tag == 'accept':
                        self._accept()
                    elif tag == 'wake':
                        self._drain_wake()
                    else:
                        if mask & selectors.EVENT_READ:
                            self._readable(tag)
                        if mask & selectors.EVENT_WRITE and \
                                not tag.closed:
                            self._writable(tag)
                self._drain_actions()
                self._tick()
                if self._shutdown_at is not None:
                    flushed = not any(c.pending_write() or c.inflight
                                      for c in self._conns.values())
                    if flushed or \
                            time.monotonic() >= self._shutdown_at:
                        break
        finally:
            for conn in list(self._conns.values()):
                self._close(conn)
            try:
                self._sel.close()
            except OSError:
                pass
            for fd in (self._wake_r, self._wake_w):
                try:
                    os.close(fd)
                except OSError:
                    pass
            self._finished.set()

    def _drain_wake(self):
        try:
            while os.read(self._wake_r, 4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def _drain_actions(self):
        while True:
            with self._alock:
                if not self._actions:
                    return
                kind, conn, data, close_after, completes = \
                    self._actions.popleft()
            if kind == 'stop_accept':
                self._stop_accept()
                continue
            if kind == 'shutdown':
                self._stop_accept()
                self._shutdown_at = time.monotonic() + (data or 0)
                continue
            if conn is None or conn.closed:
                continue
            if completes:
                conn.inflight = max(0, conn.inflight - 1)
            if kind == 'pin':
                conn.pinned += 1
                continue
            if kind == 'unpin':
                conn.pinned = max(0, conn.pinned - 1)
                continue
            if kind == 'close':
                self._close(conn)
                continue
            # send
            if data:
                conn.wbufs.append(memoryview(data))
                if conn.write_started is None:
                    conn.write_started = time.monotonic()
            if close_after:
                conn.close_after_flush = True
            conn.last_activity = time.monotonic()
            self._update_interest(conn)
            # opportunistic flush: most responses fit the socket
            # buffer, sparing a selector round-trip
            self._writable(conn)

    def _stop_accept(self):
        if not self._accepting:
            return
        self._accepting = False
        try:
            self._sel.unregister(self.listener)
        except (KeyError, OSError):
            pass
        try:
            self.listener.close()
        except OSError:
            pass

    # -- readiness handlers ------------------------------------------------

    def _accept(self):
        while self._accepting:
            try:
                sock, _ = self.listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            sock.setblocking(False)
            conn = Conn(sock, peer_identity(sock))
            if self.on_accept is not None and \
                    not self.on_accept(conn):
                # vetoed (injected accept fault): the peer sees a
                # reset/EOF — exactly the failure its retry loop
                # exists for
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            self._conns[conn.fd] = conn
            self._bump('conns_accepted')
            self._sel.register(sock, selectors.EVENT_READ, conn)
            conn.registered = True

    def _readable(self, conn):
        if conn.closed or conn.paused:
            return
        try:
            data = conn.sock.recv(_RECV_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn)
            return
        if not data:
            self._close(conn)
            return
        conn.last_activity = time.monotonic()
        conn.rbuf.feed(data)
        try:
            lines = conn.rbuf.take()
        except mod_protocol.FrameError:
            self._bump('oversized_frames')
            if self.on_overflow is not None:
                self.on_overflow(conn)
            else:
                self._close(conn)
            return
        for line in lines:
            if conn.closed or conn.paused:
                break
            self._bump('frames_in')
            conn.inflight += 1
            self.on_request(conn, line)
        if conn.closed:
            return
        if conn.rbuf.pending():
            # the deadline clock starts at the partial frame's FIRST
            # byte and is never reset by later drips — a peer feeding
            # one byte per interval must still be reaped
            if conn.read_started is None:
                conn.read_started = time.monotonic()
        else:
            conn.read_started = None

    def pause_reading(self, conn):
        """v1 backpressure: after its single request, a v1 connection
        reads nothing further (loop thread only)."""
        conn.paused = True
        self._update_interest(conn)

    def _update_interest(self, conn):
        """(Re)register `conn` for exactly the events it needs.  A
        paused connection with nothing to write is UNREGISTERED —
        keeping read interest on a socket we refuse to read (pending
        bytes, or EOF after a peer half-close) would make select()
        return instantly forever and busy-spin the loop thread."""
        if conn.closed:
            return
        events = 0
        if not conn.paused:
            events |= selectors.EVENT_READ
        if conn.pending_write():
            events |= selectors.EVENT_WRITE
        try:
            if not events:
                if conn.registered:
                    self._sel.unregister(conn.sock)
                    conn.registered = False
            elif conn.registered:
                self._sel.modify(conn.sock, events, conn)
            else:
                self._sel.register(conn.sock, events, conn)
                conn.registered = True
        except (KeyError, OSError):
            pass

    def _writable(self, conn):
        while conn.wbufs:
            buf = conn.wbufs[0]
            try:
                n = conn.sock.send(buf[conn.wpos:])
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._close(conn)
                return
            conn.wpos += n
            if conn.wpos >= len(buf):
                conn.wbufs.popleft()
                conn.wpos = 0
            if n == 0:
                break
        if not conn.wbufs:
            conn.write_started = None
            if conn.close_after_flush:
                self._close(conn)
                return
        self._update_interest(conn)

    # -- reaping -----------------------------------------------------------

    def _tick(self):
        now = time.monotonic()
        rd = self.conf.get('read_deadline_ms') or 0
        wd = self.conf.get('write_deadline_ms') or 0
        idle = self.conf.get('idle_ms') or 0
        for conn in list(self._conns.values()):
            if conn.closed:
                continue
            if rd and conn.read_started is not None and \
                    (now - conn.read_started) * 1000.0 >= rd:
                # half a request older than the read deadline: the
                # slow-loris bound — reap without stranding a worker
                self._bump('reaped_read_deadline')
                self._close(conn)
                continue
            if wd and conn.write_started is not None and \
                    (now - conn.write_started) * 1000.0 >= wd:
                self._bump('reaped_write_deadline')
                self._close(conn)
                continue
            if idle and not conn.inflight and not conn.pinned and \
                    not conn.pending_write() and \
                    conn.rbuf.pending() == 0 and \
                    (now - conn.last_activity) * 1000.0 >= idle:
                self._bump('reaped_idle')
                self._close(conn)

    def _close(self, conn):
        if conn.closed:
            return
        conn.closed = True
        self._conns.pop(conn.fd, None)
        self._bump('conns_closed')
        if self.on_close is not None:
            try:
                self.on_close(conn)
            except Exception:
                pass
        if conn.registered:
            try:
                self._sel.unregister(conn.sock)
            except (KeyError, OSError):
                pass
            conn.registered = False
        try:
            conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
