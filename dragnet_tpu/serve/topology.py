"""The static cluster map for scatter-gather serving.

A topology file describes a `dn serve` cluster: its members (name ->
endpoint), its partitions (each a replica set of members), the shard
assignment rule, and an epoch.  Every member loads the SAME file
(`dn serve --cluster=TOPOLOGY.json --member=NAME`); any member can act
as router for an incoming query, scattering partition-scoped partial
queries to the owners and merging the partial aggregates
(serve/router.py).

File format (JSON):

    {
      "epoch": 1,
      "assign": "hash",
      "members": {
        "a": {"endpoint": "/run/dn-a.sock"},
        "b": {"endpoint": "10.0.0.2:9401"},
        "c": {"endpoint": "10.0.0.3:9401"}
      },
      "partitions": [
        {"id": 0, "replicas": ["a", "b"]},
        {"id": 1, "replicas": ["b", "c"]},
        {"id": 2, "replicas": ["c", "a"]}
      ]
    }

* ``epoch`` — integer generation stamp.  Members reject partial
  queries whose epoch differs from their loaded topology (a retryable
  error), so a router and member running different topology files can
  never silently merge mismatched partitions.
* ``assign`` — the shard -> partition rule.  ``hash`` (default):
  crc32 of the shard's file name modulo the partition count — stable
  across processes and runs (never Python's salted hash()).
  ``time-range``: partitions may carry ``after``/``before`` ISO-8601
  bounds; a shard whose filename time-range starts inside a
  partition's window belongs to it, and shards that match no window
  (or carry no parseable time, e.g. an `all`-interval shard) fall
  back to the hash rule.
* ``partitions[].replicas`` — member names in PREFERENCE order: the
  router tries the first live replica, failing over (and hedging) to
  the rest.
* ``members[].endpoint`` — a unix socket path or HOST:PORT, exactly
  the `--remote` address forms (serve/client.parse_addr).
* ``members[].config`` (optional) — a per-member dragnetrc path.  When
  set, THAT member resolves datasources for partial queries and shard
  handoff through its own config instead of the request's, which lets
  each member own a private index tree (the shard-streaming handoff
  fills it).  Omitted in shared-filesystem deployments: every member
  then walks the request's tree exactly as PR 8 did.

Dynamic topology (serve/coordinator.py): the same file doubles as the
coordinator source.  A topology may carry ``"state": "pending"`` plus
a ``"prev"`` field embedding the last COMMITTED document; members
polling the file (DN_TOPO_POLL_MS) then serve from ``prev`` while the
new epoch's handoff runs, and cut over atomically when the file is
rewritten as committed (state dropped, prev dropped).
load_topology_state() returns both views; load_topology() keeps the
static single-topology contract (a pending file reads as its
committed ``prev``).

Validation is strict and centralized here (load_topology raises the
shared DNError contract; `dn serve --validate` reports it before any
socket binds): duplicate/overlapping partition ids, replica sets
naming unknown members, empty replica sets, members no partition
uses, overlapping time ranges, and malformed endpoints are all
rejected at load time, not at the first query that meets them.
"""

import json
import os
import zlib

from ..errors import DNError
from .. import jsvalues as jsv

ASSIGN_MODES = ('hash', 'time-range')
STATES = ('committed', 'pending')


class Topology(object):
    """The validated, immutable cluster map."""

    def __init__(self, doc, path=None):
        self.path = path
        self.epoch = doc['epoch']
        self.assign = doc.get('assign') or 'hash'
        self.state = doc.get('state') or 'committed'
        # free-form transition annotation (e.g. the rebalance
        # planner's decisions); surfaced in /stats, never validated
        self.note = doc.get('note')
        self.members = {name: dict(m)
                        for name, m in doc['members'].items()}
        parts = sorted(doc['partitions'], key=lambda p: p['id'])
        self.partitions = [
            {'id': p['id'], 'replicas': list(p['replicas']),
             'after': p.get('after'), 'before': p.get('before'),
             'after_ms': p.get('_after_ms'),
             'before_ms': p.get('_before_ms')}
            for p in parts]
        self._by_id = {p['id']: p for p in self.partitions}

    def partition_ids(self):
        return [p['id'] for p in self.partitions]

    def replicas(self, pid):
        """Member names owning partition `pid`, preference order."""
        return list(self._by_id[pid]['replicas'])

    def endpoint(self, member):
        return self.members[member]['endpoint']

    def member_names(self):
        return sorted(self.members)

    def partitions_of(self, member):
        return [p['id'] for p in self.partitions
                if member in p['replicas']]

    def _hash_partition(self, name):
        idx = zlib.crc32(name.encode('utf-8')) % len(self.partitions)
        return self.partitions[idx]['id']

    def partition_of(self, shard_path, timeformat=None):
        """The partition owning a shard file.  Deterministic from the
        shard's basename (and, in time-range mode, its filename
        time-range), so the router and every member agree without
        coordination."""
        name = os.path.basename(shard_path)
        if self.assign == 'time-range' and timeformat:
            from .. import index_query_mt as mod_iqmt
            rng = mod_iqmt.shard_time_range(name, timeformat)
            if rng is not None:
                start_ms = rng[0]
                for p in self.partitions:
                    after = p['after_ms']
                    before = p['before_ms']
                    if after is None and before is None:
                        continue      # windowless: hash-rule only
                    if (after is None or start_ms >= after) and \
                            (before is None or start_ms < before):
                        return p['id']
        return self._hash_partition(name)

    def member_config(self, member):
        """The member's own dragnetrc path when the topology declares
        one (per-member index trees), else None (shared tree: the
        request's config governs, the PR 8 contract)."""
        m = self.members.get(member)
        return m.get('config') if m else None

    def summary(self):
        """The /stats and --validate view."""
        return {
            'path': self.path,
            'epoch': self.epoch,
            'assign': self.assign,
            'members': {name: m['endpoint']
                        for name, m in self.members.items()},
            'partitions': [{'id': p['id'],
                            'replicas': list(p['replicas'])}
                           for p in self.partitions],
        }

    def doc(self):
        """Re-serialize as a canonical COMMITTED topology document
        (what publish_topology writes; `state`/`prev` never survive a
        round trip — transition framing is the coordinator's job)."""
        partitions = []
        for p in self.partitions:
            ent = {'id': p['id'], 'replicas': list(p['replicas'])}
            if p.get('after') is not None:
                ent['after'] = p['after']
            if p.get('before') is not None:
                ent['before'] = p['before']
            partitions.append(ent)
        return {
            'epoch': self.epoch,
            'assign': self.assign,
            'members': {name: {k: v for k, v in m.items()
                               if k in ('endpoint', 'config')}
                        for name, m in self.members.items()},
            'partitions': partitions,
        }


def _parse_bound(p, key, pid):
    """Validated ISO-8601 (or epoch-seconds) partition bound -> ms."""
    raw = p.get(key)
    if raw is None:
        return None, None
    if isinstance(raw, (int, float)) and not isinstance(raw, bool):
        return int(raw) * 1000, None
    if isinstance(raw, str):
        ms = jsv.date_parse(raw)
        if ms is not None:
            return ms, None
    return None, ('partition %s: "%s" is not a valid date: %r'
                  % (pid, key, raw))


def validate_doc(doc, _nested=False):
    """First violation of the topology document shape as a string, or
    None; on success the partitions gain parsed _after_ms/_before_ms
    fields (time-range mode).  Transition framing: "state" must be
    'committed' or 'pending'; a pending document must embed its last
    committed predecessor as "prev" (itself a valid committed doc with
    a strictly smaller epoch)."""
    if not isinstance(doc, dict):
        return 'topology is not an object'
    epoch = doc.get('epoch')
    if not isinstance(epoch, int) or isinstance(epoch, bool) or \
            epoch < 1:
        return '"epoch" must be an integer >= 1'
    state = doc.get('state', 'committed')
    if state not in STATES:
        return '"state" must be one of: %s' % ', '.join(STATES)
    prev = doc.get('prev')
    if _nested and (state != 'committed' or prev is not None):
        return '"prev" must be a committed topology without its own ' \
            '"prev"'
    if state == 'pending':
        if prev is None:
            return 'a pending topology must embed its committed ' \
                'predecessor as "prev"'
        err = validate_doc(prev, _nested=True)
        if err is not None:
            return 'prev: %s' % err
        if prev['epoch'] >= epoch:
            return 'pending epoch %d must exceed committed epoch %d' \
                % (epoch, prev['epoch'])
    elif prev is not None:
        return '"prev" is only valid with "state": "pending"'
    assign = doc.get('assign', 'hash')
    if assign not in ASSIGN_MODES:
        return '"assign" must be one of: %s' % ', '.join(ASSIGN_MODES)
    members = doc.get('members')
    if not isinstance(members, dict) or not members:
        return '"members" must be a non-empty object'
    for name, m in members.items():
        if not isinstance(m, dict) or \
                not isinstance(m.get('endpoint'), str) or \
                not m['endpoint']:
            return 'member "%s": "endpoint" must be a non-empty ' \
                'string' % name
        if 'config' in m and (not isinstance(m['config'], str) or
                              not m['config']):
            return 'member "%s": "config" must be a non-empty ' \
                'string when present' % name
    parts = doc.get('partitions')
    if not isinstance(parts, list) or not parts:
        return '"partitions" must be a non-empty array'
    seen_ids = set()
    used = set()
    ranges = []
    for i, p in enumerate(parts):
        if not isinstance(p, dict):
            return 'partitions[%d] is not an object' % i
        pid = p.get('id')
        if not isinstance(pid, int) or isinstance(pid, bool) or \
                pid < 0:
            return 'partitions[%d]: "id" must be an integer >= 0' % i
        if pid in seen_ids:
            return 'partition id %d assigned twice (overlapping ' \
                'partitions)' % pid
        seen_ids.add(pid)
        replicas = p.get('replicas')
        if not isinstance(replicas, list) or not replicas:
            return 'partition %d: "replicas" must be a non-empty ' \
                'array' % pid
        if len(set(replicas)) != len(replicas):
            return 'partition %d: duplicate replica' % pid
        for r in replicas:
            if r not in members:
                return 'partition %d: unknown member "%s"' % (pid, r)
            used.add(r)
        after_ms, err = _parse_bound(p, 'after', pid)
        if err:
            return err
        before_ms, err = _parse_bound(p, 'before', pid)
        if err:
            return err
        if after_ms is not None and before_ms is not None and \
                before_ms <= after_ms:
            return 'partition %d: "before" must be after "after"' \
                % pid
        p['_after_ms'] = after_ms
        p['_before_ms'] = before_ms
        if assign == 'time-range' and \
                (after_ms is not None or before_ms is not None):
            ranges.append((pid, after_ms, before_ms))
    for name in members:
        if name not in used:
            return 'member "%s" owns no partition' % name
    # time ranges must not overlap: two windows both claiming a shard
    # would make partition_of order-dependent
    for i, (pa, aa, ba) in enumerate(ranges):
        for pb, ab, bb in ranges[i + 1:]:
            lo = max(aa if aa is not None else float('-inf'),
                     ab if ab is not None else float('-inf'))
            hi = min(ba if ba is not None else float('inf'),
                     bb if bb is not None else float('inf'))
            if lo < hi:
                return 'partitions %d and %d have overlapping time ' \
                    'ranges' % (pa, pb)
    return None


def load_topology_state(path, member=None):
    """Load + validate a topology file as (committed, pending):
    (Topology, None) for a committed file, (Topology-of-prev,
    Topology-of-new-epoch) for a pending transition file.  Raises
    DNError on any violation, including `member` naming neither a
    committed nor a pending member."""
    try:
        with open(path, 'r') as f:
            raw = f.read()
    except OSError as e:
        raise DNError('cluster topology "%s"' % path,
                      cause=DNError(str(e)))
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise DNError('cluster topology "%s": invalid JSON' % path,
                      cause=DNError(str(e)))
    err = validate_doc(doc)
    if err is not None:
        raise DNError('cluster topology "%s": %s' % (path, err))
    if doc.get('state') == 'pending':
        committed = Topology(doc['prev'], path=path)
        pending = Topology(doc, path=path)
    else:
        committed = Topology(doc, path=path)
        pending = None
    if member is not None and member not in committed.members and \
            (pending is None or member not in pending.members):
        have = set(committed.member_names())
        if pending is not None:
            have |= set(pending.member_names())
        raise DNError('cluster topology "%s": --member "%s" is not a '
                      'member (have: %s)'
                      % (path, member, ', '.join(sorted(have))))
    return committed, pending


def load_topology(path, member=None):
    """Load + validate a topology file; raises DNError on any
    violation (including `member` not naming a member when given).
    A pending transition file reads as its COMMITTED predecessor —
    static consumers (execution plans, `dn serve` startup) serve the
    last committed map until the transition commits."""
    committed, _pending = load_topology_state(path, member=member)
    return committed
