"""The static cluster map for scatter-gather serving.

A topology file describes a `dn serve` cluster: its members (name ->
endpoint), its partitions (each a replica set of members), the shard
assignment rule, and an epoch.  Every member loads the SAME file
(`dn serve --cluster=TOPOLOGY.json --member=NAME`); any member can act
as router for an incoming query, scattering partition-scoped partial
queries to the owners and merging the partial aggregates
(serve/router.py).

File format (JSON):

    {
      "epoch": 1,
      "assign": "hash",
      "members": {
        "a": {"endpoint": "/run/dn-a.sock"},
        "b": {"endpoint": "10.0.0.2:9401"},
        "c": {"endpoint": "10.0.0.3:9401"}
      },
      "partitions": [
        {"id": 0, "replicas": ["a", "b"]},
        {"id": 1, "replicas": ["b", "c"]},
        {"id": 2, "replicas": ["c", "a"]}
      ]
    }

* ``epoch`` — integer generation stamp.  Members reject partial
  queries whose epoch differs from their loaded topology (a retryable
  error), so a router and member running different topology files can
  never silently merge mismatched partitions.
* ``assign`` — the shard -> partition rule.  ``hash`` (default):
  crc32 of the shard's file name modulo the partition count — stable
  across processes and runs (never Python's salted hash()).
  ``time-range``: partitions may carry ``after``/``before`` ISO-8601
  bounds; a shard whose filename time-range starts inside a
  partition's window belongs to it, and shards that match no window
  (or carry no parseable time, e.g. an `all`-interval shard) fall
  back to the hash rule.
* ``partitions[].replicas`` — member names in PREFERENCE order: the
  router tries the first live replica, failing over (and hedging) to
  the rest.
* ``members[].endpoint`` — a unix socket path or HOST:PORT, exactly
  the `--remote` address forms (serve/client.parse_addr).

Validation is strict and centralized here (load_topology raises the
shared DNError contract; `dn serve --validate` reports it before any
socket binds): duplicate/overlapping partition ids, replica sets
naming unknown members, empty replica sets, members no partition
uses, overlapping time ranges, and malformed endpoints are all
rejected at load time, not at the first query that meets them.
"""

import json
import os
import zlib

from ..errors import DNError
from .. import jsvalues as jsv

ASSIGN_MODES = ('hash', 'time-range')


class Topology(object):
    """The validated, immutable cluster map."""

    def __init__(self, doc, path=None):
        self.path = path
        self.epoch = doc['epoch']
        self.assign = doc.get('assign') or 'hash'
        self.members = {name: dict(m)
                        for name, m in doc['members'].items()}
        parts = sorted(doc['partitions'], key=lambda p: p['id'])
        self.partitions = [
            {'id': p['id'], 'replicas': list(p['replicas']),
             'after_ms': p.get('_after_ms'),
             'before_ms': p.get('_before_ms')}
            for p in parts]
        self._by_id = {p['id']: p for p in self.partitions}

    def partition_ids(self):
        return [p['id'] for p in self.partitions]

    def replicas(self, pid):
        """Member names owning partition `pid`, preference order."""
        return list(self._by_id[pid]['replicas'])

    def endpoint(self, member):
        return self.members[member]['endpoint']

    def member_names(self):
        return sorted(self.members)

    def partitions_of(self, member):
        return [p['id'] for p in self.partitions
                if member in p['replicas']]

    def _hash_partition(self, name):
        idx = zlib.crc32(name.encode('utf-8')) % len(self.partitions)
        return self.partitions[idx]['id']

    def partition_of(self, shard_path, timeformat=None):
        """The partition owning a shard file.  Deterministic from the
        shard's basename (and, in time-range mode, its filename
        time-range), so the router and every member agree without
        coordination."""
        name = os.path.basename(shard_path)
        if self.assign == 'time-range' and timeformat:
            from .. import index_query_mt as mod_iqmt
            rng = mod_iqmt.shard_time_range(name, timeformat)
            if rng is not None:
                start_ms = rng[0]
                for p in self.partitions:
                    after = p['after_ms']
                    before = p['before_ms']
                    if after is None and before is None:
                        continue      # windowless: hash-rule only
                    if (after is None or start_ms >= after) and \
                            (before is None or start_ms < before):
                        return p['id']
        return self._hash_partition(name)

    def summary(self):
        """The /stats and --validate view."""
        return {
            'path': self.path,
            'epoch': self.epoch,
            'assign': self.assign,
            'members': {name: m['endpoint']
                        for name, m in self.members.items()},
            'partitions': [{'id': p['id'],
                            'replicas': list(p['replicas'])}
                           for p in self.partitions],
        }


def _parse_bound(p, key, pid):
    """Validated ISO-8601 (or epoch-seconds) partition bound -> ms."""
    raw = p.get(key)
    if raw is None:
        return None, None
    if isinstance(raw, (int, float)) and not isinstance(raw, bool):
        return int(raw) * 1000, None
    if isinstance(raw, str):
        ms = jsv.date_parse(raw)
        if ms is not None:
            return ms, None
    return None, ('partition %s: "%s" is not a valid date: %r'
                  % (pid, key, raw))


def validate_doc(doc):
    """First violation of the topology document shape as a string, or
    None; on success the partitions gain parsed _after_ms/_before_ms
    fields (time-range mode)."""
    if not isinstance(doc, dict):
        return 'topology is not an object'
    epoch = doc.get('epoch')
    if not isinstance(epoch, int) or isinstance(epoch, bool) or \
            epoch < 1:
        return '"epoch" must be an integer >= 1'
    assign = doc.get('assign', 'hash')
    if assign not in ASSIGN_MODES:
        return '"assign" must be one of: %s' % ', '.join(ASSIGN_MODES)
    members = doc.get('members')
    if not isinstance(members, dict) or not members:
        return '"members" must be a non-empty object'
    for name, m in members.items():
        if not isinstance(m, dict) or \
                not isinstance(m.get('endpoint'), str) or \
                not m['endpoint']:
            return 'member "%s": "endpoint" must be a non-empty ' \
                'string' % name
    parts = doc.get('partitions')
    if not isinstance(parts, list) or not parts:
        return '"partitions" must be a non-empty array'
    seen_ids = set()
    used = set()
    ranges = []
    for i, p in enumerate(parts):
        if not isinstance(p, dict):
            return 'partitions[%d] is not an object' % i
        pid = p.get('id')
        if not isinstance(pid, int) or isinstance(pid, bool) or \
                pid < 0:
            return 'partitions[%d]: "id" must be an integer >= 0' % i
        if pid in seen_ids:
            return 'partition id %d assigned twice (overlapping ' \
                'partitions)' % pid
        seen_ids.add(pid)
        replicas = p.get('replicas')
        if not isinstance(replicas, list) or not replicas:
            return 'partition %d: "replicas" must be a non-empty ' \
                'array' % pid
        if len(set(replicas)) != len(replicas):
            return 'partition %d: duplicate replica' % pid
        for r in replicas:
            if r not in members:
                return 'partition %d: unknown member "%s"' % (pid, r)
            used.add(r)
        after_ms, err = _parse_bound(p, 'after', pid)
        if err:
            return err
        before_ms, err = _parse_bound(p, 'before', pid)
        if err:
            return err
        if after_ms is not None and before_ms is not None and \
                before_ms <= after_ms:
            return 'partition %d: "before" must be after "after"' \
                % pid
        p['_after_ms'] = after_ms
        p['_before_ms'] = before_ms
        if assign == 'time-range' and \
                (after_ms is not None or before_ms is not None):
            ranges.append((pid, after_ms, before_ms))
    for name in members:
        if name not in used:
            return 'member "%s" owns no partition' % name
    # time ranges must not overlap: two windows both claiming a shard
    # would make partition_of order-dependent
    for i, (pa, aa, ba) in enumerate(ranges):
        for pb, ab, bb in ranges[i + 1:]:
            lo = max(aa if aa is not None else float('-inf'),
                     ab if ab is not None else float('-inf'))
            hi = min(ba if ba is not None else float('inf'),
                     bb if bb is not None else float('inf'))
            if lo < hi:
                return 'partitions %d and %d have overlapping time ' \
                    'ranges' % (pa, pb)
    return None


def load_topology(path, member=None):
    """Load + validate a topology file; raises DNError on any
    violation (including `member` not naming a member when given)."""
    try:
        with open(path, 'r') as f:
            raw = f.read()
    except OSError as e:
        raise DNError('cluster topology "%s"' % path,
                      cause=DNError(str(e)))
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise DNError('cluster topology "%s": invalid JSON' % path,
                      cause=DNError(str(e)))
    err = validate_doc(doc)
    if err is not None:
        raise DNError('cluster topology "%s": %s' % (path, err))
    topo = Topology(doc, path=path)
    if member is not None and member not in topo.members:
        raise DNError('cluster topology "%s": --member "%s" is not a '
                      'member (have: %s)'
                      % (path, member,
                         ', '.join(topo.member_names())))
    return topo
