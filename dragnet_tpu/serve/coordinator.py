"""Dynamic-topology coordination: epoch publication, transition
lifecycle, and the member-side topology watcher.

The PR 8 cluster served from a topology file loaded once at startup:
adding a member, widening a replica set, or draining a hot partition
meant restarting the world.  This module makes the SAME file a live
coordinator source (Diba's re-configurable dataflow: reconfiguration
as a first-class runtime operation, not a deploy):

* publish_topology() writes a validated document atomically (fsynced
  tmp + rename, the index-journal discipline) — a reader polling the
  file sees the old document or the new one, never a torn mix.
* A transition is TWO publishes.  begin_transition() writes the new
  epoch as ``state: pending`` with the last committed document
  embedded as ``prev``: every member keeps serving the committed map
  while joiners stream their newly-assigned shards from the committed
  owners (serve/rebalance.py).  commit_transition() rewrites the file
  as the committed new epoch once every pending member reports
  handoff_ready — the atomic cutover.  abort_transition() rewrites
  the committed predecessor, withdrawing the epoch.
* TopologyWatcher runs inside each `dn serve` member
  (DN_TOPO_POLL_MS > 0): it polls the file by stat identity, loads
  changed documents through the same strict validation as startup,
  and hands (committed, pending) to DnServer.apply_topology.  A
  malformed or half-visible file never takes down a member — the
  poll logs, counts an error, and retries next period.

Failure model (the acceptance bar): the only durable state is the
topology file, and every publish is atomic.  SIGKILL the coordinator
process mid-transition and the file is either still pending (every
member keeps serving the committed ``prev`` — no partition changes
owner) or already committed (the cutover happened); re-running
`dn topo commit` resumes either way.  SIGKILL a joiner and the
committed map is untouched — its restart re-reads the pending file
and re-pulls idempotently.  Stragglers that miss the commit are
covered by the topology-epoch mismatch rejection: members reject
partials from an older (or unknown) epoch retryably, and the router
re-fetches the current map and retries (serve/router.py).
"""

import json
import os
import threading
import time

from ..errors import DNError
from .. import faults as mod_faults
from ..obs import metrics as obs_metrics
from . import topology as mod_topology


def publish_topology(path, doc):
    """Atomically write a validated topology document: fsynced tmp +
    rename (a polling member sees old or new bytes, never a mix).
    Raises DNError on validation failure — a malformed document must
    never reach the file members poll."""
    err = mod_topology.validate_doc(json.loads(json.dumps(doc)))
    if err is not None:
        raise DNError('cluster topology "%s": %s' % (path, err))
    tmp = '%s.tmp.%d' % (path, os.getpid())
    data = json.dumps(doc, indent=2, sort_keys=True) + '\n'
    try:
        with open(tmp, 'w') as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)
    except OSError as e:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise DNError('cluster topology "%s": publish failed' % path,
                      cause=DNError(str(e)))


def begin_transition(path, new_doc, note=None):
    """Publish `new_doc` as the PENDING epoch of the topology at
    `path` (its epoch defaults to committed+1 when omitted; when
    given it must exceed the committed epoch).  Returns (committed,
    pending) Topology views.  Refuses while another transition is
    already pending — one epoch in flight at a time keeps the
    handoff window reasoned about."""
    committed, pending = mod_topology.load_topology_state(path)
    if pending is not None:
        raise DNError('cluster topology "%s": transition to epoch %d '
                      'already pending (commit or abort it first)'
                      % (path, pending.epoch))
    doc = dict(new_doc)
    if 'epoch' not in doc:
        doc['epoch'] = committed.epoch + 1
    doc.pop('state', None)
    doc.pop('prev', None)
    pend = dict(doc, state='pending', prev=committed.doc())
    if note is not None:
        pend['note'] = note
    publish_topology(path, pend)
    return mod_topology.load_topology_state(path)


def commit_transition(path):
    """Atomically cut the pending epoch over to committed.  Returns
    the committed Topology.  The caller is responsible for readiness
    (wait_ready) — committing under an incomplete handoff is safe but
    degrades: members reject partials for partitions whose shards are
    still streaming, retryably, until their pull completes."""
    committed, pending = mod_topology.load_topology_state(path)
    if pending is None:
        raise DNError('cluster topology "%s": no transition pending '
                      '(epoch %d is committed)' % (path,
                                                   committed.epoch))
    publish_topology(path, pending.doc())
    new_committed, _ = mod_topology.load_topology_state(path)
    return new_committed


def abort_transition(path):
    """Withdraw the pending epoch: rewrite the committed predecessor.
    Joiners' already-streamed shards are harmless litter their
    partition filters ignore."""
    committed, pending = mod_topology.load_topology_state(path)
    if pending is None:
        raise DNError('cluster topology "%s": no transition pending'
                      % path)
    publish_topology(path, committed.doc())
    return committed


def member_topology(endpoint, timeout_s=5.0):
    """One member's `topology` op document, or {'error': ...} — what
    transition_status polls for handoff readiness."""
    from . import client as mod_client
    try:
        rc, header, out, err = mod_client.request_bytes(
            endpoint, {'op': 'topology'}, timeout_s=timeout_s,
            retry=True)
        if rc != 0:
            return {'error': err.decode('utf-8', 'replace').strip()
                    or 'topology op failed'}
        return json.loads(out.decode('utf-8'))
    except (OSError, ValueError, DNError) as e:
        return {'error': str(e)}


def transition_status(path, timeout_s=5.0):
    """The transition's live view: per-pending-member epoch /
    handoff_ready, and whether the whole transition is ready to
    commit.  A member is ready once it reports the pending epoch with
    its handoff complete — or already serves an epoch >= the pending
    one (it saw the commit before we did)."""
    committed, pending = mod_topology.load_topology_state(path)
    doc = {'path': path, 'epoch': committed.epoch,
           'state': 'committed' if pending is None else 'pending',
           'pending_epoch': pending.epoch if pending is not None
           else None, 'members': {}}
    if pending is None:
        doc['ready'] = True
        return doc
    ready = True
    for name in pending.member_names():
        m = member_topology(pending.endpoint(name),
                            timeout_s=timeout_s)
        m_epoch = m.get('epoch')
        m_ready = bool(
            (isinstance(m_epoch, int) and m_epoch >= pending.epoch) or
            (m.get('pending_epoch') == pending.epoch and
             m.get('handoff_ready')))
        doc['members'][name] = {
            'ready': m_ready, 'epoch': m_epoch,
            'pending_epoch': m.get('pending_epoch'),
            'handoff': m.get('handoff'),
            'error': m.get('error')}
        ready = ready and m_ready
    doc['ready'] = ready
    return doc


def wait_ready(path, timeout_s=60.0, poll_s=0.2, probe_timeout_s=5.0):
    """Poll transition_status until every pending member is
    handoff-ready (returns the final status doc) or `timeout_s`
    expires (returns the last status with ready=False)."""
    deadline = time.monotonic() + timeout_s
    while True:
        status = transition_status(path, timeout_s=probe_timeout_s)
        if status.get('ready'):
            return status
        if time.monotonic() >= deadline:
            return status
        time.sleep(poll_s)


class TopologyWatcher(object):
    """The member-side poller: re-read the topology file every
    `poll_ms`, apply changed epochs to the server while it serves.
    poll_now() forces a synchronous poll — the router calls it when a
    member rejects a partial with an epoch mismatch (our map is
    stale; re-fetch before retrying)."""

    def __init__(self, server, path, poll_ms, log=None):
        self.server = server
        self.path = path
        self.poll_ms = poll_ms
        self.log = log
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._poll_lock = threading.Lock()
        self._ident = None
        self._lock = threading.Lock()
        self.counters = {'polls': 0, 'errors': 0, 'applied': 0}
        self._thread = None

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name='dn-topo-watch', daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None

    def _loop(self):
        period = self.poll_ms / 1000.0
        while not self._stop.is_set():
            self._wake.wait(period)
            self._wake.clear()
            if self._stop.is_set():
                return
            self.poll_now()

    def poll_now(self):
        """One synchronous poll (thread-safe; also the router's
        resync path).  Returns True when a change was applied."""
        with self._poll_lock:
            self._bump('polls')
            try:
                mod_faults.fire('topo.poll')
                st = os.stat(self.path)
                ident = (st.st_ino, st.st_mtime_ns, st.st_size)
                if ident == self._ident:
                    # unchanged file — but a transiently FAILED pull
                    # for the still-pending epoch gets another
                    # attempt each poll (a dead-then-recovered donor
                    # must not wedge the transition)
                    self.server.retry_failed_handoff()
                    return False
                committed, pending = \
                    mod_topology.load_topology_state(self.path)
            except (OSError, DNError) as e:
                # a transient read/validate failure (or an injected
                # topo.poll fault) must never take the member down:
                # keep serving the last good map, retry next period
                self._bump('errors')
                obs_metrics.inc('topo_poll_errors_total')
                if self.log is not None:
                    self.log.warn('topology poll failed', err=str(e))
                return False
            self._ident = ident
            self.server.apply_topology(committed, pending)
            self._bump('applied')
            return True

    def _bump(self, name):
        with self._lock:
            self.counters[name] += 1

    def stats(self):
        with self._lock:
            doc = dict(self.counters)
        doc['path'] = self.path
        doc['poll_ms'] = self.poll_ms
        return doc
