"""Fleet aggregation: one merged observability document for a whole
`dn serve` cluster.

The PR 7 observability layer is strictly per-process: an operator
watching a 5-member handoff under flood polls five /stats endpoints
by hand and does the merging in their head.  The ``fleet_stats`` op
fixes that: ANY member (or a bare single-process server) scatters
``stats`` — and, when asked, ``events`` — to every topology member
over the PR 10 pooled path and merges one fleet document:

* aggregate latency quantiles — member ``serve_op_latency_ms``
  histograms re-hydrated from their /stats JSON and folded through
  the existing ``Histogram.merge`` (the same merge the registry
  uses), so fleet p50/p95 are computed over the REAL distribution,
  never averaged quantiles;
* fleet qps / shed-rate trends when members run history rings
  (DN_METRICS_HISTORY_S), summed across members per window;
* an epoch-skew table (committed + pending epoch per member — the
  first thing to look at during a reconfiguration);
* the aggregating member's breaker/draining view of everyone, plus
  each member's own draining flag;
* per-tenant fairness counters summed across members;
* repair and handoff backlogs, ingest lag per follow source, and the
  merged event tail (each entry tagged with its member).

Failure posture — the whole point of a fleet view under an incident:
every member fetch is bounded by ``fleet_timeout_s`` and runs on its
own thread; a dead member shows up as ``ok: false`` with the error
string in its slot and its name in ``unreachable``.  The view NEVER
hangs on a dead member and NEVER presents a partial doc as complete
(``complete`` is true only when every member answered).

A server with no cluster degrades to a one-member fleet of itself —
`dn top` against a bare socket renders single-process mode through
the identical document shape.
"""

import json
import threading
import time

from ..obs import events as obs_events
from ..obs import export as obs_export

FLEET_VERSION = 1

# the latency family the aggregate quantiles merge over
LATENCY_METRIC = 'serve_op_latency_ms'

# default per-member fetch bound; config.obs_config validates the
# DN_FLEET_TIMEOUT_S override
DEFAULT_TIMEOUT_S = 5


def _member_row(name, st, latency=None):
    """The trimmed per-member table row the fleet doc carries (the
    full /stats docs would make the fleet doc unbounded).  `latency`
    is the member's pre-merged op histogram (merge_fleet computes it
    once and shares it with the aggregate)."""
    reqs = st.get('requests') or {}
    infl = st.get('inflight') or {}
    topo = st.get('topology') or {}
    integ = st.get('integrity') or {}
    repair = integ.get('repair') or {}
    hist = st.get('history') or {}
    row = {
        'ok': True,
        'pid': st.get('pid'),
        'uptime_s': st.get('uptime_s'),
        'draining': bool(st.get('draining')),
        'requests': reqs.get('requests', 0),
        'errors': reqs.get('errors', 0),
        'shed': (reqs.get('shed_overloaded', 0) +
                 reqs.get('busy_rejected', 0)),
        'inflight': infl.get('active', 0),
        'queued': infl.get('queued', 0),
        'epoch': topo.get('epoch'),
        'pending_epoch': topo.get('pending_epoch'),
        'leaving': topo.get('leaving'),
        'verify': integ.get('verify'),
        'repair_queued': repair.get('queued', 0),
        'repair_completed': repair.get('completed', 0),
        'repair_failed': repair.get('failed', 0),
        'history': bool(hist.get('enabled')),
        'events': bool((st.get('events') or {}).get('enabled')),
    }
    # repeat-traffic economics: result-cache hit rate, rollup
    # coverage, and the compaction backlog per member (PR 16)
    rcache = ((st.get('caches') or {}).get('results')) or {}
    if rcache.get('enabled'):
        row['cache_hit_rate'] = rcache.get('hit_rate')
    # device-lane serving: HBM residency per member (absent rows mean
    # the member never configured it — honest absence, like the
    # result cache)
    resid = ((st.get('device') or {}).get('residency')) or {}
    if resid.get('enabled'):
        row['device_residency_hit_rate'] = resid.get('hit_rate')
        row['device_pinned_bytes'] = resid.get('bytes')
    # batched index-query offload: only members whose device lane has
    # actually dispatched report (honest absence, like residency)
    iq = ((st.get('device') or {}).get('index_query')) or {}
    if iq.get('dispatches'):
        row['index_device_dispatches'] = iq.get('dispatches')
        row['index_device_shards_per_dispatch'] = \
            iq.get('shards_per_dispatch')
        row['index_device_h2d_saved_bytes'] = \
            iq.get('h2d_saved_bytes', 0)
    # standing queries: active subscriber count per member (honest
    # absence when the member runs with DN_SUB_MAX=0)
    subs = st.get('subscriptions') or {}
    if subs.get('enabled'):
        row['subscriptions'] = subs.get('active', 0)
    roll = st.get('rollup') or {}
    if roll:
        row['rollup_coverage'] = roll.get('coverage_ratio')
    maint = st.get('maintenance')
    if maint is not None:
        row['compact_backlog'] = maint.get('compact_backlog')
    res = st.get('resources') or {}
    if res:
        # resource governance: the member's disk mode and headroom
        # ride the fleet view — a read-only member is the first thing
        # an operator needs to see during a disk incident
        row['disk_mode'] = res.get('mode')
        row['disk_free_pct'] = res.get('free_pct')
        row['degraded_ro'] = bool(res.get('read_only'))
    # per-member latency: this member's own op histograms merged
    if latency is not None and latency.total:
        row['p50_ms'] = round(latency.quantile(0.50), 3)
        row['p95_ms'] = round(latency.quantile(0.95), 3)
    # per-member qps / shed trends from its history rings
    rates = _member_rates(st)
    row.update(rates)
    fl = st.get('follow')
    if fl is not None:
        row['ingest_lag_ms'] = fl.get('ingest_lag_ms')
    return row


def _merged_latency(st):
    """One Histogram folding every serve_op_latency_ms{op=*} entry in
    a member's /stats metrics section; None when absent."""
    hists = ((st.get('metrics') or {}).get('histograms')) or {}
    merged = None
    for jname, ent in hists.items():
        if jname != LATENCY_METRIC and \
                not jname.startswith(LATENCY_METRIC + '{'):
            continue
        h = obs_export.histogram_from_doc(ent)
        if h is None:
            continue
        if merged is None:
            merged = h
        else:
            merged.merge(h)
    return merged


def _member_rates(st):
    """qps_1m / shed_1m for one member from its history section
    (None values when history is off or too young — honest, never
    fabricated)."""
    series = ((st.get('history') or {}).get('series')) or {}
    qps = None
    shed = None
    for jname, doc in series.items():
        if (jname == LATENCY_METRIC + ':count' or
                (jname.startswith(LATENCY_METRIC + '{') and
                 jname.endswith(':count'))):
            r = doc.get('rate_1m')
            if r is not None:
                qps = (qps or 0.0) + r
        elif jname.startswith('serve_shed_total'):
            r = doc.get('rate_1m')
            if r is not None:
                shed = (shed or 0.0) + r
    return {'qps_1m': round(qps, 3) if qps is not None else None,
            'shed_1m': round(shed, 3) if shed is not None else None}


def _fetch_member(endpoint, timeout_s, events_limit):
    """(stats_doc, events_list_or_None) from one remote member over
    the pooled path; raises on any failure (the caller owns the error
    slot)."""
    from . import client as mod_client
    rc, header, out, err = mod_client.request_bytes(
        endpoint, {'op': 'stats'}, timeout_s=timeout_s, pooled=True)
    if rc != 0:
        raise ValueError(err.decode('utf-8', 'replace').strip()
                         or 'stats op failed')
    st = json.loads(out.decode('utf-8'))
    events = None
    if events_limit:
        rc, header, out, err = mod_client.request_bytes(
            endpoint, {'op': 'events', 'limit': events_limit},
            timeout_s=timeout_s, pooled=True)
        if rc == 0:
            events = (json.loads(out.decode('utf-8'))
                      .get('events')) or []
    return st, events


def fleet_doc(server, timeout_s=None, events_limit=50):
    """The merged fleet document (the ``fleet_stats`` op body).  Any
    member aggregates; `server` is the local DnServer whose own stats
    are read in-process (a member never dials itself)."""
    if timeout_s is None:
        timeout_s = server.conf.get('fleet_timeout_s',
                                    DEFAULT_TIMEOUT_S)
    topo = server.cluster
    if topo is not None:
        names = sorted(topo.member_names())
        endpoints = {n: topo.endpoint(n) for n in names}
    else:
        # bare single-process server: a one-member fleet of itself
        names = [server.member or 'local']
        endpoints = {}

    stats = {}
    events = {}
    errors = {}
    threads = []
    lock = threading.Lock()

    def fetch(name):
        try:
            st, ev = _fetch_member(endpoints[name], timeout_s,
                                   events_limit)
            with lock:
                stats[name] = st
                if ev is not None:
                    events[name] = ev
        except Exception as e:
            with lock:
                errors[name] = str(e)

    self_name = server.member if server.member is not None \
        else names[0]
    for name in names:
        if name == self_name:
            continue
        t = threading.Thread(target=fetch, args=(name,),
                             daemon=True,
                             name='dn-fleet-%s' % name)
        threads.append(t)
        t.start()
    # the local member answers in-process while the others fetch
    stats[self_name] = server.stats_doc()
    j = obs_events.journal()
    if j is not None and events_limit:
        events[self_name] = j.tail(limit=events_limit)
    deadline = time.monotonic() + timeout_s + 1.0
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            # the fetch thread is wedged past its own timeout: the
            # member gets an error slot NOW — the view never hangs
            with lock:
                errors.setdefault(t.name.split('dn-fleet-', 1)[-1],
                                  'fleet fetch timed out')
    # snapshot under the lock: a wedged fetch thread that completes
    # AFTER its deadline slot must not mutate the dicts mid-merge
    with lock:
        stats = dict(stats)
        events = {n: list(v) for n, v in events.items()}
        errors = dict(errors)
    return merge_fleet(server, names, stats, events, errors,
                       timeout_s=timeout_s)


def merge_fleet(server, names, stats, events, errors, timeout_s=None):
    """Fold per-member stats/events/errors into the fleet document
    (split from fleet_doc so tests can merge canned inputs)."""
    topo = server.cluster
    members = {}
    epochs = {}
    agg_latency = None
    qps = None
    shed_rate = None
    totals = {'requests': 0, 'errors': 0, 'shed': 0}
    tenants = {}
    repair = {'scheduled': 0, 'completed': 0, 'failed': 0,
              'queued': 0}
    handoff = {}
    follow = {}
    cache_hits = cache_misses = 0
    cache_on = False
    resid_hits = resid_misses = resid_pinned = 0
    resid_on = False
    iq_dispatches = iq_shards = iq_pin_hits = iq_saved = 0
    iq_on = False
    roll_covered = roll_queried = 0
    compact_backlog = None
    sub_active = sub_pushes = 0
    sub_on = False
    for name in names:
        st = stats.get(name)
        if st is None:
            members[name] = {'ok': False, 'unreachable': True,
                             'error': errors.get(name, 'no response')}
            continue
        h = _merged_latency(st)
        row = _member_row(name, st, latency=h)
        members[name] = row
        for k in totals:
            totals[k] += row.get(k) or 0
        if row.get('qps_1m') is not None:
            qps = (qps or 0.0) + row['qps_1m']
        if row.get('shed_1m') is not None:
            shed_rate = (shed_rate or 0.0) + row['shed_1m']
        if h is not None:
            if agg_latency is None:
                agg_latency = h
            else:
                agg_latency.merge(h)
        tp = st.get('topology') or {}
        if tp.get('configured'):
            epochs[name] = {'epoch': tp.get('epoch'),
                            'pending_epoch': tp.get('pending_epoch'),
                            'state': tp.get('state')}
            if tp.get('handoff') is not None:
                handoff[name] = tp['handoff']
        for tname, tdoc in (((st.get('tenants') or {})
                             .get('tenants')) or {}).items():
            agg = tenants.setdefault(
                tname, {'requests': 0, 'admitted': 0,
                        'rejected_busy': 0, 'shed_overload': 0,
                        'completed': 0, 'queued': 0})
            for k in agg:
                agg[k] += tdoc.get(k, 0)
        rp = ((st.get('integrity') or {}).get('repair')) or {}
        for k in repair:
            repair[k] += rp.get(k, 0)
        rc = ((st.get('caches') or {}).get('results')) or {}
        if rc.get('enabled'):
            cache_on = True
            cache_hits += rc.get('hits', 0) or 0
            cache_misses += rc.get('misses', 0) or 0
        rd = ((st.get('device') or {}).get('residency')) or {}
        if rd.get('enabled'):
            resid_on = True
            resid_hits += rd.get('hits', 0) or 0
            resid_misses += rd.get('misses', 0) or 0
            resid_pinned += rd.get('bytes', 0) or 0
        iqd = ((st.get('device') or {}).get('index_query')) or {}
        if iqd.get('dispatches'):
            iq_on = True
            iq_dispatches += iqd.get('dispatches', 0) or 0
            iq_shards += iqd.get('shards', 0) or 0
            iq_pin_hits += iqd.get('pinned_shard_hits', 0) or 0
            iq_saved += iqd.get('h2d_saved_bytes', 0) or 0
        roll = st.get('rollup') or {}
        roll_covered += roll.get('covered_shards', 0) or 0
        roll_queried += roll.get('shards_queried', 0) or 0
        maint = st.get('maintenance')
        if maint is not None:
            compact_backlog = (compact_backlog or 0) + \
                (maint.get('compact_backlog') or 0)
        sb = st.get('subscriptions') or {}
        if sb.get('enabled'):
            sub_on = True
            sub_active += sb.get('active', 0) or 0
            sub_pushes += ((sb.get('counters') or {})
                           .get('pushes', 0)) or 0
        fl = st.get('follow')
        if fl is not None:
            follow[name] = {'ingest_lag_ms': fl.get('ingest_lag_ms'),
                            'sources': len(fl.get('sources') or [])}

    # the aggregating member's router view: breaker state + draining
    # per member (how THIS router would dispatch right now)
    breakers = {}
    if server.router is not None:
        for name, snap in (server.router.stats_doc()
                           .get('members') or {}).items():
            breakers[name] = {'state': snap.get('state'),
                              'draining': snap.get('draining'),
                              'last_ok_age_s':
                              snap.get('last_ok_age_s')}

    # merged event tail: every member's entries, member-tagged,
    # ordered by wall time (tie-broken by seq).  Deduped on the full
    # entry identity (member tag, seq, ts, type): embedded
    # same-process members (tests, soaks) share one journal and would
    # otherwise report each entry once per member — while two
    # DISTINCT processes whose journals happen to reuse a seq (e.g.
    # routers a and c both emitting breaker.open member=b as entry 7)
    # differ in ts and both survive.
    tail = []
    seen = set()
    for name, evs in events.items():
        for e in evs:
            if 'member' not in e or e['member'] is None:
                e = dict(e, member=name)
            key = (e.get('member'), e.get('seq'), e.get('ts'),
                   e.get('type'))
            if key in seen:
                continue
            seen.add(key)
            tail.append(e)
    tail.sort(key=lambda e: (e.get('ts') or 0, e.get('seq') or 0))

    up = [n for n in names if stats.get(n) is not None]
    unreachable = [n for n in names if n not in stats]
    known_epochs = [d['epoch'] for d in epochs.values()
                    if isinstance(d.get('epoch'), int)]
    aggregate = {
        'requests': totals['requests'],
        'errors': totals['errors'],
        'shed': totals['shed'],
        'qps_1m': round(qps, 3) if qps is not None else None,
        'shed_rate_1m': round(shed_rate, 3)
        if shed_rate is not None else None,
        # fleet repeat-traffic economics: hit rate over SUMMED member
        # hits/misses (never averaged rates), rollup coverage over
        # summed shard counts, total compaction backlog (None when no
        # member runs a cache / maintenance timer — honest absence)
        'cache_hit_rate': round(
            cache_hits / (cache_hits + cache_misses), 4)
        if cache_on and (cache_hits + cache_misses) else
        (0.0 if cache_on else None),
        'rollup_coverage': round(roll_covered / roll_queried, 4)
        if roll_queried else 0.0,
        'compact_backlog': compact_backlog,
        # device-lane serving: HBM residency over SUMMED member
        # hits/misses + total pinned bytes (None when no member
        # configured residency — honest absence, like the cache)
        'device_residency_hit_rate': round(
            resid_hits / (resid_hits + resid_misses), 4)
        if resid_on and (resid_hits + resid_misses) else
        (0.0 if resid_on else None),
        'device_pinned_bytes': resid_pinned if resid_on else None,
        # batched index-query offload: SUMMED dispatch/shard counts
        # and pinned-shard H2D savings (None when no member's device
        # index lane has engaged — honest absence)
        'index_device_dispatches': iq_dispatches if iq_on else None,
        'index_device_shards_per_dispatch': round(
            iq_shards / iq_dispatches, 2)
        if iq_on and iq_dispatches else (0.0 if iq_on else None),
        'index_device_pinned_shard_hits':
        iq_pin_hits if iq_on else None,
        'index_device_h2d_saved_bytes': iq_saved if iq_on else None,
        # standing queries: SUMMED active subscribers and lifetime
        # pushes (None when no member enables subscriptions —
        # honest absence)
        'subscriptions': sub_active if sub_on else None,
        'subscription_pushes': sub_pushes if sub_on else None,
    }
    if agg_latency is not None and agg_latency.total:
        aggregate['latency'] = {
            'count': agg_latency.total,
            'p50': round(agg_latency.quantile(0.50), 3),
            'p95': round(agg_latency.quantile(0.95), 3),
            'p99': round(agg_latency.quantile(0.99), 3),
        }
    else:
        aggregate['latency'] = None
    doc = {
        'version': FLEET_VERSION,
        'ts': round(time.time(), 3),
        'aggregated_by': server.member,
        'epoch': topo.epoch if topo is not None else None,
        'epoch_skew': (max(known_epochs) - min(known_epochs))
        if known_epochs else 0,
        'members_total': len(names),
        'members_up': len(up),
        'members_draining': sum(
            1 for n in up if members[n].get('draining') or
            members[n].get('leaving')),
        # disk governance rollup: read-only members and the fleet's
        # tightest free-space margin (None when no member reports)
        'members_read_only': sum(
            1 for n in up if members[n].get('degraded_ro')),
        'min_disk_free_pct': min(
            (members[n]['disk_free_pct'] for n in up
             if members[n].get('disk_free_pct') is not None),
            default=None),
        'unreachable': unreachable,
        'complete': not unreachable,
        'fetch_timeout_s': timeout_s,
        'aggregate': aggregate,
        'members': members,
        'epochs': epochs,
        'breakers': breakers,
        'tenants': tenants,
        'repair': repair,
        'handoff': handoff,
        'follow': follow,
        'events': tail,
    }
    return doc


def fleet_prometheus_text(doc):
    """Render the fleet document's headline numbers as Prometheus
    text (`dn stats --cluster --prom`): a synthesized dn_fleet_*
    family — member liveness, aggregate throughput/latency, repair
    backlog — for scrapers that want the merged view without N
    per-member scrape targets."""
    from ..obs import metrics as mod_metrics
    reg = mod_metrics.Registry()
    reg.set_gauge('fleet_members_total', doc['members_total'])
    reg.set_gauge('fleet_members_up', doc['members_up'])
    reg.set_gauge('fleet_members_draining', doc['members_draining'])
    reg.set_gauge('fleet_members_unreachable',
                  len(doc['unreachable']))
    reg.set_gauge('fleet_epoch_skew', doc['epoch_skew'])
    reg.set_gauge('fleet_members_read_only',
                  doc.get('members_read_only') or 0)
    if doc.get('min_disk_free_pct') is not None:
        reg.set_gauge('fleet_min_disk_free_pct',
                      doc['min_disk_free_pct'])
    if doc.get('epoch') is not None:
        reg.set_gauge('fleet_epoch', doc['epoch'])
    agg = doc['aggregate']
    reg.inc('fleet_requests_total', agg['requests'])
    reg.inc('fleet_errors_total', agg['errors'])
    reg.inc('fleet_shed_total', agg['shed'])
    if agg.get('qps_1m') is not None:
        reg.set_gauge('fleet_qps_1m', agg['qps_1m'])
    if agg.get('cache_hit_rate') is not None:
        reg.set_gauge('fleet_cache_hit_rate', agg['cache_hit_rate'])
    if agg.get('rollup_coverage') is not None:
        reg.set_gauge('fleet_rollup_coverage', agg['rollup_coverage'])
    if agg.get('compact_backlog') is not None:
        reg.set_gauge('fleet_compact_backlog', agg['compact_backlog'])
    if agg.get('device_residency_hit_rate') is not None:
        reg.set_gauge('fleet_device_residency_hit_rate',
                      agg['device_residency_hit_rate'])
    if agg.get('device_pinned_bytes') is not None:
        reg.set_gauge('fleet_device_pinned_bytes',
                      agg['device_pinned_bytes'])
    if agg.get('index_device_dispatches') is not None:
        reg.set_gauge('fleet_index_device_dispatches',
                      agg['index_device_dispatches'])
    if agg.get('index_device_h2d_saved_bytes') is not None:
        reg.set_gauge('fleet_index_device_h2d_saved_bytes',
                      agg['index_device_h2d_saved_bytes'])
    if agg.get('subscriptions') is not None:
        reg.set_gauge('fleet_subscriptions', agg['subscriptions'])
    if agg.get('subscription_pushes') is not None:
        reg.inc('fleet_subscription_pushes_total',
                agg['subscription_pushes'])
    lat = agg.get('latency')
    if lat:
        reg.set_gauge('fleet_latency_p50_ms', lat['p50'])
        reg.set_gauge('fleet_latency_p95_ms', lat['p95'])
        reg.set_gauge('fleet_latency_p99_ms', lat['p99'])
    rp = doc['repair']
    reg.set_gauge('fleet_repair_queued', rp['queued'])
    reg.inc('fleet_repair_completed_total', rp['completed'])
    reg.inc('fleet_repair_failed_total', rp['failed'])
    for name, row in doc['members'].items():
        reg.set_gauge('fleet_member_up',
                      1.0 if row.get('ok') else 0.0, member=name)
    return obs_export.prometheus_text(reg)
