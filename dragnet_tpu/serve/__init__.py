"""dn serve: the resident query server.

A long-lived daemon that holds the warm state every prior layer built
— the shard-handle LRU, the whole-tree find memo, the persisted
audition verdicts, compiled device executables — and executes
scan/build/query requests over a newline-JSON socket protocol with
byte-identical output framing.  Modules:

* server.py      — the multi-threaded daemon + request execution
* admission.py   — bounded admission, deadlines, request coalescing
* client.py      — the `--remote` thin client with local fallback
* lifecycle.py   — pidfile/socket hygiene, drain, writer invalidation
* topology.py    — the cluster map: members, partitions, epochs
* router.py      — scatter-gather routing, breakers, failover
* coordinator.py — dynamic topology: epoch publication + watcher
* rebalance.py   — partition handoff (shard streaming) + planner
* protocol.py    — wire framing (v1 and multiplexed v2)
* ioloop.py      — the selector connection front end
* pool.py        — pooled persistent multiplexed client connections

Import-light on purpose: the heavy modules load lazily so `import
dragnet_tpu` stays cheap.
"""
