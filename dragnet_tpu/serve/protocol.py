"""Wire framing for the `dn serve` protocol, v1 and v2.

v1 (PR 5): one request per connection.  The client sends one JSON
request line; the server answers with one JSON header line —
``{"ok", "rc", "nout", "nerr", "stats", "retryable"}`` — followed by
exactly ``nout`` stdout bytes and ``nerr`` stderr bytes, then closes
the connection.  Wrong shape for high fan-in: every request pays a
dial, and every idle dashboard costs the server an open-and-forgotten
socket it must thread-babysit.

v2 (this PR): persistent multiplexed connections.  A request is still
one JSON line (the existing byte-counted newline-JSON payloads are
unchanged), but carries two extra fields::

    {"proto": 2, "id": 17, "op": "query", ...}

``id`` is a client-chosen positive integer, unique among the
connection's in-flight requests.  Requests may be PIPELINED — sent
back to back without waiting — and responses may return OUT OF ORDER:
each response frame is the same header line plus payload bytes, with
``"proto": 2`` and the request's ``id`` echoed so the client can
demultiplex.  The connection stays open across requests.

Negotiation is a protocol field, not a handshake round-trip: a v1
server ignores the unknown ``proto``/``id`` keys, answers with a v1
header (no ``id``) and closes — the client detects the missing ``id``,
keeps the (correct) response, and downgrades that endpoint to
dial-per-request.  A v2 server serves requests WITHOUT ``proto``
exactly as v1 did, byte-identically, so old clients keep working.

This module holds the frame encode/decode helpers and the incremental
line splitter both sides share; the server's readiness loop lives in
ioloop.py and the client's connection pool in pool.py.
"""

import json

# one request/response frame (header line + payload) may not exceed
# this; a line that grows past it without a newline is a torn or
# malicious frame and the connection is closed
MAX_FRAME_BYTES = 1 << 24

PROTO_V2 = 2


class FrameError(Exception):
    """A malformed frame (oversized, non-JSON, bad protocol fields).
    The connection that produced it cannot be trusted to be in sync
    and is closed after an error response where one can be framed."""


def classify_request(req):
    """(proto, request_id) for a parsed request dict: (1, None) for a
    legacy request, (2, id) for a well-formed v2 frame.  Raises
    FrameError on a malformed v2 frame (proto present but wrong, or
    a missing/bad id)."""
    proto = req.get('proto')
    if proto is None or proto == 1:
        return 1, None
    if proto != PROTO_V2:
        raise FrameError('unsupported protocol %r' % (proto,))
    rid = req.get('id')
    if not isinstance(rid, int) or isinstance(rid, bool) or rid <= 0:
        raise FrameError('protocol 2 requires a positive integer '
                         '"id", got %r' % (rid,))
    return PROTO_V2, rid


def encode_request(req, rid):
    """One v2 request frame (bytes) for `req` under request id
    `rid`."""
    return json.dumps(dict(req, proto=PROTO_V2, id=rid),
                      sort_keys=True).encode('utf-8') + b'\n'


def encode_push(sub, seq, epoch, kind, payload=b'', extra=None):
    """One SERVER-INITIATED push frame (bytes) for subscription `sub`
    (`dn subscribe`, serve/subscribe.py).  Same newline-JSON header +
    byte-counted payload shape as a response, but carrying ``sub``
    (the subscription id) INSTEAD of a request ``id`` — that absence
    is the discriminator: a client frame with ``id`` answers a
    request it sent, a frame with ``sub`` is the server talking
    first.  ``kind`` is 'full' (payload = the complete rendered
    result bytes), 'delta' (payload = the inserted span; `extra`
    carries the patch doc), 'current' (resume matched — no payload),
    or 'end' (the server is dropping the subscription; `extra`
    carries the reason).  v1 connections can never receive one:
    registration itself requires a v2 frame (server.py rejects a v1
    subscribe before a subscription exists)."""
    header = {'proto': PROTO_V2, 'sub': sub, 'seq': seq,
              'epoch': epoch, 'kind': kind, 'ok': True, 'rc': 0,
              'nout': len(payload), 'nerr': 0,
              'stats': extra or {}}
    return (json.dumps(header, sort_keys=True).encode('utf-8') +
            b'\n' + payload)


def classify_frame(header):
    """Client-side demux of one received header dict: 'push' for a
    server-initiated subscription frame (``sub`` present, no request
    ``id``), 'response' for an answer to a request this side sent.
    A frame carrying BOTH is malformed — the connection is out of
    sync."""
    has_id = header.get('id') is not None
    has_sub = header.get('sub') is not None
    if has_id and has_sub:
        raise FrameError('frame carries both "id" and "sub"')
    return 'push' if has_sub else 'response'


def encode_response(rc, out, err, extra, proto=1, rid=None):
    """One response frame: the JSON header line plus the stdout and
    stderr payload bytes.  `extra` rides as the header's `stats`
    section; `retryable` and `retry_after_ms` are hoisted to the top
    level so clients can act on them without digging."""
    header = {'ok': rc == 0, 'rc': rc, 'nout': len(out),
              'nerr': len(err), 'stats': extra,
              'retryable': bool(extra.get('retryable'))}
    if extra.get('retry_after_ms') is not None:
        header['retry_after_ms'] = extra['retry_after_ms']
    if proto == PROTO_V2:
        header['proto'] = PROTO_V2
        header['id'] = rid
    return (json.dumps(header, sort_keys=True).encode('utf-8') +
            b'\n' + out + err)


# -- push-frame delta codec --------------------------------------------------
#
# A standing query's payload usually changes at the tail (new time
# buckets) or in a few counter digits, so a push can often ship just
# the edited span: a delta frame carries {"off": O, "keep": K} plus
# the inserted bytes, meaning
#
#     new = old[:O] + inserted + old[len(old)-K:]
#
# Reconstruction is pure byte splicing — trivially byte-identical, no
# structural diff to trust.  The prefix/suffix scan runs as O(log n)
# slice comparisons (C memcmp speed), not a per-byte Python loop.

def _common_prefix_len(a, b):
    n = min(len(a), len(b))
    lo, hi = 0, n
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if a[:mid] == b[:mid]:
            lo = mid
        else:
            hi = mid - 1
    return lo


def byte_delta(old, new):
    """(off, keep, inserted) such that
    ``new == old[:off] + inserted + old[len(old)-keep:]``."""
    off = _common_prefix_len(old, new)
    ta, tb = old[off:], new[off:]
    n = min(len(ta), len(tb))
    lo, hi = 0, n
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if ta[len(ta) - mid:] == tb[len(tb) - mid:]:
            lo = mid
        else:
            hi = mid - 1
    keep = lo
    return off, keep, new[off:len(new) - keep]


def apply_delta(old, off, keep, inserted):
    """Reconstruct the new payload from `old` and a delta frame's
    patch; raises FrameError on an inconsistent patch (the client's
    base diverged — reconnect and re-seed)."""
    if not isinstance(off, int) or not isinstance(keep, int) or \
            isinstance(off, bool) or isinstance(keep, bool) or \
            off < 0 or keep < 0 or off + keep > len(old):
        raise FrameError('delta patch inconsistent with base payload '
                         '(off=%r keep=%r base=%d)'
                         % (off, keep, len(old)))
    return old[:off] + inserted + old[len(old) - keep:]


class LineBuffer(object):
    """Incremental newline-frame splitter: feed() raw chunks, take()
    complete lines.  Raises FrameError when a line exceeds
    MAX_FRAME_BYTES without terminating — the only honest move left
    is closing the connection."""

    __slots__ = ('_buf', 'max_bytes')

    def __init__(self, max_bytes=MAX_FRAME_BYTES):
        self._buf = bytearray()
        self.max_bytes = max_bytes

    def feed(self, data):
        self._buf.extend(data)

    def take(self):
        """Every complete line currently buffered (without the
        trailing newline), leaving any partial tail in place."""
        lines = []
        while True:
            nl = self._buf.find(b'\n')
            if nl < 0:
                break
            lines.append(bytes(self._buf[:nl]))
            del self._buf[:nl + 1]
        if len(self._buf) > self.max_bytes:
            raise FrameError('frame exceeds %d bytes without a '
                             'newline' % self.max_bytes)
        return lines

    def pending(self):
        """Bytes of the partial line waiting for its newline."""
        return len(self._buf)
