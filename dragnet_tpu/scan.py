"""The scan operator: filter -> synthetic date fields -> time bounds ->
aggregate.

Host-side reference implementation of the reference's StreamScan pipeline
(lib/stream-scan.js:40-96), with stage order and counter semantics preserved:

    [Datasource filter] -> [User filter] -> [Datetime parser] ->
    [Time filter] -> [Aggregator]

Per-record fault tolerance matches the reference: filter-eval failures
(missing fields) drop the record with an `nfailedeval` warning; filtered
records bump `nfilteredout`; unparseable/missing date fields drop with
`baddate`/`undef` warnings (lib/stream-synthetic.js:43-80,
lib/krill-skinner-stream.js:29-52).

The vectorized engine (engine.py) executes the same operator graph over
columnar batches on device; this module is the semantic definition and the
fallback path.
"""

from . import jsvalues as jsv
from . import krill as mod_krill
from . import query as mod_query
from .aggr import Aggregator


class FilterStage(object):
    def __init__(self, predicate, stage):
        self.predicate = predicate
        self.stage = stage

    def accept(self, fields):
        self.stage.bump('ninputs')
        try:
            result = self.predicate.eval_(fields)
        except mod_krill.EvalError as e:
            self.stage.warn(e, 'nfailedeval')
            return False
        except Exception as e:  # JS comparison never throws; be safe
            self.stage.warn(e, 'nfailedeval')
            return False
        if result:
            self.stage.bump('noutputs')
            return True
        self.stage.bump('nfilteredout')
        return False


class SyntheticStage(object):
    """Materializes date-typed fields: ISO-8601 string -> unix seconds;
    numbers pass through.  (reference: lib/stream-synthetic.js:20-85)"""

    def __init__(self, synthetic, stage):
        self.synthetic = synthetic
        self.stage = stage

    def accept(self, fields):
        self.stage.bump('ninputs')
        nerrors = 0
        for fieldconf in self.synthetic:
            val = jsv.pluck(fields, fieldconf['field'])
            if val is jsv.UNDEFINED:
                if nerrors == 0:
                    self.stage.warn(
                        ValueError('field "%s" is undefined'
                                   % fieldconf['field']), 'undef')
                nerrors += 1
                continue
            if jsv.is_number(val):
                fields[fieldconf['name']] = val
                continue
            parsed = jsv.date_parse(val)
            if parsed is None:
                if nerrors == 0:
                    self.stage.warn(
                        ValueError('field "%s" is not a valid date'
                                   % fieldconf['field']), 'baddate')
                nerrors += 1
                continue
            fields[fieldconf['name']] = parsed // 1000
        if nerrors == 0:
            self.stage.bump('noutputs')
            return True
        return False


class StreamScan(object):
    """Composes the per-record operator chain for one query."""

    def __init__(self, query, time_field, pipeline, ds_filter=None):
        self.query = query
        self.stages = []

        if ds_filter is not None:
            pred = mod_krill.create(ds_filter)
            self.stages.append(FilterStage(
                pred, pipeline.stage('Datasource filter')))

        if query.qc_filter is not None:
            pred = mod_krill.create(query.qc_filter)
            self.stages.append(FilterStage(
                pred, pipeline.stage('User filter')))

        synthetic = list(query.qc_synthetic)
        if query.qc_before is not None or query.qc_after is not None:
            assert isinstance(time_field, str)
            synthetic.append({
                'name': 'dn_ts',
                'field': time_field,
                'date': '',
            })

        if synthetic:
            self.stages.append(SyntheticStage(
                synthetic, pipeline.stage('Datetime parser')))

        tfilter = mod_query.query_time_bounds_filter(query, 'dn_ts')
        if tfilter is not None:
            self.stages.append(FilterStage(
                mod_krill.create(tfilter), pipeline.stage('Time filter')))

        self.aggr = Aggregator(query, stage=pipeline.stage('Aggregator'))

    def write(self, fields, value):
        for s in self.stages:
            if not s.accept(fields):
                return
        self.aggr.write(fields, value)
