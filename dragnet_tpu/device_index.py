"""Device-offloaded index query: batched shard tensors, on-device
scatter-add merge, residency-pinned hot columns.

This module is the device engine behind the stacked index-query path
(index_query_stack.run_stacked): once the stacked batch exists, the
per-tuple weight sums are SURVEY §2.3's "index shards materialized as
dense bucket tensors merged via psum/scatter-add" — and the measured
transport asymmetry (~1 GB/s H2D vs ~12-18 MB/s D2H over the tunneled
plugin, bench round 5) dictates the rest of the shape:

* **Shard-batch staging.**  Rows arrive already perm-ordered by
  (shard, sort keys...), so each shard occupies one contiguous slice.
  Per shard we stage two pow2-padded i64 tensors — the LOCAL group
  code per row (first-occurrence rank of the row's aggregate tuple
  within the shard) and the integer weight — plus one tiny per-query
  translation table mapping local codes to the query-global segment
  ids.  Local codes are a pure function of (query plan, shard
  content): the slice order is the content-stable sort the stacked
  path already proves byte-parity for, and aggregate-tuple EQUALITY is
  content-determined even where global code values are not.  That is
  what makes the big tensors pinnable across queries whose global code
  space differs (a sliding year window re-keys every global id, but
  363 of 365 shard tensors are unchanged).
* **Slot-packed dispatches.**  Shards group by padded row count R and
  pack S-at-a-time (pow2 ladder, bounded by DN_INDEX_DEVICE_BATCH_ROWS
  and _MAX_SLOTS) into one jitted program: gather each slot's local
  codes through its translation row, then one segment_sum into the
  shared accumulator.  A 365-shard year query becomes a handful of
  device launches instead of 365 host group-bys, and the program cache
  stays O(log^2) on (S, R, T) like the scan path's pow2 ladders.
* **Device-resident fold, ONE fetch.**  The i64 accumulator rides
  device-resident through every dispatch as each jit's output fed
  into the next (psum-shaped fold, mesh-ready: under a sharded mesh
  the same program body folds partials with psum), so nothing but the
  final demuxed result ever rides the slow D2H path — np.asarray
  once, at the end.
* **Residency.**  Inside a residency-armed `dn serve`
  (serve/residency.py) the staged shard tensors pin in HBM keyed by
  (plan signature, shard integrity identity) — the integrity
  catalog's (size, crc32) when the tree has one, the handle cache's
  statkey otherwise — and retire on the same writer-epoch signal as
  every other pin, so a repeat dashboard query skips the H2D upload
  entirely.  The folded accumulator additionally pins under its
  content digest (the PR 17 contract), so an exact repeat skips the
  dispatches too.
* **Audition-gated auto.**  The persisted audition cache
  (device_scan.dn_auditions.json) grows an `iq:` verdict family:
  under DN_ENGINE=auto the lane escalates to the device when a fresh
  verdict says the device won this query shape on this backend, and
  auditions (device vs host, timed, byte-compared) only where the
  backend is already warm — a cold `dn query` never pays backend init
  to ask.  DN_INDEX_DEVICE=1 forces the lane, =0 pins the host
  bincount; engine_mode()=jax engages it exactly as before.

Byte identity with the host path is the non-negotiable contract at
every cardinality: sums run in i64 (exact for the integer weights the
stacked gate admits), the audition path verifies equality before
persisting a win, and every structural refusal (overflowing dense
segments, wedged backend, jax unavailable) falls back to the host
bincount with the stacked path's ordering — `canonical_item_sort`
order included — untouched.
"""

import os

import numpy as np

# sticky per-process device availability — SHARED with the legacy
# single-dispatch lane in index_query_stack (one verdict per process,
# whichever lane trips it first)
_DEVICE_STATE = {'ready': None, 'warned': False}

# slot-packed fold programs keyed (nslots, prow, ptab, pu)
_FOLD_CACHE = {}

# per-process engagement snapshot for /stats (server.py reads it):
# dispatches/shards/rows since process start, last auto decision
_ENGAGE = {
    'dispatches': 0,
    'shards': 0,
    'rows': 0,
    'pinned_shard_hits': 0,
    'h2d_bytes': 0,
    'h2d_saved_bytes': 0,
    'auditions': 0,
    'last_lane': None,
}
_MAX_SLOTS = 64


def _reset_device_state():
    """Test hook (shared with index_query_stack)."""
    _DEVICE_STATE['ready'] = None
    _DEVICE_STATE['warned'] = False


def _warn_device(reason):
    if not _DEVICE_STATE['warned']:
        _DEVICE_STATE['warned'] = True
        import sys
        sys.stderr.write('dn: warning: device index-query lane '
                         'unavailable (%s); using host path\n' % reason)


def _reset_engagement():
    """Test/bench hook: zero the per-process engagement snapshot."""
    for k in list(_ENGAGE):
        _ENGAGE[k] = None if k == 'last_lane' else 0


def _pow2(x, floor=8):
    p = floor
    while p < x:
        p <<= 1
    return p


def batch_rows():
    """DN_INDEX_DEVICE_BATCH_ROWS: padded-row budget per dispatch (how
    many shards pack into one launch).  Clamped to a sane floor so a
    misconfigured knob cannot serialize into per-shard dispatches."""
    try:
        v = int(os.environ.get('DN_INDEX_DEVICE_BATCH_ROWS',
                               str(1 << 20)))
    except ValueError:
        v = 1 << 20
    return max(v, 1 << 12)


# -- lane routing -----------------------------------------------------------

def _audition_key(nrows, nuniq):
    """Audition-cache key family for index queries: log2-bucketed
    (rows, uniques) — the two sizes that decide dispatch count and
    accumulator shape — plus the backend identity the verdict was
    measured on (appended by the caller via _backend_id)."""
    lr = _pow2(max(nrows, 1)).bit_length() - 1
    lu = _pow2(max(nuniq, 1)).bit_length() - 1
    return 'iq:r%d:u%d' % (lr, lu)


def _audition_warm():
    """Whether an auto-mode audition may initialize/touch the backend
    here: only when the process already paid backend init (serve
    pre-warm, a prior scan) or a serve residency manager is armed.  A
    cold ad-hoc `dn query` never blocks on plugin bring-up just to
    ask a question the host path answers in milliseconds."""
    from .ops import backend_probed
    if backend_probed():
        return True
    from .serve import residency as mod_residency
    return mod_residency.active() is not None


def lane_decision(nrows, nuniq):
    """('device'|'audition'|'host') for this aggregation.  'device'
    executes with clean host fallback; 'audition' executes BOTH paths,
    byte-compares, times, and persists the verdict the next auto query
    routes on."""
    from .engine import engine_mode, index_device_mode
    mode = index_device_mode()
    if mode == '0':
        return 'host'
    eng = engine_mode()
    if eng == 'jax' or mode == '1':
        return 'device'
    if eng != 'auto':
        return 'host'            # host/vector pins stay host
    from . import device_scan as mod_ds
    hint = mod_ds.audition_cache_shape_hint(_audition_key(nrows,
                                                          nuniq))
    if hint is True:
        return 'device'
    if hint is None and _audition_warm():
        return 'audition'
    return 'host'


# -- shard identity ---------------------------------------------------------

_CATALOG_DIR_MEMO = {}


def _shard_identity(path, statkey):
    """Residency identity for one shard file: the integrity catalog's
    (size, crc32) when the tree publishes one — content identity that
    survives a byte-identical republish — else the handle cache's
    (mtime_ns, size, ino) statkey.  None when neither exists (the
    shard then stages fresh every query, which is always correct)."""
    from . import integrity as mod_integrity
    d = os.path.dirname(os.path.abspath(path))
    for root in (d, os.path.dirname(d)):
        has = _CATALOG_DIR_MEMO.get(root)
        if has is None:
            has = os.path.exists(mod_integrity.catalog_path(root))
            _CATALOG_DIR_MEMO[root] = has
        if not has:
            continue
        try:
            cat = mod_integrity.cached_catalog(root)
        except Exception:
            break
        rel = os.path.relpath(os.path.abspath(path), root)
        ent = cat.get(rel)
        if ent is not None:
            return ('crc', rel, int(ent[0]), int(ent[1]))
    if statkey is not None:
        return ('stat',) + tuple(statkey)
    return None


def plan_signature(query):
    """Digest of everything that determines a shard's staged tensors
    GIVEN its content: the composed filter inputs, the breakdown
    specs (bucketizer parameters included — they live in the spec
    dicts), and the time window.  Two queries with equal signatures
    stage byte-identical (local, weight) tensors from an identical
    shard."""
    import hashlib
    h = hashlib.blake2b(digest_size=12)
    h.update(repr((query.qc_filter, query.qc_breakdowns,
                   query.qc_before, query.qc_after)).encode())
    return h.hexdigest()


# -- staging ----------------------------------------------------------------

def _stage_shard(inv_sl):
    """(local codes i64[n], ttable i64[nlocal], nlocal) for one
    shard's slice of the perm-ordered batch.  Local code = rank of the
    row's aggregate tuple in the shard's first-occurrence order —
    content-stable, so the padded tensor can pin across queries; the
    ttable maps local -> this query's global segment id."""
    lu, first, linv = np.unique(inv_sl, return_index=True,
                                return_inverse=True)
    order = np.argsort(first, kind='stable')
    rankmap = np.empty(len(lu), dtype=np.int64)
    rankmap[order] = np.arange(len(lu), dtype=np.int64)
    local = rankmap[linv.reshape(-1)]
    return local, lu[order], len(lu)


def _pad_slot(local, w, nlocal, prow):
    """Pow2-pad one shard's staged pair: pad rows carry the sentinel
    local code `nlocal`, whose ttable slot points at the accumulator's
    last segment with weight 0 — the same harmless-pad trick the
    legacy single-dispatch lane uses."""
    pl = np.full(prow, nlocal, dtype=np.int64)
    pl[:len(local)] = local
    pw = np.zeros(prow, dtype=np.int64)
    pw[:len(w)] = w
    return pl, pw


# -- the fold program -------------------------------------------------------

def _fold_program(nslots, prow, ptab, pu):
    """Jitted slot-packed scatter-add fold: `nslots` shard tensors of
    `prow` rows each gather their global segment ids through per-slot
    translation rows [ptab] and merge into the i64[pu] accumulator in
    ONE segment_sum.  The accumulator stays device-resident across
    dispatches by riding the jit output back into the next call — the
    psum-shaped fold.  Deliberately NOT donated: donating the
    accumulator buffer segfaults jaxlib 0.4.36's CPU client under the
    multi-device test mesh (flaky heap corruption on repeated
    donate-and-refeed), and the buffer is pu*8 bytes — there is
    nothing worth donating."""
    prog = _FOLD_CACHE.get((nslots, prow, ptab, pu))
    if prog is None:
        from .ops import get_jax
        jax, jnp = get_jax()

        def run(locs, ws, ttabs, acc):
            lmat = jnp.stack(locs)              # [S, prow]
            wmat = jnp.stack(ws)                # [S, prow]
            seg = jnp.take_along_axis(ttabs, lmat, axis=1)
            return acc + jax.ops.segment_sum(
                wmat.reshape(-1), seg.reshape(-1), num_segments=pu)
        prog = jax.jit(run)
        if len(_FOLD_CACHE) >= 32:
            _FOLD_CACHE.pop(next(iter(_FOLD_CACHE)))
        _FOLD_CACHE[(nslots, prow, ptab, pu)] = prog
    return prog


def _residency():
    from .serve import residency as mod_residency
    return mod_residency.active()


def _note_engagement(ndispatch, nshards, nrows, pinned_hits,
                     h2d_bytes, h2d_saved):
    from .obs import metrics as obs_metrics
    _ENGAGE['dispatches'] += ndispatch
    _ENGAGE['shards'] += nshards
    _ENGAGE['rows'] += nrows
    _ENGAGE['pinned_shard_hits'] += pinned_hits
    _ENGAGE['h2d_bytes'] += h2d_bytes
    _ENGAGE['h2d_saved_bytes'] += h2d_saved
    obs_metrics.inc('index_device_dispatches', ndispatch)
    obs_metrics.inc('index_device_shards', nshards)
    obs_metrics.inc('index_device_rows', nrows)
    obs_metrics.inc('index_device_pinned_hits', pinned_hits)
    obs_metrics.inc('index_device_h2d_bytes', h2d_bytes)
    obs_metrics.inc('index_device_h2d_saved_bytes', h2d_saved)
    if ndispatch:
        obs_metrics.set_gauge('index_device_shards_per_dispatch',
                              nshards / ndispatch)


def stats_doc():
    """Engagement snapshot for /stats' device section."""
    doc = dict(_ENGAGE)
    d = doc['dispatches']
    doc['shards_per_dispatch'] = round(doc['shards'] / d, 2) if d \
        else 0.0
    return doc


# -- execution --------------------------------------------------------------

def _device_fold(inv, w64, nuniq, shard_ctx):
    """The staged, slot-packed, device-resident fold.  Returns the
    fetched i64[nuniq] accumulator (host ndarray).  Raises on any
    backend trouble — the caller owns fallback and the sticky state.
    `shard_ctx` is (sids i64[n] ascending, [(path, statkey)] per
    shard, query) from the stacked path, or None (single anonymous
    shard)."""
    from .ops import get_jax
    jax, _jnp = get_jax()
    pu = _pow2(nuniq)

    if shard_ctx is not None:
        sid, pairs, query = shard_ctx
    else:
        sid = np.zeros(len(inv), dtype=np.int64)
        pairs, query = [(None, None)], None
    nshards_total = (int(sid[-1]) + 1) if len(sid) else 0
    bounds = np.searchsorted(sid, np.arange(nshards_total + 1))

    res = _residency()
    repoch = plan = None
    if res is not None:
        from . import index_query_mt as mod_iqmt
        repoch = mod_iqmt.cache_epoch()
        if query is not None:
            plan = plan_signature(query)

    # stage every non-empty shard: pinned device tensors where
    # residency has them, fresh host arrays (uploaded per dispatch,
    # then pinned) otherwise
    staged = []                  # (prow, ttable, dev_local, dev_w)
    pinned_hits = 0
    h2d_bytes = 0
    h2d_saved = 0
    for s in range(nshards_total):
        lo, hi = int(bounds[s]), int(bounds[s + 1])
        if lo == hi:
            continue
        local, ttable, nlocal = _stage_shard(inv[lo:hi])
        prow = _pow2(hi - lo)
        key = None
        if plan is not None and s < len(pairs):
            ident = _shard_identity(*pairs[s]) \
                if pairs[s][0] is not None else None
            if ident is not None:
                key = ('iq-shard', plan, ident, prow)
            dev = res.get_device(key, repoch)
            if dev is not None:
                staged.append((prow, ttable, nlocal, dev[0], dev[1]))
                pinned_hits += 1
                h2d_saved += prow * 16          # two i64 lanes
                continue
        pl, pw = _pad_slot(local, w64[lo:hi], nlocal, prow)
        dl = jax.device_put(pl)
        dw = jax.device_put(pw)
        h2d_bytes += pl.nbytes + pw.nbytes
        if key is not None:
            res.put_device(key, repoch, (dl, dw),
                           nbytes=pl.nbytes + pw.nbytes)
        staged.append((prow, ttable, nlocal, dl, dw))

    if not staged:
        return np.zeros(nuniq, dtype=np.int64), None, 0, 0, 0, 0

    # pack by padded row count: pow2 slot ladder bounded by the
    # batch-rows budget, so a year of daily shards folds in a handful
    # of launches and the program cache stays O(log^2)
    groups = {}
    for st in staged:
        groups.setdefault(st[0], []).append(st)
    budget = batch_rows()
    acc = jax.device_put(np.zeros(pu, dtype=np.int64))
    ndispatch = 0
    for prow in sorted(groups):
        todo = groups[prow]
        smax = max(1, min(_MAX_SLOTS, budget // prow))
        i = 0
        while i < len(todo):
            s = 1
            while s * 2 <= min(smax, len(todo) - i):
                s <<= 1
            chunk = todo[i:i + s]
            i += s
            ptab = _pow2(max(c[2] + 1 for c in chunk))
            ttabs = np.full((s, ptab), pu - 1, dtype=np.int64)
            for j, (_pr, tt, nl, _dl, _dw) in enumerate(chunk):
                ttabs[j, :nl] = tt
            h2d_bytes += ttabs.nbytes
            prog = _fold_program(s, prow, ptab, pu)
            acc = prog(tuple(c[3] for c in chunk),
                       tuple(c[4] for c in chunk), ttabs, acc)
            ndispatch += 1
    try:
        acc.block_until_ready()
    except AttributeError:
        pass
    # ONE fetch: everything upstream stayed on the device
    out = np.asarray(acc)[:nuniq]
    return out, acc, ndispatch, pinned_hits, h2d_bytes, h2d_saved


def batched_sums(inv, weights, nuniq, shard_ctx=None, stage=None,
                 audition=False):
    """Per-tuple weight sums through the batched device engine, or
    None for the host bincount.  Exactness contract: i64 sums over the
    gate-admitted integer weights are bit-equal to the host path.
    The first device contact in the process runs under the probe
    deadline (device_scan.run_with_deadline): a wedged backend warns
    once and falls back instead of hanging `dn query`.  With
    `audition=True` both paths run, results are byte-compared, and
    the timed verdict persists to the audition cache for the next
    auto-mode query."""
    from .engine import MAX_DENSE_SEGMENTS
    from .obs import metrics as obs_metrics
    if nuniq > MAX_DENSE_SEGMENTS or len(inv) == 0:
        return None
    st = _DEVICE_STATE
    if st['ready'] is False:
        return None
    from .ops import get_jax
    if get_jax() is None:
        st['ready'] = False
        _warn_device('jax unavailable')
        return None

    w64 = weights.astype(np.int64)
    res = _residency()
    rkey = repoch = None
    if res is not None:
        from . import index_query_mt as mod_iqmt
        from .serve import residency as mod_residency
        rkey = mod_residency.content_key('iq-acc', (inv, w64),
                                         (_pow2(nuniq), nuniq))
        repoch = mod_iqmt.cache_epoch()
        pinned = res.get(rkey, repoch)
        if pinned is not None:
            _ENGAGE['last_lane'] = 'device'
            if stage is not None:
                stage.bump_hidden('index device sums', 1)
            return pinned.copy()

    import time as mod_time
    t0 = mod_time.monotonic()

    def compute():
        from .ops import backend_ready
        if not backend_ready():
            return None
        return _device_fold(inv, w64, nuniq, shard_ctx)

    if st['ready'] is None:
        from .device_scan import run_with_deadline, probe_deadline_s
        status, out = run_with_deadline(compute, probe_deadline_s(),
                                        'iq-device-batch')
        if status == 'timeout':
            st['ready'] = False
            _warn_device('backend unresponsive past the %.0fs probe '
                         'deadline' % probe_deadline_s())
            return None
        if status == 'error' or out is None:
            st['ready'] = False
            _warn_device('backend failed to initialize')
            return None
        st['ready'] = True
    else:
        try:
            out = compute()
        except Exception as e:
            st['ready'] = False
            _warn_device(repr(e))
            return None
        if out is None:
            st['ready'] = False
            _warn_device('backend failed to initialize')
            return None
    acc, dev_acc, ndispatch, pinned_hits, h2d_bytes, h2d_saved = out
    device_s = mod_time.monotonic() - t0
    host = acc.astype(np.float64)

    nshards = len(shard_ctx[1]) if shard_ctx is not None else 1
    _note_engagement(ndispatch, nshards, len(inv), pinned_hits,
                     h2d_bytes, h2d_saved)
    _ENGAGE['last_lane'] = 'device'
    if stage is not None:
        stage.bump_hidden('index device sums', 1)

    if audition:
        from . import device_scan as mod_ds
        t1 = mod_time.monotonic()
        ref = np.bincount(inv, weights=weights, minlength=nuniq)
        host_s = max(mod_time.monotonic() - t1, 1e-9)
        equal = np.array_equal(host, ref)
        rate_d = len(inv) / max(device_s, 1e-9)
        rate_h = len(inv) / host_s
        won = bool(equal and rate_d > rate_h)
        key = '%s@%s' % (_audition_key(len(inv), nuniq),
                         mod_ds._backend_id())
        mod_ds.audition_cache_put(key, won, device_rate=rate_d,
                                  host_rate=rate_h)
        _ENGAGE['auditions'] += 1
        obs_metrics.inc('index_device_auditions', 1)
        if not equal:
            # never ship an inexact device result — and never trust
            # this lane again this process (exactness gate tripped)
            st['ready'] = False
            _warn_device('device/host sums mismatch (audition)')
            return None

    if res is not None and dev_acc is not None:
        # pin the final device-side accumulator + its one fetched
        # copy: an exact repeat answers with zero transfer either way
        res.put(rkey, repoch, dev_acc, host, h2d_bytes=h2d_bytes)
        return host.copy()
    return host


def aggregate_weights(inv, weights, nuniq, stage=None,
                      shard_ctx=None):
    """The stacked path's aggregation seam: route to the batched
    device engine per lane_decision, host np.bincount otherwise —
    byte-identical either way."""
    lane = lane_decision(len(inv), nuniq)
    if lane != 'host':
        dense = batched_sums(inv, weights, nuniq,
                             shard_ctx=shard_ctx, stage=stage,
                             audition=(lane == 'audition'))
        if dense is not None:
            return dense
    _ENGAGE['last_lane'] = 'host'
    return np.bincount(inv, weights=weights, minlength=nuniq)
