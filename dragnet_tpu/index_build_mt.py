"""Batched, parallel index build: columnar bucket routing and a shard
writer pool.

The build path used to end exactly where the paper says not to:
aggregates were flattened into per-point field dicts tagged with
__dn_metric, each dict cost one ISO-timestamp format to pick its
hour/day shard, one sink.write() call, and every interval shard was
flushed sequentially (BENCH_r05: the 365-shard build leg ran ~275k
rec/s against a 2M rec/s scan).  This module owns the write side's
three fixes, mirroring what index_query_mt did for the read side:

* Columnar blocks: the Aggregator exports each metric's result as
  parallel key columns + weights (Aggregator.point_rows, the same
  decoded values points() emits) — no per-point dicts, no re-lookup of
  breakdown fields by name per point.

* Vectorized bucketing: hour/day shard membership is derived from the
  __dn_ts column with integer floor-division in one numpy pass; the
  ISO label is formatted once per *bucket*, not once per point
  (bucket-min values are step-aligned, so flooring to the interval
  span reproduces the old prefix-of-to_iso_string label exactly).

* A shard writer pool: each bucket's sink is created, bulk-written
  (sink.write_rows), flushed, and cache-invalidated by exactly one
  DN_BUILD_THREADS worker (auto = min(6, cpus-1); 0 = the sequential
  loop).  Output files are byte-identical for any worker count — every
  shard's bytes depend only on its own rows, whose order is pinned to
  the emission order — and the first error re-raises deterministically
  in bucket order after the pool drains.  Undrained pools are caught
  by watchdog.LeakCheck at exit.

StreamingIndexWriter covers the other producer of index files, the
stdin point stream of `dn index-read`: points arrive in bounded chunks
(the old path materialized the whole stream), route through the same
bulk write path, and flush on the same pool.
"""

import os
import threading
from collections import OrderedDict

import numpy as np

from .errors import DNError
from . import jsvalues as jsv
from .index_sink import (make_index_sink, metric_catalog_rows,
                         point_metric, point_row)
from .watchdog import LeakCheck

# a flush executor that is never drained means some shards may never
# have been written (or their errors never surfaced)
_EXECUTOR_LEAKS = LeakCheck(
    'index-build flush executor(s) never drained; index shards may be '
    'missing', lambda ex: not ex.closed)

# -- post-write notification ------------------------------------------------
#
# Every completed index write (build fan-out, streaming index-read,
# the `_index_write` path) already invalidates the reader caches shard
# by shard (shard_cache_invalidate); these hooks additionally tell
# long-lived observers — `dn serve`'s lifecycle layer — that a write
# LANDED, so they can retire whole-tree derived state (find memo,
# handle cache sweeps) and count invalidations coherently.

_WRITE_HOOKS_LOCK = threading.Lock()
_WRITE_HOOKS = []


def register_index_write_hook(fn):
    """fn(indexroot, shard_paths) runs after every completed index
    write.  Hook errors are swallowed (writers must not fail because
    an observer did)."""
    with _WRITE_HOOKS_LOCK:
        _WRITE_HOOKS.append(fn)


def unregister_index_write_hook(fn):
    with _WRITE_HOOKS_LOCK:
        if fn in _WRITE_HOOKS:
            _WRITE_HOOKS.remove(fn)


def _notify_index_written(indexroot, paths):
    with _WRITE_HOOKS_LOCK:
        hooks = list(_WRITE_HOOKS)
    for fn in hooks:
        try:
            fn(indexroot, list(paths))
        except Exception:
            pass


def build_threads():
    """Worker-pool size for the index-write fan-out.  DN_BUILD_THREADS:
    auto (default) = min(6, cpus - 1) — one core stays with the caller
    (which in the build path just submitted and waits, but in the
    streaming path keeps parsing stdin while shards flush); at least 1,
    0 = sequential."""
    v = os.environ.get('DN_BUILD_THREADS', 'auto')
    if v != 'auto':
        try:
            return max(0, int(v))
        except ValueError:
            return 0
    return max(1, min(6, (os.cpu_count() or 2) - 1))


# interval -> (span_seconds, iso-prefix length).  The shard label is
# the prefix of the bucket start's ISO timestamp with 'T' -> '-'
# ('2014-07-02' / '2014-07-02-13'), exactly what the per-point
# to_iso_string slicing produced.
_INTERVALS = {
    'hour': (3600, len('2014-07-02T00')),
    'day': (86400, len('2014-07-02')),
}


def interval_span(interval):
    """Seconds per shard for an hour/day interval (DNError otherwise,
    matching the sequential path's message)."""
    if interval not in _INTERVALS:
        raise DNError('unsupported interval: "%s"' % interval)
    return _INTERVALS[interval][0]


def bucket_label(bucket_s, interval):
    """Shard filename stem for a bucket start (seconds, span-aligned)."""
    prefixlen = _INTERVALS[interval][1]
    return jsv.to_iso_string(bucket_s * 1000)[:prefixlen] \
        .replace('T', '-')


def bucket_starts(ts_values, span):
    """Floor a __dn_ts column to its interval span in one vectorized
    pass — the per-point to_iso_string + date_parse round trip reduced
    to integer arithmetic.  Accepts the Python-number columns the
    Aggregator emits (bucket-min ints; floats tolerated); non-numeric
    values raise the same DNError contract the sinks use."""
    if not ts_values:
        return np.zeros(0, dtype=np.int64)
    try:
        arr = np.asarray(ts_values)
        if arr.dtype == object or arr.dtype.kind not in 'iuf':
            raise ValueError(arr.dtype)
        return (np.floor_divide(arr, span) * span).astype(np.int64)
    except (ValueError, TypeError, OverflowError):
        # mixed/huge values: exact Python floor division, still no
        # per-point string formatting
        out = []
        for t in ts_values:
            if not jsv.is_number(t):
                raise DNError('index point has non-numeric "__dn_ts": '
                              '%r' % (t,))
            out.append(int(t // span) * span)
        return np.asarray(out, dtype=np.int64)


# -- flush pool ------------------------------------------------------------

class SinkFlushExecutor(object):
    """Run per-bucket build tasks across worker threads AND the
    caller's thread (the caller has no merge work during a build, so
    it claims tasks like any worker instead of idling — on a 2-core
    host DN_BUILD_THREADS=1 means two active flushers).

    Tasks are claimed in bucket order off a shared cursor; each runs
    entirely on one thread (so a sink is only ever touched by a single
    thread).  Errors are collected per task index, tasks ordered after
    the earliest known failure are skipped (the sequential loop would
    never have reached them), and after everything drains the earliest
    error — by bucket order, deterministically — is re-raised."""

    def __init__(self, nworkers):
        assert nworkers >= 1, nworkers
        self.closed = False
        _EXECUTOR_LEAKS.track(self)
        self.nworkers = nworkers
        self.lock = threading.Lock()
        self.first_err = None          # (seq, exception)
        self.threads = []
        self._tasks = []
        self._next = 0

    def _drain(self):
        while True:
            with self.lock:
                seq = self._next
                if seq >= len(self._tasks):
                    return
                self._next = seq + 1
                skip = self.first_err is not None and \
                    seq > self.first_err[0]
            if skip:
                continue
            try:
                self._tasks[seq]()
            except BaseException as e:
                with self.lock:
                    if self.first_err is None or seq < self.first_err[0]:
                        self.first_err = (seq, e)

    def run(self, tasks):
        """Execute every task; must be called exactly once.  Raises the
        earliest (bucket-order) task error after all threads drain."""
        self._tasks = list(tasks)
        try:
            for _ in range(self.nworkers):
                t = threading.Thread(target=self._drain, daemon=True)
                t.start()
                self.threads.append(t)
            self._drain()              # the caller works too
        finally:
            self.close()
        if self.first_err is not None:
            raise self.first_err[1]

    def close(self):
        if self.closed:
            return
        with self.lock:
            self._next = len(self._tasks)    # stop claiming
        for t in self.threads:
            t.join()
        self.threads = []
        self.closed = True


def run_flush_tasks(tasks, nworkers=None):
    """Run per-bucket build tasks on the DN_BUILD_THREADS pool
    (nworkers overrides; 0 = the in-order sequential loop, identical
    output bytes either way — a single task skips the pool)."""
    if nworkers is None:
        nworkers = build_threads()
    if nworkers <= 0 or len(tasks) <= 1:
        for task in tasks:
            task()
        return
    ex = SinkFlushExecutor(min(nworkers, len(tasks)))
    ex.run(tasks)


# -- build-side entry: columnar blocks -> sharded index files --------------

def _breakdown_positions(decomp_names, metric):
    """Column index of each of the metric's breakdowns within its
    aggregate's decomposition tuple (duplicate names: last wins, the
    dict-fields behavior of the per-point path)."""
    pos = {name: i for i, name in enumerate(decomp_names)}
    sel = []
    for b in metric.m_breakdowns:
        if b['b_name'] not in pos:
            raise DNError('point is missing breakdown "%s"'
                          % b['b_name'])
        sel.append(pos[b['b_name']])
    return sel


def _prepare_task(metrics, indexpath, config, parts, catalog, suffix,
                  out, i):
    """One bucket's PREPARE, run by exactly one worker: create the
    sink (per-build tmp suffix), bulk-append every metric's rows, and
    write the complete tmp file — no rename yet; the journaled commit
    phase (_publish_buckets) renames every prepared shard at once.
    `catalog` is the shared metric_catalog_rows result — identical in
    every shard, serialized once per build instead of once per
    shard."""
    def task():
        sink = make_index_sink(metrics, indexpath, config=config,
                               catalog=catalog, tmp_suffix=suffix)
        out[i] = sink
        try:
            for mi, keycols, values in parts:
                sink.write_rows(mi, keycols, values)
            sink.prepare()
        except BaseException:
            sink.abort()      # crash hygiene: no tmp litter
            out[i] = None
            raise
    return task


def publish_prepared(journal, sinks, paths, extra_paths=None,
                     deletes=None, integrity_remove=None):
    """The commit phase shared by the block, streaming, and follow
    publishers: land the journal's commit record (THE commit point),
    rename every prepared tmp into place in bucket order, retire the
    journal.

    `extra_paths` is the append-merge publish seam `dn follow` rides:
    non-shard files (its durable checkpoint) whose complete tmps were
    pre-written at journal.tmp_for(final).  They join the SAME commit
    record and rename after the shards, so a batch's shard updates and
    its checkpoint land atomically-or-not-at-all across kill -9 — the
    checkpoint can never claim bytes whose shards rolled back, nor
    miss bytes whose shards rolled forward.

    Rename failures do NOT discard state: the commit record makes the
    tmps durable publish intent, so every remaining tmp and the
    journal stay on disk and the loop keeps renaming what it can —
    the recovery sweep finishes the publish once this process dies,
    or the next build over the tree supersedes the intent
    (index_journal.cleanup_own_stale).  The earliest bucket-order
    error still re-raises so the caller reports the failure.

    Integrity: every prepared shard tmp is checksummed (size + crc32)
    BEFORE the commit record lands; the checksums ride the record (so
    a crash between record and catalog is recovered by the sweep's
    roll-forward) and land in the per-tree `.dn_integrity.json`
    catalog after the renames — verified reads (DN_VERIFY) and `dn
    scrub` compare committed bytes against exactly what this publish
    wrote.  extra_paths (the follow checkpoint, not a shard) are
    excluded: the catalog describes the queryable shard set.

    `deletes` + `integrity_remove` are the compactor's supersede
    seam: generation shards consumed by a rewrite ride the commit
    record and are unlinked (and de-catalogued) only AFTER every
    rename lands — a crash at any instant leaves either the full old
    generation set or the compacted shard (possibly plus stale
    generations the roll-forward/next pass retires), never a tree
    missing rows."""
    from . import integrity as mod_integrity
    from .index_query_mt import shard_cache_invalidate
    from .obs import metrics as obs_metrics
    extra_paths = list(extra_paths or [])
    with obs_metrics.timed_stage('index_build.commit',
                                 nshards=len(paths)):
        integ = mod_integrity.integrity_entries(
            [os.path.abspath(p) for p in paths],
            tmp_for=journal.tmp_for)
        try:
            journal.record_commit(list(paths) + extra_paths,
                                  integrity=integ, deletes=deletes,
                                  integrity_remove=integrity_remove)
        except BaseException:
            # PRE-commit failure (e.g. ENOSPC on the record itself):
            # nothing was published, so the prepared tmps are not
            # recoverable intent — discard them all.  A retry loop
            # (follow's publish backoff) must never fill the disk
            # with one stranded prepared set per failed attempt.
            for sink in sinks:
                if sink is not None:
                    sink.abort()
            for path in extra_paths:
                try:
                    os.unlink(journal.tmp_for(path))
                except OSError:
                    pass
            raise
        err = None
        for sink, path in zip(sinks, paths):
            try:
                sink.commit(discard_on_error=False)
                shard_cache_invalidate(path)
            except BaseException as e:
                if err is None:
                    err = e
        for path in extra_paths:
            try:
                os.rename(journal.tmp_for(path), path)
            except OSError as e:
                if err is None:
                    err = e
        if err is not None:
            raise err
        mod_integrity.record_published(integ)
        if deletes or integrity_remove:
            from . import index_journal as mod_journal
            mod_journal.apply_commit_deletes({
                'deletes': list(deletes or []),
                'integrity_remove': dict(integrity_remove or {})})
        journal.retire()


def _publish_buckets(metrics, indexroot, buckets, catalog, nworkers):
    """Two-phase publish of one build's whole shard set.  `buckets` is
    [(indexpath, config, parts)] in bucket order.  Phase 1 prepares
    every shard's complete tmp on the flush pool; phase 2 is
    publish_prepared.  A crash at any instant leaves a tree the
    recovery sweep lands on exactly pre-build (no commit record: tmps
    quarantined) or exactly post-build (commit record: renames
    finished) — never a mix.  Prepare-phase errors keep the seed
    contract: the earliest bucket-order error re-raises and no tmp
    litter survives."""
    from . import index_journal as mod_journal
    from .obs import metrics as obs_metrics
    from .obs import trace as obs_trace

    mod_journal.sweep_index_tree(indexroot)
    mod_journal.cleanup_own_stale(indexroot)
    journal = mod_journal.BuildJournal(indexroot)
    paths = [p for p, config, parts in buckets]
    sinks = [None] * len(buckets)
    tasks = [_prepare_task(metrics, path, config, parts, catalog,
                           journal.tmp_suffix, sinks, i)
             for i, (path, config, parts) in enumerate(buckets)]
    try:
        with obs_metrics.timed_stage('index_build.prepare',
                                     nshards=len(buckets)):
            run_flush_tasks(tasks, nworkers)
    except BaseException:
        for sink in sinks:
            if sink is not None:
                sink.abort()
        raise
    with obs_trace.span('index_build.publish', nshards=len(paths)):
        publish_prepared(journal, sinks, paths)
        _notify_index_written(indexroot, paths)


def write_index_blocks(metrics, interval, indexroot, blocks,
                       nworkers=None):
    """Write per-metric columnar aggregate blocks into interval-chunked
    index files.  `blocks` is one (decomp_names, key_columns, weights)
    triple per metric — Aggregator.point_rows output plus its decomp
    names — in metric order.  Behaviorally identical to the retired
    per-point loop (same files, same bytes, same dn_start config) for
    any worker count; the shard set publishes through the crash-safe
    journal (_publish_buckets)."""
    catalog = metric_catalog_rows(metrics)
    if interval == 'all':
        parts = []
        for mi, (names, cols, weights) in enumerate(blocks):
            sel = _breakdown_positions(names, metrics[mi])
            parts.append((mi, [cols[p] for p in sel], weights))
        allpath = os.path.join(indexroot, 'all')
        _publish_buckets(metrics, indexroot,
                         [(allpath, None, parts)], catalog, nworkers)
        return

    span = interval_span(interval)
    root = os.path.join(indexroot, 'by_' + interval)

    buckets = OrderedDict()     # bucket_s -> [(mi, keycols, values)]
    for mi, (names, cols, weights) in enumerate(blocks):
        if not weights:
            continue
        if '__dn_ts' not in names:
            raise DNError('point is missing breakdown "__dn_ts"')
        sel = _breakdown_positions(names, metrics[mi])
        bs = bucket_starts(cols[names.index('__dn_ts')], span)
        uniq, inv = np.unique(bs, return_inverse=True)
        inv = inv.reshape(-1)   # numpy-2 return_inverse shape quirk
        if len(uniq) == 1:
            # single-shard metric: append the columns whole
            buckets.setdefault(int(uniq[0]), []).append(
                (mi, [cols[p] for p in sel], weights))
            continue
        # stable sort by bucket keeps each bucket's rows in emission
        # order — the property that makes the output byte-identical to
        # the per-point sequential loop
        order = np.argsort(inv, kind='stable').tolist()
        counts = np.bincount(inv).tolist()
        pos = 0
        selcols = [cols[p] for p in sel]
        for k, b in enumerate(uniq.tolist()):
            idxs = order[pos:pos + counts[k]]
            pos += counts[k]
            buckets.setdefault(int(b), []).append(
                (mi,
                 [[col[i] for i in idxs] for col in selcols],
                 [weights[i] for i in idxs]))

    ordered = []
    for bucket_s in sorted(buckets):
        indexpath = os.path.join(
            root, bucket_label(bucket_s, interval) + '.sqlite')
        ordered.append((indexpath, {'dn_start': bucket_s},
                        buckets[bucket_s]))
    _publish_buckets(metrics, indexroot, ordered, catalog, nworkers)


# -- streaming entry: tagged point chunks -> sharded index files -----------

class StreamingIndexWriter(object):
    """Incremental tagged-point index writer (the `dn index-read`
    path): chunks of (fields, value) points — each carrying
    __dn_metric and, for hour/day intervals, __dn_ts — route to
    per-bucket sinks through the bulk write path, and finish() flushes
    every sink on the build pool.  Peak memory is bounded by the chunk
    size plus the sinks' own buffering (for the SQLite engine rows go
    straight to disk; DNC buffers unique aggregate tuples, the
    reference's own memory model), not by the stream length.

    Sinks are created on the caller's thread and flushed by exactly
    one worker; access is serialized by the task structure."""

    def __init__(self, metrics, interval, indexroot):
        from . import index_journal as mod_journal
        self.metrics = metrics
        self.interval = interval
        self.indexroot = indexroot
        # every sink writes tmps under this build's id; finish()
        # publishes the whole set through the commit journal
        mod_journal.sweep_index_tree(indexroot)
        mod_journal.cleanup_own_stale(indexroot)
        self._journal = mod_journal.BuildJournal(indexroot)
        self._catalog = metric_catalog_rows(metrics)
        self._names = [[b['b_name'] for b in m.m_breakdowns]
                       for m in metrics]
        if interval == 'all':
            self.span = None
            self.root = indexroot
        else:
            self.span = interval_span(interval)
            self.root = os.path.join(indexroot, 'by_' + interval)
        self.sinks = OrderedDict()      # bucket_s (or None) -> sink
        self.sinkpaths = {}

    def _sink_for(self, bucket_s):
        sink = self.sinks.get(bucket_s)
        if sink is None:
            if bucket_s is None:
                indexpath = os.path.join(self.root, 'all')
                config = None
            else:
                indexpath = os.path.join(
                    self.root,
                    bucket_label(bucket_s, self.interval) + '.sqlite')
                config = {'dn_start': bucket_s}
            sink = make_index_sink(self.metrics, indexpath,
                                   config=config,
                                   catalog=self._catalog,
                                   tmp_suffix=self._journal.tmp_suffix)
            self.sinks[bucket_s] = sink
            self.sinkpaths[bucket_s] = indexpath
        return sink

    def write_points(self, points):
        """Route one bounded chunk of tagged points.  Rows are grouped
        per (bucket, metric) in first-appearance order — for the
        metric-major streams index-scan emits, the resulting insert
        order is identical to the per-point loop's."""
        groups = OrderedDict()
        for fields, value in points:
            mi = point_metric(fields, len(self.metrics))
            if self.span is None:
                bucket_s = None
            else:
                dnts = fields.get('__dn_ts')
                if not jsv.is_number(dnts):
                    raise DNError('index point has non-numeric '
                                  '"__dn_ts": %r' % (dnts,))
                bucket_s = int(dnts // self.span) * self.span
            groups.setdefault((bucket_s, mi), []).append(
                (point_row(fields, self._names[mi]), value))
        for (bucket_s, mi), rows in groups.items():
            sink = self._sink_for(bucket_s)
            if self._names[mi]:
                keycols = [list(c) for c in
                           zip(*[r for r, v in rows])]
            else:
                keycols = []
            sink.write_rows(mi, keycols, [v for r, v in rows])

    def abort(self):
        """Discard everything: close every sink and best-effort unlink
        its tmp file (mid-stream failure must leave the index
        directory clean)."""
        for sink in self.sinks.values():
            sink.abort()

    def finish(self, nworkers=None):
        """Publish every bucket sink through the two-phase journal:
        prepare each complete tmp on the pool, land the commit record,
        then rename the whole set (see _publish_buckets — same crash
        contract).  On a prepare error the remaining sinks are aborted
        (no tmp litter) and the earliest bucket-order error
        re-raises."""
        if self.span is None and not self.sinks:
            # an 'all' build always writes its (possibly empty) index
            # file — a zero-point stream must still produce a queryable
            # catalog, exactly like the per-point path did
            self._sink_for(None)
        entries = list(self.sinks.items())
        done = [False] * len(entries)

        def make_task(i, sink):
            def task():
                try:
                    sink.prepare()
                except BaseException:
                    sink.abort()
                    raise
                done[i] = True
            return task

        tasks = [make_task(i, sink)
                 for i, (key, sink) in enumerate(entries)]
        try:
            run_flush_tasks(tasks, nworkers)
        except BaseException:
            for i, (key, sink) in enumerate(entries):
                if not done[i]:
                    sink.abort()
            raise
        paths = [self.sinkpaths[key] for key, sink in entries]
        publish_prepared(self._journal, [s for k, s in entries],
                         paths)
        _notify_index_written(self.indexroot, paths)
