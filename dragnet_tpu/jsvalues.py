"""JavaScript value semantics needed for byte-identical behavior parity.

The reference implementation (TritonDataCenter/dragnet) is a Node.js program;
its observable behavior — output formatting, predicate evaluation, date
parsing — leans on JavaScript value semantics.  This module concentrates every
such rule in one place so that the rest of the framework can be written as
straightforward Python/JAX:

* number -> string conversion (JS Number#toString; reference: everywhere a
  value is printed, e.g. bin/dn:1066-1076),
* String(v) coercion incl. null -> "null", missing -> "undefined"
  (reference: skinner aggregation keys, observed in tests/dn goldens),
* loose equality / relational comparison for predicate evaluation
  (reference: krill predicate eval via JS == and < operators,
  lib/krill-skinner-stream.js:38),
* Date.parse for ISO-8601 timestamps, ES5 semantics (missing timezone means
  UTC; reference: lib/stream-synthetic.js:68),
* Date#toISOString (reference: bin/dn:1020-1022, histogram labels),
* JSON.stringify-compatible encoding (reference: --points output,
  bin/dn:972-975; config serialization, lib/config-local.js:101).

Sentinel: JS distinguishes null from undefined (absent).  We represent JS
null as Python None and JS undefined as the UNDEFINED sentinel.
"""

import math
import re
from datetime import datetime, timezone


class _Undefined(object):
    """Sentinel for JavaScript `undefined` (distinct from null/None)."""
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super(_Undefined, cls).__new__(cls)
        return cls._instance

    def __repr__(self):
        return 'undefined'

    def __bool__(self):
        return False


UNDEFINED = _Undefined()


def is_number(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def as_float(v):
    """float(v) with JS overflow semantics: Python ints beyond float64
    range become +-Infinity (JS numbers are doubles throughout)."""
    try:
        return float(v)
    except OverflowError:
        return math.inf if v > 0 else -math.inf


def number_to_string(v):
    """JS Number#toString(10): shortest round-trip decimal.

    Integral floats print without a decimal point (JS has no int/float
    distinction); NaN -> "NaN", Infinity -> "Infinity".  Exponential notation
    kicks in at >= 1e21 or < 1e-6, matching ECMA-262 Number::toString.
    """
    if isinstance(v, bool):
        return 'true' if v else 'false'
    if isinstance(v, int):
        # JS numbers are doubles: integers beyond 2^53 lose precision and
        # print as the shortest round-trip digits zero-padded, not the
        # exact value.
        if -(1 << 53) <= v <= (1 << 53):
            return str(v)
        v = as_float(v)
    else:
        # normalize numpy scalars (np.float64 subclasses float but its
        # numpy-2.x repr() wraps the value in its type, breaking the
        # shortest-round-trip logic below)
        v = float(v)
    if math.isnan(v):
        return 'NaN'
    if math.isinf(v):
        return 'Infinity' if v > 0 else '-Infinity'
    if v == int(v) and abs(v) < 1e21:
        iv = int(v)
        if -(1 << 53) <= iv <= (1 << 53):
            return str(iv)
        # Shortest round-trip digits, zero-padded (JS Number#toString).
        mant, exp = ('%.17e' % v).split('e')
        s = repr(v)
        if 'e' in s or 'E' in s:
            mant, exp = s.lower().split('e')
        else:
            return s
        digits = mant.replace('.', '').replace('-', '').rstrip('0') or '0'
        sign = '-' if v < 0 else ''
        return sign + digits.ljust(int(exp) + 1, '0')
    # repr() gives the shortest round-trip form, like V8.
    s = repr(v)
    if 'e' in s:
        # Python: 1e+21 / 1e-07; JS: 1e+21 / 1e-7 (no zero-padded exponent)
        mant, exp = s.split('e')
        exp = int(exp)
        s = mant + 'e' + ('+' if exp >= 0 else '-') + str(abs(exp))
    else:
        av = abs(v)
        if av != 0 and av < 1e-6:
            # JS switches to exponential below 1e-6; Python repr does not
            # always.  Convert.
            mant, exp = ('%e' % v).split('e')
            mant = mant.rstrip('0').rstrip('.')
            s = mant + 'e' + ('-' if int(exp) < 0 else '+') + \
                str(abs(int(exp)))
    return s


def to_string(v):
    """JS String(v) coercion."""
    if v is UNDEFINED:
        return 'undefined'
    if v is None:
        return 'null'
    if isinstance(v, bool):
        return 'true' if v else 'false'
    if is_number(v):
        return number_to_string(v)
    if isinstance(v, str):
        return v
    if isinstance(v, list):
        return ','.join('' if x is None or x is UNDEFINED else to_string(x)
                        for x in v)
    if isinstance(v, dict):
        return '[object Object]'
    return str(v)


def to_number(v):
    """JS ToNumber coercion.  Returns float (NaN on failure)."""
    if v is UNDEFINED:
        return float('nan')
    if v is None:
        return 0.0
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if is_number(v):
        return as_float(v)
    if isinstance(v, str):
        s = v.strip()
        if s == '':
            return 0.0
        try:
            if s.startswith('0x') or s.startswith('0X'):
                return float(int(s, 16))
            return float(s)
        except ValueError:
            return float('nan')
    return float('nan')


def loose_eq(a, b):
    """JS abstract equality (==) for the value types JSON can carry."""
    a_null = a is None or a is UNDEFINED
    b_null = b is None or b is UNDEFINED
    if a_null or b_null:
        return a_null and b_null
    a_num = is_number(a) or isinstance(a, bool)
    b_num = is_number(b) or isinstance(b, bool)
    if isinstance(a, str) and isinstance(b, str):
        return a == b
    if a_num and b_num:
        fa, fb = as_float(a), as_float(b)
        return fa == fb and not (math.isnan(fa) or math.isnan(fb))
    if a_num and isinstance(b, str):
        fb = to_number(b)
        return as_float(a) == fb and not math.isnan(fb)
    if isinstance(a, str) and b_num:
        fa = to_number(a)
        return fa == as_float(b) and not math.isnan(fa)
    # object vs primitive: ToPrimitive coerces via toString
    # ([1,2] == "1,2" is true in JS; {} == "[object Object]" too)
    a_obj = isinstance(a, (list, dict))
    b_obj = isinstance(b, (list, dict))
    if a_obj and not b_obj:
        return loose_eq(to_string(a), b)
    if b_obj and not a_obj:
        return loose_eq(a, to_string(b))
    # object vs object: identity
    return a is b


def relational(a, b, op):
    """JS relational comparison (<, <=, >, >=).

    If both operands are strings, compare lexicographically; otherwise
    numerically (NaN makes every comparison false).  Objects coerce via
    ToPrimitive (toString).
    """
    if isinstance(a, (list, dict)):
        a = to_string(a)
    if isinstance(b, (list, dict)):
        b = to_string(b)
    if isinstance(a, str) and isinstance(b, str):
        if op == 'lt':
            return a < b
        if op == 'le':
            return a <= b
        if op == 'gt':
            return a > b
        return a >= b
    fa, fb = to_number(a), to_number(b)
    if math.isnan(fa) or math.isnan(fb):
        return False
    if op == 'lt':
        return fa < fb
    if op == 'le':
        return fa <= fb
    if op == 'gt':
        return fa > fb
    return fa >= fb


_ISO_RE = re.compile(
    r'^(\d{4})(?:-(\d{2})(?:-(\d{2}))?)?'
    r'(?:[T ](\d{2}):(\d{2})(?::(\d{2})(?:\.(\d{1,6})\d*)?)?'
    r'(Z|[+-]\d{2}:?\d{2})?)?$')


def date_parse(s):
    """JS Date.parse subset: ISO-8601 (ES5: missing offset == UTC).

    Returns milliseconds since epoch, or None (JS NaN) if unparseable.
    Handles the formats dragnet data actually uses: full ISO with 'Z' or
    offset, date-only, and space-separated datetime.
    """
    if not isinstance(s, str):
        return None
    m = _ISO_RE.match(s.strip())
    if m is None:
        return None
    year = int(m.group(1))
    month = int(m.group(2) or 1)
    day = int(m.group(3) or 1)
    hour = int(m.group(4) or 0)
    minute = int(m.group(5) or 0)
    sec = int(m.group(6) or 0)
    frac = m.group(7)
    ms = int((frac or '0').ljust(3, '0')[:3]) if frac else 0
    us = ms * 1000
    tz = m.group(8)
    try:
        dt = datetime(year, month, day, hour, minute, sec, us,
                      tzinfo=timezone.utc)
    except ValueError:
        return None
    epoch_ms = int(dt.timestamp() * 1000)
    # timestamp() can lose sub-ms precision; recompute exactly
    epoch_ms = (int(datetime(year, month, day, hour, minute, sec,
                             tzinfo=timezone.utc).timestamp()) * 1000) + ms
    if tz and tz != 'Z':
        sign = 1 if tz[0] == '+' else -1
        tzh = int(tz[1:3])
        tzm = int(tz[-2:])
        epoch_ms -= sign * (tzh * 60 + tzm) * 60000
    return epoch_ms


def to_iso_string(epoch_ms):
    """JS Date#toISOString: always UTC with milliseconds."""
    ms = int(epoch_ms)
    dt = datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc)
    # avoid float rounding: compute components from integer math
    secs, msec = divmod(ms, 1000)
    dt = datetime.fromtimestamp(secs, tz=timezone.utc)
    return '%04d-%02d-%02dT%02d:%02d:%02d.%03dZ' % (
        dt.year, dt.month, dt.day, dt.hour, dt.minute, dt.second, msec)


def _json_escape(s):
    out = []
    for ch in s:
        if ch == '"':
            out.append('\\"')
        elif ch == '\\':
            out.append('\\\\')
        elif ch == '\n':
            out.append('\\n')
        elif ch == '\r':
            out.append('\\r')
        elif ch == '\t':
            out.append('\\t')
        elif ch == '\b':
            out.append('\\b')
        elif ch == '\f':
            out.append('\\f')
        elif ord(ch) < 0x20:
            out.append('\\u%04x' % ord(ch))
        else:
            out.append(ch)
    return ''.join(out)


def json_stringify(v):
    """JSON.stringify: compact, insertion-ordered keys, JS number format.

    Properties with value `undefined` are omitted (JS behavior); a top-level
    undefined returns None (JS returns undefined, which console.log prints as
    "undefined").
    """
    if v is UNDEFINED:
        return None
    if v is None:
        return 'null'
    if isinstance(v, bool):
        return 'true' if v else 'false'
    if is_number(v):
        if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
            return 'null'
        return number_to_string(v)
    if isinstance(v, str):
        return '"' + _json_escape(v) + '"'
    if isinstance(v, (list, tuple)):
        parts = []
        for x in v:
            sv = json_stringify(x)
            parts.append('null' if sv is None else sv)
        return '[' + ','.join(parts) + ']'
    if isinstance(v, dict):
        parts = []
        for k, val in v.items():
            sv = json_stringify(val)
            if sv is None:
                continue
            parts.append('"' + _json_escape(str(k)) + '":' + sv)
        return '{' + ','.join(parts) + '}'
    raise TypeError('cannot stringify %r' % (v,))


def json_parse(text):
    """JSON.parse with V8-compatible error messages (for CLI parity).

    Returns the parsed value; raises ValueError whose message matches V8's
    SyntaxError messages for the common cases exercised by the reference
    tests (e.g. "Unexpected end of input" for truncated input;
    reference: tests/dn/local/tst.badargs.sh.out, tst.config.sh.out).
    """
    import json as _json
    try:
        return _json.loads(text, parse_constant=_reject_nonfinite)
    except _json.JSONDecodeError as e:
        msg = _v8_json_error(text, e)
        raise ValueError(msg)


def _reject_nonfinite(name):
    # Python's json accepts NaN/Infinity/-Infinity as an extension;
    # JSON.parse does not, and downstream engines diverge on non-finite
    # constants (SQL has no literal for them) — reject with the token
    # V8's tokenizer would report.
    raise ValueError('Unexpected token %s' % name.lstrip('-')[0])


def _v8_json_error(text, e):
    if e.pos >= len(text.rstrip()) or 'Expecting' in e.msg and \
            e.pos >= len(text):
        return 'Unexpected end of input'
    if e.pos >= len(text):
        return 'Unexpected end of input'
    ch = text[e.pos] if e.pos < len(text) else ''
    if ch:
        return 'Unexpected token %s' % ch
    return 'Unexpected end of input'


def inspect(v, depth=0):
    """Approximate Node util.inspect() for plain JSON-ish values.

    Used for krill-style error messages, e.g.
    `predicate { junk: [ 'foo', 'bar' ] }: unknown operator "junk"`
    (reference: krill validation, observed in tst.badargs.sh.out).
    """
    if v is None:
        return 'null'
    if v is UNDEFINED:
        return 'undefined'
    if isinstance(v, bool):
        return 'true' if v else 'false'
    if is_number(v):
        return number_to_string(v)
    if isinstance(v, str):
        return "'" + v.replace('\\', '\\\\').replace("'", "\\'") + "'"
    if isinstance(v, (list, tuple)):
        if not v:
            return '[]'
        return '[ ' + ', '.join(inspect(x, depth + 1) for x in v) + ' ]'
    if isinstance(v, dict):
        if not v:
            return '{}'
        parts = []
        for k, val in v.items():
            key = k if re.match(r'^[A-Za-z_$][A-Za-z0-9_$]*$', str(k)) \
                else "'" + str(k) + "'"
            parts.append('%s: %s' % (key, inspect(val, depth + 1)))
        return '{ ' + ', '.join(parts) + ' }'
    return str(v)


def pluck(obj, key):
    """jsprim.pluck: direct property first, then split on the first dot.

    This direct-key-first rule is what makes skinner points re-ingestable:
    a point {"req.method": "GET"} round-trips even though the raw record was
    {"req": {"method": "GET"}}.  (reference: jsprim pluckv, used by
    lib/stream-synthetic.js:50 and skinner decomposition.)
    """
    while True:
        if not isinstance(obj, dict):
            return UNDEFINED
        if key in obj:
            return obj[key]
        i = key.find('.')
        if i == -1:
            return UNDEFINED
        obj = obj.get(key[:i], UNDEFINED)
        key = key[i + 1:]
