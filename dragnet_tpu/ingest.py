"""Ingest: newline-separated JSON (and json-skinner points) -> records.

Re-implements the reference's parse layer (lib/format-json.js):

* the byte stream is the *concatenation* of all found files (catstreams
  semantics: a partial trailing line joins across file boundaries),
* each line is JSON-decoded; undecodable lines bump the "json parser"
  stage's "invalid json" counter and are dropped,
* format "json": each object becomes a record with weight 1
  (SkinnerAdapterStream),
* format "json-skinner": each object is already {"fields":...,"value":N}.

The iterator yields (fields_dict, value) pairs.  A columnar fast path
(batch.py / ops/) consumes the same line stream in blocks.
"""

import json

from .errors import DNError


def parser_for(fmt):
    """Validate a datasource format name.

    Contract: RETURNS (never raises) the parser token for a supported
    format, or a DNError instance for anything else — the datasource
    error-plumbing convention (create_datasource, _scan_init, and the
    find layer all return DNError for config-shaped failures and let
    the command layer raise).  Every call site must isinstance-check
    the result; tests/test_ingest.py pins both halves of the
    contract."""
    if fmt == 'json-skinner':
        return 'json-skinner'
    if fmt == 'json':
        return 'json'
    return DNError('unsupported format: "%s"' % fmt)


def open_byte_source(path, chunk_size=1 << 20):
    """THE pluggable fetcher seam: every ingest path obtains raw bytes
    as a chunk iterator of this shape — local files are the only
    built-in fetcher.  A remote-object-store backend (the reference's
    Manta listInputs/fetch, lib/datasource-manta.js:392-433) would
    plug in here by yielding fetched chunks for a remote path; today
    remote ingest is an explicit, documented non-goal
    (docs/architecture.md) and a shared filesystem is the contract."""
    with open(path, 'rb') as f:
        while True:
            chunk = f.read(chunk_size)
            if not chunk:
                break
            yield chunk


class LineAssembler(object):
    """THE chunk-boundary joiner, incremental form: feed() byte chunks
    in, get back buffers of COMPLETE lines (trailing newline
    included); a chunk ending mid-line is *held* — never emitted as a
    truncated record — until more bytes arrive or the caller flushes
    (EOF / stop).  One implementation serves the batch paths
    (iter_chunk_lines, iter_line_buffers, and through them iter_lines
    / iter_stream_lines / the raw-byte parse lanes) AND the live-tail
    path (`dn follow`'s source tailer), so the join-across-chunks
    semantics can't drift apart.

    The live-tail case is why the carry must be explicit: a growing
    file routinely ends mid-line (the appender's write() landed
    between our read()s), and a joiner that emitted the partial tail
    at iterator end would hand the parser a truncated record that the
    eventual complete line then duplicates.

    The carry between chunks is a *list* of chunk references, joined
    only when a newline finally arrives — appending chunks to a bytes
    buffer would re-copy the whole accumulated tail every read and go
    quadratic on multi-MB single-line inputs."""

    __slots__ = ('_tail', '_npending')

    def __init__(self):
        self._tail = []
        self._npending = 0

    def feed(self, chunk):
        """Absorb one chunk; returns a buffer of complete lines
        (possibly spanning the held carry), or b'' when the chunk left
        no line complete."""
        nl = chunk.rfind(b'\n')
        if nl == -1:
            if chunk:
                self._tail.append(chunk)
                self._npending += len(chunk)
            return b''
        head = chunk[:nl + 1]
        if self._tail:
            self._tail.append(head)
            head = b''.join(self._tail)
            self._tail = []
            self._npending = 0
        rest = chunk[nl + 1:]
        if rest:
            self._tail.append(rest)
            self._npending = len(rest)
        return head

    def pending(self):
        """Bytes currently held mid-line (the tailer's checkpoint
        offset is its read position minus this)."""
        return self._npending

    def flush(self):
        """Give up the held partial line (no trailing newline), or
        b''.  EOF-at-stop semantics: a file whose last line is
        unterminated still yields that line when the stream ends, just
        as the batch paths (and the reference's catstreams) do."""
        if not self._tail:
            return b''
        out = b''.join(self._tail)
        self._tail = []
        self._npending = 0
        return out


def iter_chunk_lines(chunks):
    """Yield complete lines (no newline) from an iterable of byte
    chunks, joining lines split across chunk boundaries
    (LineAssembler); a final partial line flushes last."""
    asm = LineAssembler()
    for chunk in chunks:
        buf = asm.feed(chunk)
        if buf:
            for line in buf[:-1].split(b'\n'):
                yield line
    last = asm.flush()
    if last:
        yield last


def iter_line_buffers(chunks):
    """The same joiner at buffer granularity: yield byte buffers that
    end on a line boundary (trailing newline included; a final partial
    line flushes last, without one).  This is the ingest unit of the
    columnar byte-parse lanes — one buffer per read chunk, complete
    lines only, identical carry discipline to iter_chunk_lines."""
    asm = LineAssembler()
    for chunk in chunks:
        buf = asm.feed(chunk)
        if buf:
            yield buf
    last = asm.flush()
    if last:
        yield last


def _file_chunks(paths, chunk_size):
    for path in paths:
        for chunk in open_byte_source(path, chunk_size):
            yield chunk


def iter_lines(paths, chunk_size=1 << 20):
    """Yield decoded text lines from the concatenated contents of
    paths (catstreams semantics: a partial trailing line joins across
    file boundaries)."""
    return iter_chunk_lines(_file_chunks(paths, chunk_size))


def _stream_chunks(instream, chunk_size):
    while True:
        chunk = instream.read(chunk_size)
        if not chunk:
            break
        if isinstance(chunk, str):
            chunk = chunk.encode()
        yield chunk


def iter_stream_lines(instream, chunk_size=1 << 20):
    """Yield lines from an already-open (binary or text) stream in
    bounded chunks — the stdin ingest path (`dn index-read`) must not
    materialize the whole pipe.  A trailing line without a newline is
    still yielded."""
    return iter_chunk_lines(_stream_chunks(instream, chunk_size))


def make_parser_stages(pipeline, fmt):
    """Create the parse-layer pipeline stages eagerly so --counters output
    preserves the reference's stage order (parser before scan stages)."""
    parser_stage = pipeline.stage('json parser')
    adapter_stage = pipeline.stage('SkinnerAdapterStream') \
        if fmt == 'json' else None
    return (parser_stage, adapter_stage)


def iter_records(lines, fmt, pipeline=None, stages=None):
    """Yield (fields, value) records with parse counters.

    `fmt` is 'json' or 'json-skinner'.
    """
    if stages is not None:
        parser_stage, adapter_stage = stages
    elif pipeline is not None:
        parser_stage, adapter_stage = make_parser_stages(pipeline, fmt)
    else:
        parser_stage = adapter_stage = None

    for line in lines:
        if parser_stage is not None:
            parser_stage.bump('ninputs')
        try:
            obj = json.loads(line)
        except ValueError as e:
            if parser_stage is not None:
                parser_stage.warn(e, 'invalid json')
            continue
        if parser_stage is not None:
            parser_stage.bump('noutputs')
        if fmt == 'json':
            if adapter_stage is not None:
                adapter_stage.bump('ninputs')
                adapter_stage.bump('noutputs')
            yield (obj, 1)
        else:
            yield (obj.get('fields', {}), obj.get('value'))
