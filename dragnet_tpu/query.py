"""Query model: QueryConfig, field parsing, bucketizers, metric model.

Re-implements the reference's query normalization layer
(lib/dragnet.js:28-244) and the metric (de)serialization + per-metric query
synthesis of lib/dragnet-impl.js:243-323, plus the two skinner bucketizers
(power-of-two and linear) whose semantics are pinned by the golden outputs:

* p2: value 0 -> bucket 0; value v >= 1 -> bucket floor(log2(v)) + 1;
  bucket_min(0) = 0, bucket_min(i) = 2^(i-1)   (DTrace quantize shape)
* linear(step): bucket floor(v/step); bucket_min(i) = i*step

Bucket ordinals are the internal representation (skinner `ordinalBuckets`);
points and index rows carry bucket-min values so that partial aggregates
re-aggregate idempotently (the map-reduce composability seam).
"""

import math

from .errors import DNError
from . import jsvalues as jsv
from . import krill as mod_krill


class P2Bucketizer(object):
    """Power-of-two bucketizer (skinner makeP2Bucketizer)."""

    def bucketize(self, v):
        if v < 1:
            return 0
        if isinstance(v, int):
            return v.bit_length()
        return math.frexp(v)[1]

    def bucket_min(self, i):
        if i <= 0:
            return 0
        return 1 << (i - 1)


class LinearBucketizer(object):
    """Linear bucketizer with fixed step (skinner makeLinearBucketizer)."""

    def __init__(self, step):
        self.step = step

    def bucketize(self, v):
        return int(math.floor(v / self.step))

    def bucket_min(self, i):
        return i * self.step


class QueryConfig(object):
    """Immutable parameters of a query (reference: lib/dragnet.js:28-77)."""

    def __init__(self, filter=None, breakdowns=None, time_before=None,
                 time_after=None, time_field=None):
        self.qc_filter = filter if filter is not None else None
        self.qc_breakdowns = [dict(b) for b in (breakdowns or [])]
        self.qc_before = time_before
        self.qc_after = time_after
        self.qc_fieldsbyname = {}
        self.qc_bucketizers = {}
        self.qc_synthetic = []

        if time_field:
            self.qc_synthetic.append({
                'name': time_field,
                'field': time_field,
                'date': '',
            })

        for fieldconf in self.qc_breakdowns:
            self.qc_fieldsbyname[fieldconf['name']] = fieldconf
            if 'date' in fieldconf:
                self.qc_synthetic.append(fieldconf)
            if 'aggr' not in fieldconf:
                continue
            if fieldconf['aggr'] == 'quantize':
                self.qc_bucketizers[fieldconf['name']] = P2Bucketizer()
            else:
                assert fieldconf['aggr'] == 'lquantize'
                self.qc_bucketizers[fieldconf['name']] = \
                    LinearBucketizer(fieldconf['step'])

        if self.qc_before is not None:
            assert self.qc_after is not None
        else:
            assert self.qc_after is None


def query_load(query, allow_reserved=False):
    """Normalize/validate a query; returns QueryConfig or DNError.

    (reference: lib/dragnet.js:103-144)
    """
    filt = query.get('filter')
    if filt is not None:
        try:
            mod_krill.create(filt)
        except DNError as ex:
            return DNError('invalid query: invalid filter', cause=ex)
    else:
        filt = None

    breakdowns = parse_fields(query.get('breakdowns', []),
                              allow_reserved=allow_reserved)
    if isinstance(breakdowns, DNError):
        return DNError('invalid query', cause=breakdowns)

    timebounds = parse_time_bounds(query.get('timeAfter'),
                                   query.get('timeBefore'))
    if isinstance(timebounds, DNError):
        return timebounds

    return QueryConfig(filter=filt, breakdowns=breakdowns,
                       time_after=timebounds[0], time_before=timebounds[1],
                       time_field=query.get('timeField'))


def parse_time_bounds(time_after, time_before):
    """Validate before/after; both-or-neither.  Values are epoch-ms ints or
    date strings.  Returns (after_ms, before_ms) or DNError.
    (reference: lib/dragnet.js:151-186)
    """
    if time_after is not None:
        if time_before is None:
            return DNError('"after" requires specifying "before" too')
        after_ms = _to_ms(time_after)
        if after_ms is None:
            return DNError('"after": not a valid date: "%s"'
                           % jsv.to_string(time_after))
        before_ms = _to_ms(time_before)
        if before_ms is None:
            return DNError('"before": not a valid date: "%s"'
                           % jsv.to_string(time_before))
        if after_ms > before_ms:
            return DNError('"after" timestamp may not come after "before"')
        return (after_ms, before_ms)
    elif time_before is not None:
        return DNError('"before" requires specifying "after" too')
    return (None, None)


def _to_ms(v):
    if isinstance(v, int) and not isinstance(v, bool):
        return v
    if isinstance(v, str):
        return jsv.date_parse(v)
    return None


def parse_fields(inputs, allow_reserved=False):
    fields = []
    for i, b in enumerate(inputs):
        ret = parse_field(b, allow_reserved=allow_reserved)
        if isinstance(ret, DNError):
            return DNError('field %d ("[object Object]") is invalid' % i,
                           cause=ret)
        fields.append(ret)
    return fields


def parse_field(b, allow_reserved=False):
    """(reference: lib/dragnet.js:210-244, incl. the "lquzntize" typo)"""
    b = dict(b)
    if 'aggr' in b:
        if b['aggr'] not in ('quantize', 'lquantize'):
            return DNError('unsupported aggr: "%s"' % b['aggr'])
        if b['aggr'] == 'lquantize':
            if 'step' not in b:
                return DNError('aggr "lquantize" requires "step"')
            step = _parse_int(b['step'])
            if step is None:
                return DNError('aggr "lquzntize": invalid value for '
                               '"step": "%s"' % jsv.to_string(b['step']))
            b['step'] = step

    if not allow_reserved and b['name'].startswith('__dn'):
        return DNError('field names starting with "__dn" are reserved')

    if 'field' not in b:
        b['field'] = b['name']

    return b


def _parse_int(v):
    """JS parseInt(v, 10): leading-prefix integer parse."""
    if isinstance(v, bool):
        return None
    if isinstance(v, int):
        return v
    if isinstance(v, float):
        return int(v)
    s = str(v).strip()
    i = 0
    if i < len(s) and s[i] in '+-':
        i += 1
    j = i
    while j < len(s) and s[j].isdigit():
        j += 1
    if j == i:
        return None
    return int(s[:j])


def has_date_field(columns):
    return any('date' in c for c in columns)


# ---------------------------------------------------------------------------
# Metric model (reference: lib/dragnet-impl.js:243-323)
# ---------------------------------------------------------------------------

class Metric(object):
    def __init__(self, name, datasource, filter, breakdowns):
        self.m_name = name
        self.m_datasource = datasource
        self.m_filter = filter
        # each breakdown: dict with b_name, b_field, and optional b_date,
        # b_aggr, b_step
        self.m_breakdowns = breakdowns


def metric_serialize(metric, skip_datasource=False):
    rv = {}
    rv['name'] = metric.m_name
    if not skip_datasource:
        rv['datasource'] = metric.m_datasource
    rv['filter'] = metric.m_filter
    bds = []
    for b in metric.m_breakdowns:
        brv = {}
        brv['name'] = b['b_name']
        brv['field'] = b['b_field']
        if 'b_date' in b:
            brv['date'] = b['b_date']
        if 'b_aggr' in b:
            brv['aggr'] = b['b_aggr']
        if 'b_step' in b:
            brv['step'] = b['b_step']
        bds.append(brv)
    rv['breakdowns'] = bds
    return rv


def metric_deserialize(metconfig):
    breakdowns = []
    for b in metconfig['breakdowns']:
        rv = {}
        for k, v in b.items():
            rv['b_' + k] = v
        breakdowns.append(rv)
    return Metric(metconfig['name'], metconfig.get('datasource'),
                  metconfig.get('filter'), breakdowns)


def metric_query(metric, after, before, interval, timefield):
    """Build the QueryConfig describing a metric for index construction;
    for hour/day intervals a reserved __dn_ts lquantize breakdown is
    prepended so aggregates can be demultiplexed into per-interval index
    shards.  (reference: lib/dragnet-impl.js:290-323)
    """
    queryconfig = metric_serialize(metric)
    if interval != 'all':
        step = 3600 if interval == 'hour' else 3600 * 24
        queryconfig['breakdowns'].insert(0, {
            'name': '__dn_ts',
            'aggr': 'lquantize',
            'step': step,
            'field': timefield,
            'date': '',
        })
    q = {
        'breakdowns': queryconfig['breakdowns'],
        'filter': queryconfig['filter'],
    }
    if after is not None:
        q['timeAfter'] = after
    if before is not None:
        q['timeBefore'] = before
    query = query_load(q, allow_reserved=True)
    assert not isinstance(query, DNError), query
    return query


def query_time_bounds_filter(query, timefield):
    """krill filter enforcing the query's [after, before) bounds in seconds.
    (reference: lib/dragnet-impl.js:94-125)
    """
    if query.qc_before is not None:
        assert query.qc_after is not None
        return {'and': [
            {'ge': [timefield, _ceil_div(query.qc_after, 1000)]},
            {'lt': [timefield, _ceil_div(query.qc_before, 1000)]},
        ]}
    return None


def _ceil_div(ms, div):
    return -((-ms) // div)


def filter_and(*filters):
    """AND-combine krill filters, ignoring Nones.
    (reference: lib/dragnet-impl.js:332-343)
    """
    fs = [f for f in filters if f is not None]
    if len(fs) == 0:
        return None
    if len(fs) == 1:
        return fs[0]
    return {'and': fs}
