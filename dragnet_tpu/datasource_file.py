"""File-backend execution engine: scan, build, query, index-scan,
index-read.

Re-implements lib/datasource-file.js on the host side: input enumeration
(strftime-pruned when the datasource has a time format), concatenated line
parsing, the per-metric scan fan-out for index builds (one pass over raw
data feeds every metric's aggregator), the hour/day index multiplexer keyed
on __dn_ts, and the per-index-file query fan-in.

The aggregation hot path is delegated to engine.py (vectorized/JAX) when
the query shape allows, with scan.py as the exact-semantics fallback.
"""

import os
import sys

import numpy as np

from .errors import DNError
from . import jsvalues as jsv
from . import log as mod_log
from . import query as mod_query
from . import ingest as mod_ingest
from . import find as mod_find
from .aggr import Aggregator
from .scan import StreamScan
from .vpipe import Pipeline

LOG = mod_log.get('datasource-file')


def create_datasource(dsconfig):
    assert dsconfig['ds_backend'] == 'file'
    if not isinstance(dsconfig['ds_backend_config'].get('path'), str):
        return DNError('expected datasource "path" to be a string')
    return DatasourceFile(dsconfig)


class ScanResult(object):
    def __init__(self, pipeline, points=None, dry_run_files=None,
                 query=None):
        self.pipeline = pipeline
        self.points = points
        self.dry_run_files = dry_run_files
        self.dry_run_plan = None    # cluster backend: execution plan
        self.parse_plan = None      # scan dry run: DN_PARSE lane info
        self.query = query

    def clone_for_output(self):
        """An output-formatting view of this result with a PRIVATE
        pipeline (stage names/counters copied, points shared
        read-only).  The CLI output layer mutates the pipeline it
        formats — it appends a Flattener stage and bumps counters — so
        `dn serve` requests coalesced onto one shared execution must
        each format through their own clone, or the second --counters
        dump would show the first request's stages doubled."""
        pl = Pipeline()
        pl.warn_func = None
        for s in self.pipeline.stages:
            stage = pl.stage(s.name)
            stage.counters = dict(s.counters)
            stage.hidden = set(s.hidden)
        rv = ScanResult(pl, points=self.points,
                        dry_run_files=self.dry_run_files,
                        query=self.query)
        rv.dry_run_plan = self.dry_run_plan
        rv.parse_plan = self.parse_plan
        return rv


class DatasourceFile(object):
    def __init__(self, dsconfig):
        bc = dsconfig['ds_backend_config']
        self.ds_format = dsconfig.get('ds_format')
        self.ds_timeformat = bc.get('timeFormat')
        self.ds_timefield = bc.get('timeField')
        self.ds_datapath = bc['path']
        self.ds_indexpath = bc.get('indexPath')
        self.ds_filter = dsconfig.get('ds_filter')

    def close(self):
        pass

    def _vector_scan_cls(self):
        from .device_scan import scan_class
        return scan_class()

    # -- input enumeration ------------------------------------------------

    def _find(self, root, timeformat, start_ms, end_ms, pipeline):
        """Returns list of (path, stat) or DNError."""
        if end_ms is None:
            return mod_find.find_walk([root], pipeline)
        assert start_ms is not None
        pathenum = mod_find.create_path_enumerator(
            os.path.join(root, timeformat), start_ms, end_ms)
        if isinstance(pathenum, DNError):
            return pathenum
        roots = pathenum.paths()
        return mod_find.find_walk(roots, pipeline, pathenum=pathenum)

    def _scan_init(self, time_after, time_before, pipeline):
        """Common setup for scan and build: format check, file list.
        Returns (files, fmt) or DNError.  (Record-level filtering happens
        downstream in StreamScan / FilterStage.)"""
        if self.ds_timefield is None and \
                (time_before is not None or time_after is not None):
            return DNError('datasource is missing "timefield" for '
                           '"before" and "after" constraints')

        fmt = mod_ingest.parser_for(self.ds_format)
        if isinstance(fmt, DNError):
            return fmt

        if self.ds_timeformat is not None:
            files = self._find(self.ds_datapath, self.ds_timeformat,
                               time_after, time_before, pipeline)
        else:
            if time_before is not None or time_after is not None:
                sys.stderr.write('warn: datasource is missing '
                                 '"timeformat" for "before" and "after" '
                                 'constraints\n')
            files = self._find(self.ds_datapath, None, None, None, pipeline)
        if isinstance(files, DNError):
            return files
        return (files, fmt)

    # -- scan -------------------------------------------------------------

    def scan(self, query, dry_run=False, warn_func=None):
        """Scan raw data to execute a query.  Returns a ScanResult whose
        points are the aggregated output.  (reference:
        lib/datasource-file.js:72-108)"""
        pipeline = Pipeline()
        pipeline.warn_func = warn_func
        ctx = self._scan_init(query.qc_after, query.qc_before, pipeline)
        if isinstance(ctx, DNError):
            raise ctx
        files, fmt = ctx

        from . import byteparse as mod_byteparse

        if dry_run:
            result = ScanResult(pipeline,
                                dry_run_files=[p for p, st in files])
            from . import native as mod_native
            lane = mod_byteparse.choose_lane(
                [query], self.ds_timefield, self.ds_filter, fmt,
                mod_native.get_lib() is not None)
            result.parse_plan = {'parse_lane': lane.lane,
                                 'parse_mode':
                                     mod_byteparse.parse_mode(),
                                 'reason': lane.reason}
            return result

        LOG.debug('scan start', datapath=self.ds_datapath,
                  nfiles=len(files),
                  nbytes=sum(getattr(st, 'st_size', 0) or 0
                             for p, st in files))

        # The vectorized engine produces identical results; --warnings
        # needs the per-record host path for ordered warning output.
        # Within the vectorized path, ingest runs one of the DN_PARSE
        # lanes: the native C++ parser (host), the vectorized byte
        # parser (vector/device — byteparse.py), or the Python record
        # path when neither engages.
        from .engine import engine_mode
        use_vector = warn_func is None and engine_mode() != 'host'
        native_lib = None
        lane = None
        if use_vector:
            from . import native as mod_native
            native_lib = mod_native.get_lib()
            lane = mod_byteparse.choose_lane(
                [query], self.ds_timefield, self.ds_filter, fmt,
                native_lib is not None)

        if use_vector and (native_lib is not None or lane.engaged):
            scanner = self._scan_native(query, files, fmt, pipeline,
                                        lane)
        elif use_vector:
            from .engine import BATCH_SIZE
            stages = mod_ingest.make_parser_stages(pipeline, fmt)
            # no native library AND the byte lane could not engage:
            # the ineligibility counter must still appear
            mod_byteparse.note_ineligible(stages[0], lane)
            scanner = self._vector_scan_cls()(
                query, self.ds_timefield, pipeline,
                ds_filter=self.ds_filter)
            records = mod_ingest.iter_records(
                mod_ingest.iter_lines([p for p, st in files]), fmt,
                stages=stages)
            buf_r, buf_w = [], []
            for fields, value in records:
                buf_r.append(fields)
                buf_w.append(value)
                if len(buf_r) >= BATCH_SIZE:
                    scanner.write_batch(buf_r, buf_w)
                    buf_r, buf_w = [], []
            scanner.write_batch(buf_r, buf_w)
        else:
            from .engine import weights_array
            stages = mod_ingest.make_parser_stages(pipeline, fmt)
            scanner = StreamScan(query, self.ds_timefield, pipeline,
                                 ds_filter=self.ds_filter)
            records = mod_ingest.iter_records(
                mod_ingest.iter_lines([p for p, st in files]), fmt,
                stages=stages)
            for fields, value in records:
                # weight coercion identical to the vectorized paths
                # (json-skinner values may be strings/garbage)
                if not isinstance(value, int):
                    value = float(weights_array([value])[0])
                    value = int(value) if value.is_integer() else value
                scanner.write(fields, value)

        if hasattr(scanner, 'finish'):
            scanner.finish()   # merge any device-buffered batches
        points = scanner.aggr.points()
        LOG.debug('scan done', npoints=len(points),
                  engine=type(scanner).__name__)
        return ScanResult(pipeline, points=points, query=query)

    def _make_parser(self, lane, paths, hints, dicts, parser_stage):
        """Instantiate the selected ingest parser: the byte lane
        (byteparse.ByteParser, numpy or jax structural kernel) when it
        engaged, the native C++ parser otherwise.  A requested-but-
        ineligible byte lane is recorded as a hidden counter."""
        from . import byteparse as mod_byteparse
        if lane is not None:
            mod_byteparse.note_ineligible(parser_stage, lane)
            if lane.engaged:
                return mod_byteparse.ByteParser(
                    paths, hints, dicts,
                    device=(lane.lane == 'device'))
        from . import native as mod_native
        return mod_native.NativeParser(paths, hints, dicts)

    def _scan_native(self, query, files, fmt, pipeline, lane=None):
        """Scan via a columnar parser — the C++ one (host lane) or the
        vectorized byte parser (DN_PARSE=vector|device): one pass over
        the concatenated bytes, projected fields only, batched into
        the vectorized engine.  (The byte stream is the concatenation
        of all files — a partial trailing line joins across file
        boundaries, matching catstreams semantics.)  With
        DN_SCAN_THREADS > 0 the engine step runs on worker threads
        pipelined behind the parse (scan_mt), with byte-identical
        results."""
        from .engine import BATCH_SIZE, NativeColumns, VectorScan
        from . import scan_mt

        stages = mod_ingest.make_parser_stages(pipeline, fmt)
        parser_stage, adapter_stage = stages
        stage_offset = len(pipeline.stages)
        scan_cls = self._vector_scan_cls()
        scanner = scan_cls(
            query, self.ds_timefield, pipeline, ds_filter=self.ds_filter)

        skinner = fmt == 'json-skinner'
        proj = scanner.projection()
        if skinner:
            paths = ['fields.' + p for p, h, d in proj] + ['value']
            hints = [h for p, h, d in proj] + [False]
            dicts = [d for p, h, d in proj] + [True]
        else:
            paths = [p for p, h, d in proj]
            hints = [h for p, h, d in proj]
            dicts = [d for p, h, d in proj]
        parser = self._make_parser(lane, paths, hints, dicts,
                                   parser_stage)
        remap = {p: np_ for p, np_ in
                 zip([p for p, h, d in proj], paths)} if skinner \
            else None

        nworkers = scan_mt.scan_threads()
        use_mt = nworkers > 0 and scan_cls is VectorScan
        # auto-device mode runs the MT host engine too: workers are
        # plain VectorScans, and the device path (the main scanner) can
        # TAKE OVER the stream mid-flight once its background backend
        # probe succeeds and enough work remains — or hand back if it
        # loses its probation window.  (Round 3 pinned auto to the
        # single-threaded path, so auto regressed vs DN_ENGINE=host on
        # multicore hosts before the device ever helped.)
        auto_mt = nworkers > 0 and \
            getattr(scan_cls, 'AUTO_STREAM', False)
        progress_fn = getattr(scanner, 'set_progress', None)

        if use_mt or auto_mt:
            def build_worker(wp):
                wscan = VectorScan(query, self.ds_timefield, wp,
                                   ds_filter=self.ds_filter)
                # workers drain per batch through the recorder; the
                # deferred columnar merge would hold rows past drain
                wscan._defer_enabled = False
                rec = scan_mt.BatchRecorder(wscan.aggr.stage)
                wscan.aggr = rec

                def process(snap):
                    src = _RemappedParser(snap, remap) if skinner \
                        else snap
                    provider = NativeColumns(src)
                    wscan._process(provider,
                                   _batch_weights(skinner, snap,
                                                  snap.batch_size()))
                    return rec.drain()
                return process

            def new_executor():
                # one radix merge per executor epoch: finalize() runs
                # inside finish(), so a device takeover (or the final
                # drain) always observes this executor's batches fully
                # merged, in order
                radix = scan_mt.RadixMerge(scanner)
                return scan_mt.MTScanExecutor(nworkers, build_worker,
                                              radix.apply_calls,
                                              pipeline, stage_offset,
                                              finish_fn=radix.finalize)

            def device_batch(src, n):
                nlines, nbad = parser.counters()
                _bump_parse_counters(parser_stage, adapter_stage,
                                     nlines, nbad, n)
                weights = _batch_weights(skinner, parser, n)
                scanner.write_native_batch(src, weights)
                parser.reset_batch()
                if scanner._disabled:
                    scanner._flush()
                    return False     # hand back to the MT executor
                return True

            def submit_batch(ex, n):
                snap = scan_mt.ParserSnapshot(parser, paths, hints,
                                              dicts)
                parser.reset_batch()
                _bump_parse_counters(parser_stage, adapter_stage,
                                     snap.nlines, snap.nbad, n)
                if auto_mt:
                    scanner.note_external_batch(n)
                    scanner.shadow_feed(snap, n)
                ex.submit(snap)

            if auto_mt:
                from .device_scan import DeviceScan
                from .vpipe import Pipeline as _Pipeline
                scanner.enable_shadow(
                    lambda: [DeviceScan(query, self.ds_timefield,
                                        _Pipeline(),
                                        ds_filter=self.ds_filter)],
                    lambda snap: NativeColumns(
                        _RemappedParser(snap, remap) if skinner
                        else snap),
                    lambda snap, n: _batch_weights(skinner, snap, n))

            self._takeover_stream(
                files, parser, BATCH_SIZE, progress_fn, new_executor,
                submit_batch,
                scanner.take_over_now if auto_mt else None,
                lambda: _RemappedParser(parser, remap) if skinner
                else parser,
                device_batch)
        else:
            # one provider for the whole scan so per-column caches
            # (decoded array values etc.) persist across batches
            src = _RemappedParser(parser, remap) if skinner else parser

            def flush():
                n = parser.batch_size()
                if n == 0:
                    return
                nlines, nbad = parser.counters()
                _bump_parse_counters(parser_stage, adapter_stage,
                                     nlines, nbad, n)
                weights = _batch_weights(skinner, parser, n)
                scanner.write_native_batch(src, weights)
                parser.reset_batch()

            self._stream_native(files, parser, flush, BATCH_SIZE,
                                progress=progress_fn)
        # counters even when the final batch was empty
        nlines, nbad = parser.counters()
        if nlines:
            parser_stage.counters['ninputs'] = nlines
            parser_stage.counters['noutputs'] = nlines - nbad
            if nbad:
                parser_stage.counters['invalid json'] = nbad
        from . import byteparse as mod_byteparse
        mod_byteparse.publish_counters(parser_stage, parser)
        return scanner

    # -- build / index-scan -----------------------------------------------

    def check_time_args(self, time_after, time_before):
        if time_after is not None and time_before is None:
            return DNError('cannot specify --after without --before')
        if time_before is not None and time_after is None:
            return DNError('cannot specify --before without --after')
        return None

    def check_index_args(self, interval, needsindex, needstime):
        if needsindex and self.ds_indexpath is None:
            return DNError('datasource is missing "indexpath"')
        if needstime and interval != 'all' and self.ds_timefield is None:
            return DNError('datasource is missing "timefield"')
        return None

    def build(self, metrics, interval, time_after=None, time_before=None,
              dry_run=False, warn_func=None):
        from . import resources as mod_resources
        # a full disk / exhausted fd table mid-build (real, or armed
        # enospc/emfile at the sink/journal seams) surfaces as the
        # clean retryable disk_full DNError, never a traceback — the
        # two-phase journal already guarantees the tree is left
        # pre-build or post-build, never torn
        with mod_resources.translate_pressure_errors('index build'):
            return self._index_scan_impl(
                metrics, interval, self.ds_filter, time_after,
                time_before, dry_run, sink='index',
                warn_func=warn_func)

    def index_scan(self, metrics, interval, filter=None, time_after=None,
                   time_before=None, warn_func=None):
        return self._index_scan_impl(
            metrics, interval, filter, time_after, time_before, False,
            sink='points', warn_func=warn_func)

    def _index_scan_impl(self, metrics, interval, filter, time_after,
                         time_before, dry_run, sink, warn_func=None):
        """One pass over raw data feeding every metric's scan; output goes
        to index files (build) or tagged points (index-scan).
        (reference: lib/datasource-file.js:322-433)"""
        pipeline = Pipeline()
        pipeline.warn_func = warn_func
        error = self.check_time_args(time_after, time_before)
        if error is None:
            error = self.check_index_args(interval, sink == 'index', True)
        if error is not None:
            raise error

        ctx = self._scan_init(time_after, time_before, pipeline)
        if isinstance(ctx, DNError):
            raise ctx
        files, fmt = ctx

        if dry_run:
            return ScanResult(pipeline,
                              dry_run_files=[p for p, st in files])

        LOG.debug('%s start' % ('build' if sink == 'index'
                                else 'index-scan'),
                  datapath=self.ds_datapath, nfiles=len(files),
                  nmetrics=len(metrics), interval=interval)

        queries = [mod_query.metric_query(m, time_after, time_before,
                                          interval, self.ds_timefield)
                   for m in metrics]

        # --warnings needs the per-record host path for ordered
        # warning output (same rule as scan())
        from .engine import engine_mode
        use_vector = warn_func is None \
            and os.environ.get('DN_BUILD_ENGINE', 'auto') != 'host' \
            and engine_mode() != 'host'
        native_lib = None
        lane = None
        if use_vector:
            from . import native as mod_native
            from . import byteparse as mod_byteparse
            native_lib = mod_native.get_lib()
            lane = mod_byteparse.choose_lane(
                queries, self.ds_timefield, filter, fmt,
                native_lib is not None)

        if native_lib is not None or (lane is not None and
                                      lane.engaged):
            scanners = self._index_scan_native(
                queries, files, fmt, filter, pipeline, lane)
        else:
            stages = mod_ingest.make_parser_stages(pipeline, fmt)
            if lane is not None:
                # no native library AND the byte lane could not
                # engage: the ineligibility counter must still appear
                from . import byteparse as mod_byteparse
                mod_byteparse.note_ineligible(stages[0], lane)

            # The datasource filter is applied once on the shared parse
            # stream; each metric's own filter lives in its StreamScan
            # (reference: lib/datasource-file.js:124-192 vs :403-427).
            ds_filter_stage = None
            if filter is not None:
                from . import krill as mod_krill
                from .scan import FilterStage
                ds_filter_stage = FilterStage(
                    mod_krill.create(filter),
                    pipeline.stage('Datasource filter'))

            scanners = []
            for qi, q in enumerate(queries):
                s = StreamScan(q, self.ds_timefield, pipeline,
                               ds_filter=None)
                pipeline.stage('Add __dn_metric')
                scanners.append(s)

            lines = mod_ingest.iter_lines([p for p, st in files])
            for fields, value in mod_ingest.iter_records(lines, fmt,
                                                         stages=stages):
                if ds_filter_stage is not None and \
                        not ds_filter_stage.accept(fields):
                    continue
                for s in scanners:
                    s.write(fields, value)

        if sink == 'index':
            # columnar hand-off: each metric's aggregate goes to the
            # index writer as parallel key columns + weights
            # (Aggregator.point_rows) — no per-point field dicts, no
            # __dn_metric tagging pass (index_build_mt routes blocks
            # by position)
            from . import index_build_mt as mod_ibmt
            blocks = []
            for s in scanners:
                if hasattr(s, 'finish'):
                    s.finish()   # merge any device-buffered batches
                cols, weights = s.aggr.point_rows()
                blocks.append((list(s.aggr.decomps), cols, weights))
            mod_ibmt.write_index_blocks(metrics, interval,
                                        self.ds_indexpath, blocks)
            return ScanResult(pipeline, points=None)

        tagged = []
        for qi, s in enumerate(scanners):
            if hasattr(s, 'finish'):
                s.finish()   # merge any device-buffered batches
            for fields, value in s.aggr.points():
                fields['__dn_metric'] = qi
                tagged.append((fields, value))
        return ScanResult(pipeline, points=tagged)

    def _index_scan_native(self, queries, files, fmt, filter, pipeline,
                           lane=None):
        """Build fan-out over a columnar parser (native C++ or the
        DN_PARSE byte lane): ONE pass over raw bytes feeds every
        metric's vectorized scan (the reference pipes one parse stream
        into N StreamScans, lib/datasource-file.js:403-427; here one
        columnar provider feeds N engine passes, parallelized across
        worker threads when DN_SCAN_THREADS > 0)."""
        from .engine import (BATCH_SIZE, NativeColumns, VectorPredicate,
                             VectorScan)
        from . import scan_mt
        from .ops.kernels import TRUE

        stages = mod_ingest.make_parser_stages(pipeline, fmt)
        parser_stage, adapter_stage = stages
        stage_offset = len(pipeline.stages)
        scan_cls = self._vector_scan_cls()

        class _Holder(object):
            def __init__(self):
                self.raw_columns = {}
                self.filter_fields = []

        def make_scan_set(pl, cls):
            """The per-pipeline scan state: datasource predicate (+its
            stage) and one scan per metric; identical stage layout on
            the main and every worker pipeline."""
            pred = stage = None
            if filter is not None:
                holder = _Holder()
                pred = VectorPredicate(filter, holder)
                stage = pl.stage('Datasource filter')
            scans = []
            for q in queries:
                s = cls(q, self.ds_timefield, pl, ds_filter=None)
                pl.stage('Add __dn_metric')
                scans.append(s)
            return pred, stage, scans, holder if filter is not None \
                else None

        def make_scan_set_host(pl):
            return make_scan_set(pl, VectorScan)

        ds_pred, ds_stage, scanners, holder = make_scan_set(pipeline,
                                                            scan_cls)

        skinner = fmt == 'json-skinner'
        proj = {}
        if filter is not None:
            for f in holder.filter_fields:
                proj.setdefault(f, [False, True])
        for s in scanners:
            for p, h, d in s.projection():
                ent = proj.setdefault(p, [False, False])
                ent[0] = ent[0] or h
                ent[1] = ent[1] or d

        items = list(proj.items())
        if skinner:
            paths = ['fields.' + p for p, hd in items] + ['value']
            hints = [hd[0] for p, hd in items] + [False]
            dicts = [hd[1] for p, hd in items] + [True]
        else:
            paths = [p for p, hd in items]
            hints = [hd[0] for p, hd in items]
            dicts = [hd[1] for p, hd in items]
        parser = self._make_parser(lane, paths, hints, dicts,
                                   parser_stage)
        remap = {p: np_ for (p, hd), np_ in zip(items, paths)} \
            if skinner else None

        def eval_ds_filter(pred, stage, provider, n):
            stage.bump('ninputs', n)
            out = pred.outcomes(provider)
            nfail = int((out == 2).sum())
            ndrop = int((out == 0).sum())
            if nfail:
                stage.bump('nfailedeval', nfail)
            if ndrop:
                stage.bump('nfilteredout', ndrop)
            alive0 = out == TRUE
            stage.bump('noutputs', int(alive0.sum()))
            return alive0

        # stacked multi-metric device program: all metrics fold in ONE
        # dispatch per batch with shared columns uploaded once (SURVEY
        # §7.7); None when the scanners don't support it (host engine,
        # mesh subclass, single metric) — then the per-scan loop runs
        from . import device_scan as mod_device_scan
        stack = mod_device_scan.make_stack(scanners) \
            if scan_cls is not VectorScan else None

        nworkers = scan_mt.scan_threads()
        use_mt = nworkers > 0 and scan_cls is VectorScan
        # auto-device builds mirror the scan path: MT host workers by
        # default, with a coordinated device takeover (and hand-back on
        # lost probation) across all metric scanners
        auto_mt = nworkers > 0 and \
            getattr(scan_cls, 'AUTO_STREAM', False)

        def set_all_progress(done, total):
            for s in scanners:
                if hasattr(s, 'set_progress'):
                    s.set_progress(done, total)
        progress_fn = set_all_progress \
            if any(hasattr(s, 'set_progress') for s in scanners) else None

        if use_mt or auto_mt:
            def build_worker(wp):
                wpred, wstage, wscans, _ = make_scan_set_host(wp)
                recs = []
                for s in wscans:
                    s._defer_enabled = False   # drained per batch
                    rec = scan_mt.BatchRecorder(s.aggr.stage)
                    s.aggr = rec
                    recs.append(rec)

                def process(snap):
                    n = snap.batch_size()
                    src = _RemappedParser(snap, remap) if skinner \
                        else snap
                    provider = NativeColumns(src)
                    weights = _batch_weights(skinner, snap, n)
                    alive0 = None
                    if wpred is not None:
                        alive0 = eval_ds_filter(wpred, wstage,
                                                provider, n)
                    out = []
                    for s, rec in zip(wscans, recs):
                        s._process(provider, weights, alive=alive0)
                        out.append(rec.drain())
                    return out
                return process

            def new_executor():
                # one radix merge per metric scanner per executor epoch
                radixes = [scan_mt.RadixMerge(s) for s in scanners]

                def apply_result(results):
                    for radix, calls in zip(radixes, results):
                        radix.apply_calls(calls)

                def finish_fn():
                    for radix in radixes:
                        radix.finalize()
                return scan_mt.MTScanExecutor(nworkers, build_worker,
                                              apply_result, pipeline,
                                              stage_offset,
                                              finish_fn=finish_fn)

            def take_over():
                if not scanners[0].take_over_now():
                    return False
                # share the probe result so sibling scanners don't
                # each wait on their own probe thread
                for s in scanners[1:]:
                    s._backend_ok = scanners[0]._backend_ok
                return True

            def device_batch(src, n):
                nlines, nbad = parser.counters()
                _bump_parse_counters(parser_stage, adapter_stage,
                                     nlines, nbad, n)
                provider = NativeColumns(src)
                weights = _batch_weights(skinner, parser, n)
                alive0 = None
                if ds_pred is not None:
                    alive0 = eval_ds_filter(ds_pred, ds_stage,
                                            provider, n)
                if stack is not None:
                    stack.process(provider, weights, alive0)
                else:
                    for s in scanners:
                        s._process(provider, weights, alive=alive0)
                parser.reset_batch()
                if any(s._disabled for s in scanners):
                    # coordinated hand-back: all metric scanners leave
                    # the device together
                    for s in scanners:
                        s._flush()
                        s._disabled = True
                    return False
                return True

            def submit_batch(ex, n):
                snap = scan_mt.ParserSnapshot(parser, paths, hints,
                                              dicts)
                parser.reset_batch()
                _bump_parse_counters(parser_stage, adapter_stage,
                                     snap.nlines, snap.nbad, n)
                if auto_mt:
                    for s in scanners:
                        s.note_external_batch(n)
                    scanners[0].shadow_feed(snap, n)
                ex.submit(snap)

            if auto_mt:
                from .device_scan import DeviceScan
                from .vpipe import Pipeline as _Pipeline
                # the audition replays every metric's scan, so the
                # measured rate reflects the whole build fan-out
                scanners[0].enable_shadow(
                    lambda: [DeviceScan(q, self.ds_timefield,
                                        _Pipeline(), ds_filter=None)
                             for q in queries],
                    lambda snap: NativeColumns(
                        _RemappedParser(snap, remap) if skinner
                        else snap),
                    lambda snap, n: _batch_weights(skinner, snap, n),
                    # production passes the shared ds-filter mask as a
                    # non-None alive; the replay must match that shape
                    # or the staged profile misses the program cache
                    make_alive=(
                        (lambda n: np.ones(n, dtype=bool))
                        if filter is not None else None))

            self._takeover_stream(
                files, parser, BATCH_SIZE, progress_fn, new_executor,
                submit_batch,
                take_over if auto_mt else None,
                lambda: _RemappedParser(parser, remap) if skinner
                else parser,
                device_batch)
        else:
            # one provider object per build so per-column caches persist
            src = _RemappedParser(parser, remap) if skinner else parser

            def flush():
                n = parser.batch_size()
                if n == 0:
                    return
                nlines, nbad = parser.counters()
                _bump_parse_counters(parser_stage, adapter_stage,
                                     nlines, nbad, n)
                provider = NativeColumns(src)
                weights = _batch_weights(skinner, parser, n)
                alive0 = None
                if ds_pred is not None:
                    alive0 = eval_ds_filter(ds_pred, ds_stage, provider,
                                            n)
                if stack is not None:
                    stack.process(provider, weights, alive0)
                else:
                    for s in scanners:
                        s._process(provider, weights, alive=alive0)
                parser.reset_batch()

            self._stream_native(files, parser, flush, BATCH_SIZE,
                                progress=progress_fn)
        nlines, nbad = parser.counters()
        if nlines:
            parser_stage.counters['ninputs'] = nlines
            parser_stage.counters['noutputs'] = nlines - nbad
            if nbad:
                parser_stage.counters['invalid json'] = nbad
        from . import byteparse as mod_byteparse
        mod_byteparse.publish_counters(parser_stage, parser)
        return scanners

    def _takeover_stream(self, files, parser, batch_size, progress,
                         new_executor, submit_batch, take_over,
                         make_device_src, device_batch):
        """The MT-host / device takeover state machine shared by scan
        and build: batches go to the MT executor until take_over()
        (auto mode's escalation decision) fires, then to the device
        scanner(s) via device_batch; a False from device_batch (lost
        probation) drains back to a fresh MT executor.  Batch order —
        and therefore the aggregator's insertion order — is preserved
        across both transitions: the executor is fully drained before
        any device batch flushes, and the device accumulator is flushed
        before the next executor starts."""
        state = {'ex': new_executor(), 'src': None}

        def flush():
            n = parser.batch_size()
            if n == 0:
                return
            if state['ex'] is not None and take_over is not None and \
                    take_over():
                state['ex'].finish()
                state['ex'] = None
                state['src'] = make_device_src()
            if state['ex'] is None:
                if not device_batch(state['src'], n):
                    state['src'] = None
                    state['ex'] = new_executor()
                return
            submit_batch(state['ex'], n)

        try:
            self._stream_native(files, parser, flush, batch_size,
                                progress=progress)
        finally:
            if state['ex'] is not None:
                state['ex'].finish()

    def _stream_native(self, files, parser, flush, batch_size,
                       progress=None):
        """Feed the concatenated file bytes to the native parser,
        flushing a batch whenever enough records accumulate (partial
        trailing lines join across file boundaries — catstreams
        semantics).  The bulk of each read chunk is parsed in place
        (zero-copy span); only the carry-spanning line is stitched.

        progress(bytes_done, bytes_total), when given, is called before
        each flush — auto mode's device-switch heuristic estimates
        remaining work from it (total is 0 when sizes are unknowable,
        e.g. character devices)."""
        # larger reads amortize the multithreaded parse's fork/join; the
        # cap bounds how far a batch can overshoot the flush threshold
        # (flush is only checked between reads).  DN_READ_SIZE overrides
        # (testing / IO tuning).
        readsz = min(1 << 24, (1 << 22) * getattr(parser, 'nthreads', 1))
        try:
            readsz = int(os.environ.get('DN_READ_SIZE', 0)) or readsz
        except ValueError:
            pass
        parse_at = getattr(parser, 'parse_at', None)
        total = 0
        for path, st in files:
            sz = getattr(st, 'st_size', 0) if st is not None else 0
            total += sz if sz and sz > 0 else 0
        state = {'done': 0}

        def counted_chunks():
            for chunk in _read_ahead(files, readsz):
                state['done'] += len(chunk)
                yield chunk

        if parse_at is None:
            # byte-lane / plain parsers: complete-line buffers from
            # the shared chunk-boundary joiner (ingest.py — the same
            # carry discipline as iter_lines/iter_stream_lines)
            for lbuf in mod_ingest.iter_line_buffers(counted_chunks()):
                parser.parse(lbuf)
                if parser.batch_size() >= batch_size:
                    if progress is not None:
                        progress(state['done'], total)
                    flush()
            if progress is not None:
                progress(state['done'], total)
            flush()
            return

        carry = b''
        for chunk in counted_chunks():
            nl = chunk.rfind(b'\n')
            if nl == -1:
                carry += chunk
                continue
            start = 0
            if carry:
                first = chunk.index(b'\n', 0, nl + 1)
                parser.parse(carry + chunk[:first + 1])
                start = first + 1
            arr = np.frombuffer(chunk, dtype=np.uint8)
            if nl + 1 > start:
                parse_at(arr[start:].ctypes.data,
                         nl + 1 - start)
            carry = chunk[nl + 1:]
            if parser.batch_size() >= batch_size:
                if progress is not None:
                    progress(state['done'], total)
                flush()
        if carry:
            parser.parse(carry)
        if progress is not None:
            progress(state['done'], total)
        flush()

    def _index_write(self, metrics, interval, tagged_points):
        """Write tagged aggregated points into interval-chunked index
        files via the bulk write path; each file is written atomically
        and failures leave no tmp litter.  (reference:
        lib/datasource-file.js:444-547; the build path itself hands
        columnar blocks straight to index_build_mt.write_index_blocks)"""
        from . import index_build_mt as mod_ibmt
        writer = mod_ibmt.StreamingIndexWriter(metrics, interval,
                                               self.ds_indexpath)
        try:
            writer.write_points(tagged_points)
            writer.finish()
        except BaseException:
            writer.abort()
            raise

    # how many stdin points index_read routes to the sinks at a time:
    # large enough to amortize the bulk write, small enough that peak
    # memory stays flat however long the piped stream is
    INDEX_READ_CHUNK = 4096

    def index_read(self, metrics, interval, instream):
        """Read tagged json-skinner points (from stdin) and write index
        files, streaming in bounded chunks — the old path materialized
        the whole stream (bytes AND point dicts) before writing.
        (reference: lib/datasource-file.js:729-746)"""
        error = self.check_index_args(interval, True, False)
        if error is not None:
            raise error
        pipeline = Pipeline()
        from . import index_build_mt as mod_ibmt
        from . import resources as mod_resources
        writer = mod_ibmt.StreamingIndexWriter(metrics, interval,
                                               self.ds_indexpath)
        with mod_resources.translate_pressure_errors('index-read'):
            try:
                chunk = []
                for rec in mod_ingest.iter_records(
                        mod_ingest.iter_stream_lines(instream),
                        'json-skinner', pipeline):
                    chunk.append(rec)
                    if len(chunk) >= self.INDEX_READ_CHUNK:
                        writer.write_points(chunk)
                        chunk = []
                if chunk:
                    writer.write_points(chunk)
                writer.finish()
            except BaseException:
                writer.abort()
                raise
        return ScanResult(pipeline)

    # -- query ------------------------------------------------------------

    def index_find_params(self, interval, time_after, time_before):
        """(reference: lib/dragnet-impl.js:194-236)"""
        if interval == 'day':
            return (os.path.join(self.ds_indexpath, 'by_day'),
                    '%Y-%m-%d.sqlite', time_after, time_before)
        if interval == 'hour':
            return (os.path.join(self.ds_indexpath, 'by_hour'),
                    '%Y-%m-%d-%H.sqlite', time_after, time_before)
        if interval == 'all':
            return (os.path.join(self.ds_indexpath, 'all'), None, None,
                    None)
        return DNError('unsupported interval: "%s"' % interval)

    def _cached_index_walk(self, root, pipeline):
        """The unbounded index-tree walk, memoized on the directory's
        stat identity (index_query_mt.cached_find_walk) — the cluster
        backend overrides this to partition the cached listing across
        processes, the same way its _find override partitions fresh
        walks."""
        from . import index_query_mt as mod_iqmt
        return mod_iqmt.cached_find_walk(root, pipeline)

    def index_query_paths(self, query, interval, pipeline):
        """Enumerate the shard files an index query over `query` x
        `interval` would read: argument checks, the crash-recovery
        sweep, the (possibly memoized) tree walk, and the
        journal/tmp/quarantine litter filter — everything up to (not
        including) time-range pruning.  Returns (root, timeformat,
        files) with files as (path, statbuf) pairs in find order.
        Shared by query() below and the cluster partial-query
        executor (serve/router.py), so a member's partition-filtered
        shard set is drawn from the IDENTICAL walk a single-process
        query performs."""
        error = self.check_time_args(query.qc_after, query.qc_before)
        if error is None:
            error = self.check_index_args(interval, True, False)
        if error is not None:
            raise error

        params = self.index_find_params(interval or 'all', query.qc_after,
                                        query.qc_before)
        if isinstance(params, DNError):
            raise params
        root, timeformat, after, before = params

        # crash-recovery sweep (TTL-throttled): a builder that died
        # mid-publish must be rolled forward/back before this reader
        # walks the tree (index_journal)
        from . import index_journal as mod_journal
        mod_journal.maybe_sweep(self.ds_indexpath)

        if before is None and pipeline.warn_func is None:
            # unbounded query over a flat index tree: the whole-tree
            # walk (one stat per shard) is memoized on the directory's
            # stat identity — stage counters replay byte-identically
            files = self._cached_index_walk(root, pipeline)
        else:
            files = self._find(root, timeformat, after, before, pipeline)
        if isinstance(files, DNError):
            raise files
        # never open build machinery as a shard: journals, in-flight
        # tmps (a concurrent builder's), and the quarantine directory
        # stay out of the shard set
        files = [(p, st) for p, st in files
                 if not mod_journal.is_index_litter(p)]
        if timeformat is not None:
            # follow --append mini-generations: bounded finds
            # enumerate exact in-window filenames and can never name
            # a `<shard>.sqlite-gNNNNNN`; splice existing generations
            # in after their bases (unbounded walks see them
            # naturally)
            from . import rollup as mod_rollup
            files = mod_rollup.augment_generation_files(root, files)
        return root, timeformat, files

    def query(self, query, interval, dry_run=False):
        """Query the indexes.  (reference:
        lib/datasource-file.js:573-691)"""
        pipeline = Pipeline()
        root, timeformat, files = self.index_query_paths(
            query, interval, pipeline)

        if dry_run:
            return ScanResult(pipeline,
                              dry_run_files=[p for p, st in files])

        index_list = pipeline.stage('Index List')
        aggr = Aggregator(query,
                          stage=pipeline.stage('Index Result Aggregator'))

        # Shard fan-out (index_query_mt): time-range pruning by shard
        # filename, then a DN_IQ_THREADS worker pool over the shard
        # handle cache, merged in find order — byte-identical to the
        # sequential loop (the reference's vasync barrier merged the
        # same way, lib/datasource-file.js:629-689).
        from . import index_query_mt as mod_iqmt
        paths = [p for p, st in files]
        paths, npruned = mod_iqmt.prune_shards(
            paths, timeformat, query.qc_after, query.qc_before)
        # time-bounded finds never enumerate out-of-window shards, so
        # count the tree's skipped files for the pruned counter (the
        # found list can only re-prune what enumeration missed)
        npruned = max(npruned, mod_iqmt.count_pruned_shards(
            root, timeformat, query.qc_after, query.qc_before))
        if npruned:
            index_list.bump_hidden('index shards pruned', npruned)
        index_list.bump_hidden('index shards queried', len(paths))

        # verified reads (integrity.py): a catalogued shard that is
        # MISSING from the walk (quarantined after a corrupt detect,
        # or externally deleted) must degrade explicitly — a clean
        # retryable error naming the shard — never silently short
        # result bytes
        from . import integrity as mod_integrity
        if mod_integrity.verify_mode() != 'off':
            mod_integrity.check_missing(
                self.ds_indexpath, paths,
                subdir=os.path.basename(root)
                if timeformat is not None else None,
                timeformat=timeformat, after_ms=query.qc_after,
                before_ms=query.qc_before)

        nworkers = mod_iqmt.iq_threads()
        LOG.debug('query start', indexroot=root, nindexes=len(paths),
                  npruned=npruned, nworkers=nworkers,
                  interval=interval)

        aggr_stage = aggr.stage

        def merge(items):
            # per-shard aggregates arrive as key items (the
            # Aggregator wire format) in emission order: write_key
            # replays them byte-identically to re-writing the
            # shard's points.  Counter parity with the per-point
            # write() loop: one Index List input/output and one
            # aggregator-stage input per point, bumped in bulk.
            npts = len(items)
            if npts == 0:
                return
            index_list.bump('ninputs', npts)
            index_list.bump('noutputs', npts)
            aggr_stage.bump('ninputs', npts)
            aggr.merge_key_items(items)

        # Query planner (rollup.py): serve from the coarsest covering
        # rollup shards and fold follow mini-generations into their
        # logical base shard.  plan_query returns None whenever the
        # walk is plain per-file shards — the stacked/pooled paths
        # below then run completely untouched.
        from . import rollup as mod_rollup
        plan = mod_rollup.plan_query(self.ds_indexpath,
                                     interval or 'all', paths, query)
        if plan is not None:
            # bump_hidden mirrors into the process-global store, so
            # `dn serve` /stats sees the fleet-wide coverage too
            index_list.bump_hidden('index shards via rollup',
                                   plan['ncovered'])
            index_list.bump_hidden('rollup shards queried',
                                   plan['nrollup'])

            def query_one(path, q):
                if nworkers <= 0:
                    return mod_iqmt.query_shard_once(path, q)
                return mod_iqmt._query_shard_cached(path, q)

            mod_rollup.execute_plan(plan, query, query_one, merge)
            return ScanResult(pipeline, points=aggr.points(),
                              query=query)

        # Stacked cross-shard execution (index_query_stack, default):
        # shard readers only LOAD matching column blocks, and one
        # vectorized filter+group-by over the concatenated batch
        # replaces the per-shard mask -> groupby -> merge loop —
        # byte-identical output (the stacked lexsort reproduces the
        # sequential insertion order exactly).  Falls back to the
        # per-shard loop when the query shape or the exactness gate
        # (non-integer weights) demands it, or under DN_IQ_STACK=0.
        from . import index_query_stack as mod_iqs
        stacked = False
        if mod_iqs.stack_enabled() and mod_iqs.stack_eligible(query):
            stacked = mod_iqs.run_stacked(paths, query, aggr,
                                          index_list)

        if not stacked:
            mod_iqmt.run_shard_queries(paths, query, nworkers, merge)

        return ScanResult(pipeline, points=aggr.points(), query=query)


def _read_ahead(files, readsz):
    """Yield the concatenated chunk stream of `files` with a producer
    thread reading one chunk ahead (so file IO overlaps parse and
    engine work while at most ~2 chunks are resident).  Bytes come
    through ingest.open_byte_source — the pluggable fetcher seam.
    Producer exceptions (unreadable file mid-stream) re-raise at the
    consumer."""
    import queue as mod_queue
    import threading

    q = mod_queue.Queue(maxsize=1)
    stop = threading.Event()

    def put(item):
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except mod_queue.Full:
                continue
        return False

    def produce():
        try:
            for path, st in files:
                for chunk in mod_ingest.open_byte_source(path, readsz):
                    if not put(chunk):
                        return
            put(None)
        except BaseException as e:     # re-raised by the consumer
            put(e)

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is None:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


def _bump_parse_counters(parser_stage, adapter_stage, nlines, nbad, n):
    """Parse-layer counters (totals are monotonic; assigned, not
    accumulated) plus the per-batch adapter bumps."""
    parser_stage.counters['ninputs'] = nlines
    parser_stage.counters['noutputs'] = nlines - nbad
    if nbad:
        parser_stage.counters['invalid json'] = nbad
    if adapter_stage is not None and n:
        adapter_stage.bump('ninputs', n)
        adapter_stage.bump('noutputs', n)


def _batch_weights(skinner, src, n):
    """Per-record weights for one batch: 1 for raw json, the coerced
    point value for json-skinner (src is a parser or snapshot)."""
    if skinner:
        tags, nums, strcodes = src.columns('value')
        return _skinner_weights(tags, nums, strcodes, src)
    return np.ones(n, dtype=np.float64)


def _skinner_weights(tags, nums, strcodes, parser):
    """json-skinner point weights with JS Number coercion (NaN -> 0),
    matching engine.weights_array on the Python ingest path."""
    from . import native as mod_native
    from . import jsvalues as jsv
    weights = np.zeros(len(tags), dtype=np.float64)
    m = (tags == mod_native.TAG_INT) | (tags == mod_native.TAG_NUMBER)
    weights[m] = nums[m]
    weights[tags == mod_native.TAG_TRUE] = 1.0
    ms = tags == mod_native.TAG_STRING
    if ms.any():
        d = parser.dictionary('value')
        table = np.array(
            [0.0 if (f := jsv.to_number(s)) != f else f for s in d],
            dtype=np.float64)
        weights[ms] = table[strcodes[ms]]
    return weights


class _RemappedParser(object):
    """Presents a NativeParser whose projection paths were prefixed
    (json-skinner: fields.*) under the engine's unprefixed names."""

    def __init__(self, parser, remap):
        self.parser = parser
        self.remap = remap
        # alias the wrapped parser's decoded-array cache (if it has
        # one) so per-batch wrappers don't defeat it (the engine
        # caches on the provider's parser attribute)
        cache = getattr(parser, '_array_cache', None)
        if cache is not None:
            self._array_cache = cache

    def batch_size(self):
        return self.parser.batch_size()

    def columns(self, path):
        return self.parser.columns(self.remap[path])

    def date_columns(self, path):
        return self.parser.date_columns(self.remap[path])

    def dictionary(self, path):
        return self.parser.dictionary(self.remap[path])

    # one-pass batch stats (device-path eligibility); absent on
    # snapshot sources — callers feature-test with getattr
    def field_stats(self, path):
        fn = getattr(self.parser, 'field_stats', None)
        return None if fn is None else fn(self.remap[path])

    def nums_i32(self, path):
        return self.parser.nums_i32(self.remap[path])

    def date_stats(self, path):
        fn = getattr(self.parser, 'date_stats', None)
        return None if fn is None else fn(self.remap[path])

    def date_i32(self, path):
        return self.parser.date_i32(self.remap[path])

    def date_err(self, path):
        return self.parser.date_err(self.remap[path])

    def tags_col(self, path):
        return self.parser.tags_col(self.remap[path])

    def strcodes_col(self, path):
        return self.parser.strcodes_col(self.remap[path])


