"""Index writer: aggregated points -> self-describing index file.

Schema-compatible with the reference's SQLite index format
(lib/index-sink.js:116-230): a `dragnet_config` table (version 2.0.0 plus
extra pairs like dn_start), a `dragnet_metrics` catalog (id, label, filter
JSON, params JSON), and one `dragnet_index_<i>` table per metric with
escaped column names ('.'/'-' -> '_'), `integer` columns for aggregated
fields and varchar(128) otherwise, plus a `value` column.

Durability contract preserved: written to a tmp name (`<name>.<pid>`
by default; journaled builds pass a per-build `tmp_suffix`), fsync
disabled (pragma synchronous=off), atomically renamed into place on
flush (lib/index-sink.js:264-304) — a crash never leaves a torn
*committed* index.  A *failed* flush (or abort()) best-effort unlinks
the tmp file, so error paths leave the index directory clean too.

flush() is split into the two-phase primitives the build journal
(index_journal.py) sequences across a whole shard set: prepare()
writes and closes the complete tmp file, commit() atomically renames
it into place.  flush() == prepare()+commit() for single-shard
callers.  A SIGKILL between the phases leaves only a complete tmp
plus the journal, which the recovery sweep rolls forward or back —
a reader can only ever observe the pre-build or post-build tree.

Both storage engines share one error contract (point_metric/point_row):
a bad __dn_metric tag or a missing breakdown raises DNError — the
pre-PR-2 mix of bare asserts (stripped under -O) and IndexError is gone.
Both also share the bulk write_rows(mi, key_columns, values) entry: one
executemany per block here, a direct columnar append in the DNC sink.
"""

import os
import sqlite3

from .errors import DNError
from . import jsvalues as jsv
from . import query as mod_query

INDEX_VERSION = '2.0.0'


def sqlite3_escape(name):
    return name.replace('.', '_').replace('-', '_')


def check_metric_index(mi, nmetrics):
    """Validate a metric index; both storage engines raise the same
    DNError for a missing/mistyped/out-of-range value."""
    if not (isinstance(mi, int) and not isinstance(mi, bool)
            and 0 <= mi < nmetrics):
        raise DNError('bad __dn_metric: %r' % (mi,))
    return mi


def check_block(mi, keycols, names):
    """Shared write_rows validation: metric index + one key column per
    breakdown (`names` is the per-metric breakdown-name table)."""
    check_metric_index(mi, len(names))
    if len(keycols) != len(names[mi]):
        raise DNError('write_rows: expected %d key columns, got %d'
                      % (len(names[mi]), len(keycols)))


def point_metric(fields, nmetrics):
    """The validated __dn_metric tag of a tagged point."""
    return check_metric_index(fields.get('__dn_metric'), nmetrics)


def point_row(fields, names):
    """A point's breakdown values in column order; a missing breakdown
    raises the shared DNError contract."""
    row = []
    for name in names:
        if name not in fields:
            raise DNError('point is missing breakdown "%s"' % name)
        row.append(fields[name])
    return row


def metric_catalog_rows(metrics):
    """(id, label, filter, params) rows of the embedded metric catalog —
    identical strings in both storage engines so metric selection
    behaves the same whichever wrote the file."""
    rows = []
    for i, m in enumerate(metrics):
        ms = mod_query.metric_serialize(m, skip_datasource=True)
        rows.append((i, m.m_name, jsv.json_stringify(m.m_filter),
                     jsv.json_stringify(ms['breakdowns'])))
    return rows


def make_index_sink(metrics, filename, config=None, catalog=None,
                    tmp_suffix=None):
    """Index writer for the configured format: DN_INDEX_FORMAT=dnc (the
    native columnar store, default) or sqlite (reference-compatible
    files).  Readers dispatch on file content, so either is queryable.
    `catalog` is an optional precomputed metric_catalog_rows(metrics) —
    a 365-shard build serializes the identical catalog into every
    shard, so the caller computes it once.  `tmp_suffix` overrides the
    default `<pid>` tmp-name suffix (journaled builds use their build
    id so concurrent builds and the recovery sweep can tell tmps
    apart)."""
    fmt = os.environ.get('DN_INDEX_FORMAT', 'dnc')
    if fmt == 'sqlite':
        return IndexSink(metrics, filename, config=config,
                         catalog=catalog, tmp_suffix=tmp_suffix)
    from .index_dnc import DncIndexSink
    return DncIndexSink(metrics, filename, config=config,
                        catalog=catalog, tmp_suffix=tmp_suffix)


class IndexSink(object):
    def __init__(self, metrics, filename, config=None, catalog=None,
                 tmp_suffix=None):
        from . import faults as mod_faults
        mod_faults.fire('sink.create')
        self.is_metrics = metrics
        self.is_dbfilename = filename
        self.is_dbtmpfilename = filename + '.' + \
            (tmp_suffix or str(os.getpid()))
        self.is_config = dict(config or {})
        self.is_nwritten = 0
        self._prepared = False

        dirname = os.path.dirname(self.is_dbtmpfilename)
        if dirname:
            os.makedirs(dirname, exist_ok=True)

        # check_same_thread=False: the build pool hands a sink to
        # exactly one flush worker (index_build_mt), so a connection
        # created on the streaming thread is later used — never
        # concurrently — on another; serialized access makes it safe.
        self.is_db = sqlite3.connect(self.is_dbtmpfilename,
                                     check_same_thread=False)
        self.is_db.execute('pragma synchronous = off;')

        cur = self.is_db.cursor()
        cur.execute('CREATE TABLE dragnet_config(\n'
                    '    key varchar(128) primary key,\n'
                    '    value varchar(128)\n);')
        cur.execute('CREATE TABLE dragnet_metrics(\n'
                    '    id integer,\n'
                    '    label varchar(64),\n'
                    '    filter varchar(1024),\n'
                    '    params varchar(1024)\n);')

        self._names = []
        self._insert_sql = []
        for i, m in enumerate(metrics):
            tblname = 'dragnet_index_%d' % i
            cols = []
            for b in m.m_breakdowns:
                ctype = 'integer' if 'b_aggr' in b else 'varchar(128)'
                cols.append('    %s %s' % (sqlite3_escape(b['b_name']),
                                           ctype))
            cols.append('    value integer')
            cur.execute('CREATE TABLE %s(\n%s\n);'
                        % (tblname, ',\n'.join(cols)))
            self._names.append([b['b_name'] for b in m.m_breakdowns])
            self._insert_sql.append(
                'INSERT INTO %s VALUES (%s)'
                % (tblname, ', '.join('?' for _ in cols)))

        configpairs = [('version', INDEX_VERSION)]
        for k, v in self.is_config.items():
            assert k != 'version'
            configpairs.append((k, v))
        cur.executemany('INSERT INTO dragnet_config VALUES (?, ?)',
                        configpairs)

        cur.executemany('INSERT INTO dragnet_metrics VALUES (?, ?, ?, ?)',
                        catalog if catalog is not None
                        else metric_catalog_rows(metrics))

    def write(self, fields, value):
        """Write one aggregated point; fields must carry __dn_metric."""
        mi = point_metric(fields, len(self.is_metrics))
        row = point_row(fields, self._names[mi])
        row.append(value)
        self.is_db.execute(self._insert_sql[mi], row)
        self.is_nwritten += 1

    def write_rows(self, mi, keycols, values):
        """Bulk append one metric's block: `keycols` is one column per
        breakdown (in breakdown order), `values` the value column —
        a single executemany, the whole sink committing as one
        transaction at flush."""
        check_block(mi, keycols, self._names)
        self.is_db.executemany(self._insert_sql[mi],
                               zip(*keycols, values))
        self.is_nwritten += len(values)

    def prepare(self):
        """Phase 1: the complete shard body lands in the tmp file and
        the connection closes.  On failure the tmp is discarded."""
        from . import faults as mod_faults
        try:
            # torn kind: the tmp already carries partial body bytes —
            # truncate-and-crash models the mid-write power cut
            mod_faults.fire('sink.flush',
                            torn_path=self.is_dbtmpfilename)
            self.is_db.commit()
            self.is_db.close()
            self._prepared = True
        except BaseException:
            self._discard_tmp()
            raise

    def commit(self, discard_on_error=True):
        """Phase 2: atomically rename the prepared tmp into place.
        (No torn kind here: past the commit record the tmp must stay
        complete so the recovery roll-forward publishes whole bytes —
        kill/error/delay still apply.  The flip kind DOES target the
        tmp: its checksum already landed in the commit record, so a
        flipped byte models post-publish rot the integrity catalog
        must catch.)  Journaled publishers pass
        discard_on_error=False: their commit record makes the tmp
        recoverable state, not litter."""
        from . import faults as mod_faults
        try:
            mod_faults.fire('sink.rename',
                            flip_path=self.is_dbtmpfilename)
            os.rename(self.is_dbtmpfilename, self.is_dbfilename)
        except BaseException:
            if discard_on_error:
                self._discard_tmp()
            raise

    def flush(self):
        if not self._prepared:
            self.prepare()
        self.commit()

    def abort(self):
        """Discard the sink: close the connection and best-effort
        unlink the tmp file (a failed build must not leave
        `<name>.<pid>` litter behind)."""
        try:
            self.is_db.close()
        except Exception:
            pass
        self._discard_tmp()

    def _discard_tmp(self):
        try:
            os.unlink(self.is_dbtmpfilename)
        except OSError:
            pass
