"""Index writer: aggregated points -> self-describing index file.

Schema-compatible with the reference's SQLite index format
(lib/index-sink.js:116-230): a `dragnet_config` table (version 2.0.0 plus
extra pairs like dn_start), a `dragnet_metrics` catalog (id, label, filter
JSON, params JSON), and one `dragnet_index_<i>` table per metric with
escaped column names ('.'/'-' -> '_'), `integer` columns for aggregated
fields and varchar(128) otherwise, plus a `value` column.

Durability contract preserved: written to `<name>.<pid>`, fsync disabled
(pragma synchronous=off), atomically renamed into place on flush
(lib/index-sink.js:264-304) — a crash never leaves a torn index.
"""

import os
import sqlite3

from . import jsvalues as jsv
from . import query as mod_query

INDEX_VERSION = '2.0.0'


def sqlite3_escape(name):
    return name.replace('.', '_').replace('-', '_')


def metric_catalog_rows(metrics):
    """(id, label, filter, params) rows of the embedded metric catalog —
    identical strings in both storage engines so metric selection
    behaves the same whichever wrote the file."""
    rows = []
    for i, m in enumerate(metrics):
        ms = mod_query.metric_serialize(m, skip_datasource=True)
        rows.append((i, m.m_name, jsv.json_stringify(m.m_filter),
                     jsv.json_stringify(ms['breakdowns'])))
    return rows


def make_index_sink(metrics, filename, config=None):
    """Index writer for the configured format: DN_INDEX_FORMAT=dnc (the
    native columnar store, default) or sqlite (reference-compatible
    files).  Readers dispatch on file content, so either is queryable."""
    fmt = os.environ.get('DN_INDEX_FORMAT', 'dnc')
    if fmt == 'sqlite':
        return IndexSink(metrics, filename, config=config)
    from .index_dnc import DncIndexSink
    return DncIndexSink(metrics, filename, config=config)


class IndexSink(object):
    def __init__(self, metrics, filename, config=None):
        self.is_metrics = metrics
        self.is_dbfilename = filename
        self.is_dbtmpfilename = filename + '.' + str(os.getpid())
        self.is_config = dict(config or {})
        self.is_nwritten = 0

        dirname = os.path.dirname(self.is_dbtmpfilename)
        if dirname:
            os.makedirs(dirname, exist_ok=True)

        self.is_db = sqlite3.connect(self.is_dbtmpfilename)
        self.is_db.execute('pragma synchronous = off;')

        cur = self.is_db.cursor()
        cur.execute('CREATE TABLE dragnet_config(\n'
                    '    key varchar(128) primary key,\n'
                    '    value varchar(128)\n);')
        cur.execute('CREATE TABLE dragnet_metrics(\n'
                    '    id integer,\n'
                    '    label varchar(64),\n'
                    '    filter varchar(1024),\n'
                    '    params varchar(1024)\n);')

        self._insert_sql = []
        for i, m in enumerate(metrics):
            tblname = 'dragnet_index_%d' % i
            cols = []
            for b in m.m_breakdowns:
                ctype = 'integer' if 'b_aggr' in b else 'varchar(128)'
                cols.append('    %s %s' % (sqlite3_escape(b['b_name']),
                                           ctype))
            cols.append('    value integer')
            cur.execute('CREATE TABLE %s(\n%s\n);'
                        % (tblname, ',\n'.join(cols)))
            self._insert_sql.append(
                'INSERT INTO %s VALUES (%s)'
                % (tblname, ', '.join('?' for _ in cols)))

        configpairs = [('version', INDEX_VERSION)]
        for k, v in self.is_config.items():
            assert k != 'version'
            configpairs.append((k, v))
        cur.executemany('INSERT INTO dragnet_config VALUES (?, ?)',
                        configpairs)

        cur.executemany('INSERT INTO dragnet_metrics VALUES (?, ?, ?, ?)',
                        metric_catalog_rows(metrics))

    def write(self, fields, value):
        """Write one aggregated point; fields must carry __dn_metric."""
        mi = fields['__dn_metric']
        assert isinstance(mi, int) and 0 <= mi < len(self.is_metrics)
        m = self.is_metrics[mi]
        row = []
        for b in m.m_breakdowns:
            assert b['b_name'] in fields
            row.append(fields[b['b_name']])
        row.append(value)
        self.is_db.execute(self._insert_sql[mi], row)
        self.is_nwritten += 1

    def flush(self):
        self.is_db.commit()
        self.is_db.close()
        os.rename(self.is_dbtmpfilename, self.is_dbfilename)
