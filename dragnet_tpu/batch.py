"""Columnar record batches: the vectorized representation of the scan
input.

A batch columnarizes the fields a query actually needs (projection is
derived from the query plan — breakdowns, filter fields, synthetic date
sources, time field), replacing the reference's per-record object stream:

* key columns (non-aggregated breakdowns) are dictionary-encoded on their
  String(v) form (null -> "null", missing -> "undefined" — the skinner
  keying rule),
* aggregated (quantize/lquantize) columns are coerced to f64 with a
  validity mask (numeric strings coerce; anything else drops the record),
* filter columns are dictionary-encoded on their raw JS value so each
  predicate leaf is evaluated once per *unique* value with exact JS
  semantics, then broadcast to records as a table gather,
* date columns are parsed ISO-8601 -> epoch seconds with undef/baddate
  classification (stream-synthetic.js rules).

Dictionaries are global per column (append-only across batches) so codes
are stable and per-batch partial aggregates merge cheaply.
"""

import numpy as np

from . import jsvalues as jsv


class ValueDict(object):
    """Append-only dictionary over hashable JS-value identities."""

    def __init__(self):
        self.index = {}
        self.values = []

    def code(self, key, value):
        c = self.index.get(key)
        if c is None:
            c = len(self.values)
            self.index[key] = c
            self.values.append(value)
        return c


def js_value_key(v):
    """Hashable identity preserving JS comparison class."""
    if v is jsv.UNDEFINED:
        return ('u',)
    if v is None:
        return ('0',)
    if isinstance(v, bool):
        return ('b', v)
    if jsv.is_number(v):
        return ('n', jsv.as_float(v))
    if isinstance(v, str):
        return ('s', v)
    if isinstance(v, list):
        # arrays compare via ToPrimitive (join), so their string form is
        # exactly their comparison-equivalence class
        return ('a', jsv.to_string(v))
    return ('o',)  # plain objects all coerce to "[object Object]"


class StringColumn(object):
    """Dictionary-encoded String(v) column with a global dictionary."""

    def __init__(self):
        self.dict = ValueDict()

    def encode(self, values):
        index = self.dict.index
        vals = self.dict.values
        get = index.get
        to_string = jsv.to_string
        out = np.empty(len(values), dtype=np.int64)
        for i, v in enumerate(values):
            s = v if type(v) is str else to_string(v)
            c = get(s)
            if c is None:
                c = len(vals)
                index[s] = c
                vals.append(s)
            out[i] = c
        return out


class RawColumn(object):
    """Dictionary-encoded raw-JS-value column (for filter evaluation)."""

    def __init__(self):
        self.dict = ValueDict()

    def encode(self, values):
        code = self.dict.code
        return np.array([code(js_value_key(v), v) for v in values],
                        dtype=np.int64)


def numeric_column(values):
    """Coerce to f64 with validity (bucketizer input rules: numbers pass,
    numeric strings coerce, everything else is invalid)."""
    n = len(values)
    out = np.empty(n, dtype=np.float64)
    valid = np.ones(n, dtype=bool)
    for i, v in enumerate(values):
        if isinstance(v, bool):
            valid[i] = False
            out[i] = 0.0
        elif isinstance(v, (int, float)):
            out[i] = jsv.as_float(v)
        elif isinstance(v, str):
            f = jsv.to_number(v)
            if f != f:
                valid[i] = False
                out[i] = 0.0
            else:
                out[i] = f
        else:
            valid[i] = False
            out[i] = 0.0
    return out, valid


UNDEF, BADDATE = 1, 2


def date_column(values):
    """Parse date-typed fields: numbers pass through, strings via
    Date.parse -> floor(ms/1000); returns (seconds f64, errkind u8)."""
    n = len(values)
    out = np.zeros(n, dtype=np.float64)
    err = np.zeros(n, dtype=np.uint8)
    cache = {}
    for i, v in enumerate(values):
        if v is jsv.UNDEFINED:
            err[i] = UNDEF
        elif jsv.is_number(v) and not isinstance(v, bool):
            out[i] = jsv.as_float(v)
        else:
            key = v if isinstance(v, str) else None
            ms = cache.get(key, -1)
            if ms == -1:
                ms = jsv.date_parse(v) if isinstance(v, str) else None
                if isinstance(v, str):
                    cache[key] = ms
            if ms is None:
                err[i] = BADDATE
            else:
                out[i] = ms // 1000
    return out, err


def pluck_column(records, path):
    """Column extraction with fast paths for flat and two-level paths
    (full jsprim-pluck semantics preserved: direct key first, then split
    on the first dot)."""
    UD = jsv.UNDEFINED
    if '.' not in path:
        return [r.get(path, UD) if type(r) is dict else UD
                for r in records]
    head, tail = path.split('.', 1)
    if '.' not in tail:
        out = []
        append = out.append
        for r in records:
            if type(r) is not dict:  # scalar top-level JSON lines
                append(UD)
                continue
            v = r.get(path, UD)
            if v is UD:
                sub = r.get(head)
                if type(sub) is dict:
                    v = sub.get(tail, UD)
            append(v)
        return out
    pluck = jsv.pluck
    return [pluck(r, path) for r in records]
